"""Sharded async checkpointing (checkpoint.py — SURVEY §5.4's
"add sharded async checkpoint" beyond the reference's synchronous
save/load)."""
import os
import threading

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

from incubator_mxnet_tpu.checkpoint import AsyncCheckpointManager


def test_async_save_restore_roundtrip(tmp_path):
    ckpt = AsyncCheckpointManager(tmp_path, keep=5)
    tree = {"w": jnp.arange(12.0).reshape(3, 4),
            "b": jnp.ones((4,), jnp.float32),
            "step_count": onp.int64(7)}
    ckpt.save(3, tree)  # returns immediately; writer thread finishes it
    ckpt.wait()
    assert ckpt.latest_step() == 3
    back = ckpt.restore()
    onp.testing.assert_array_equal(back["w"], onp.arange(12.0).reshape(3, 4))
    onp.testing.assert_array_equal(back["b"], onp.ones(4))
    assert int(back["step_count"]) == 7


def test_sharded_arrays_one_file_per_shard(tmp_path):
    from incubator_mxnet_tpu.parallel import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P
    assert jax.device_count() >= 8
    mesh = make_mesh(dp=8)
    x = jnp.arange(64.0).reshape(8, 8)
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    assert len(xs.addressable_shards) == 8
    ckpt = AsyncCheckpointManager(tmp_path)
    ckpt.save(1, {"sharded": xs, "plain": jnp.ones((2,))}, wait=True)
    d = os.path.join(str(tmp_path), "step_00000001")
    shard_files = [f for f in os.listdir(d) if "_s" in f and
                   f.endswith(".npy")]
    assert len(shard_files) == 8  # one file per unique addressable shard
    back = ckpt.restore(1)
    onp.testing.assert_array_equal(back["sharded"], onp.asarray(x))
    # and the restored global array can be re-sharded to resume
    res = jax.device_put(jnp.asarray(back["sharded"]),
                         NamedSharding(mesh, P("dp", None)))
    onp.testing.assert_array_equal(onp.asarray(res), onp.asarray(x))


def test_replicated_array_saved_once(tmp_path):
    """A fully-replicated sharded array writes ONE copy, not one per
    device (replica_id filter)."""
    from incubator_mxnet_tpu.parallel import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh(dp=8)
    x = jnp.arange(16.0).reshape(4, 4)
    xr = jax.device_put(x, NamedSharding(mesh, P(None, None)))  # replicated
    assert len(xr.addressable_shards) == 8
    ckpt = AsyncCheckpointManager(tmp_path)
    ckpt.save(1, {"rep": xr}, wait=True)
    d = os.path.join(str(tmp_path), "step_00000001")
    data_files = [f for f in os.listdir(d) if f.endswith(".npy")]
    assert len(data_files) == 1, data_files
    onp.testing.assert_array_equal(ckpt.restore(1)["rep"], onp.asarray(x))


def test_donation_cannot_corrupt_snapshot(tmp_path):
    """save() copies on device, so a train step that donates the very
    param buffers (fuse.py default) cannot invalidate the snapshot."""
    ckpt = AsyncCheckpointManager(tmp_path)
    w = jnp.arange(8.0)

    @jax.jit
    def donating_step(w):
        return w * 2.0

    donating_step_d = jax.jit(lambda w: w * 2.0, donate_argnums=(0,))
    ckpt.save(5, {"w": w})
    w2 = donating_step_d(w)  # donates/deletes the original buffer
    ckpt.wait()
    onp.testing.assert_array_equal(ckpt.restore(5)["w"], onp.arange(8.0))
    onp.testing.assert_array_equal(onp.asarray(w2), onp.arange(8.0) * 2)


def test_retention_prunes_oldest(tmp_path):
    ckpt = AsyncCheckpointManager(tmp_path, keep=2)
    for s in (1, 2, 3, 4):
        ckpt.save(s, {"v": jnp.full((2,), float(s))}, wait=True)
    assert ckpt.all_steps() == [3, 4]
    onp.testing.assert_array_equal(ckpt.restore()["v"], [4.0, 4.0])
    onp.testing.assert_array_equal(ckpt.restore(3)["v"], [3.0, 3.0])


def test_snapshot_immune_to_later_updates(tmp_path):
    """The step-N snapshot must hold values as of save() even though
    training keeps producing new arrays (immutability contract)."""
    ckpt = AsyncCheckpointManager(tmp_path)
    w = jnp.zeros((4,))
    ckpt.save(0, {"w": w})
    for _ in range(50):
        w = w + 1.0  # new arrays; old snapshot must stay zeros
    ckpt.wait()
    onp.testing.assert_array_equal(ckpt.restore(0)["w"], onp.zeros(4))


def test_torn_checkpoint_never_published(tmp_path, monkeypatch):
    """A failed write (IO error on the writer thread) leaves no step
    directory, cleans its staging dir, and raises at wait()."""
    from incubator_mxnet_tpu import checkpoint as ckpt_mod
    ckpt = AsyncCheckpointManager(tmp_path)

    def boom(*a, **k):
        raise IOError("disk gone")

    monkeypatch.setattr(ckpt_mod.onp, "save", boom)
    ckpt.save(9, {"bad": jnp.ones((2,))})
    with pytest.raises(RuntimeError, match="checkpoint write failed"):
        ckpt.wait()
    assert ckpt.all_steps() == []
    assert not os.path.exists(os.path.join(str(tmp_path), "step_00000009"))
    assert not os.path.exists(os.path.join(str(tmp_path),
                                           "step_00000009.tmp"))


def test_restore_missing_is_explicit(tmp_path):
    ckpt = AsyncCheckpointManager(tmp_path)
    with pytest.raises(FileNotFoundError):
        ckpt.restore()


def test_async_checkpoint_handler_in_estimator(tmp_path):
    """AsyncCheckpointHandler snapshots during estimator.fit without
    blocking and restores into a fresh net."""
    from incubator_mxnet_tpu import nd, gluon
    from incubator_mxnet_tpu.gluon.contrib.estimator import (
        Estimator, AsyncCheckpointHandler)
    from incubator_mxnet_tpu.gluon import nn, loss as gloss
    net = nn.Dense(3, in_units=5)
    net.initialize()
    est = Estimator(net, gloss.L2Loss(),
                    trainer=gluon.Trainer(net.collect_params(), "sgd",
                                          {"learning_rate": 0.05}))
    X = nd.random.uniform(shape=(32, 5))
    Y = nd.random.uniform(shape=(32, 3))
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    loader = DataLoader(ArrayDataset(X, Y), batch_size=8)
    handler = AsyncCheckpointHandler(str(tmp_path), batch_period=2)
    est.fit(loader, epochs=2, event_handlers=[handler])
    steps = handler.manager.all_steps()
    assert steps, "no async snapshots were taken"
    net2 = nn.Dense(3, in_units=5)
    net2.initialize()
    net2(nd.zeros((1, 5)))
    handler.restore_into(net2, steps[-1])
    x = nd.random.uniform(shape=(2, 5))
    onp.testing.assert_allclose(net2(x).asnumpy(), net(x).asnumpy(),
                                rtol=1e-5)


def test_bfloat16_roundtrip(tmp_path):
    """bf16 params — the TPU common case — survive save/restore for
    both sharded and unsharded leaves (numpy writes exotic dtypes as
    raw void; restore views them back)."""
    from incubator_mxnet_tpu.parallel import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh(dp=8)
    x = jnp.arange(64.0, dtype=jnp.bfloat16).reshape(8, 8)
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    ckpt = AsyncCheckpointManager(tmp_path)
    ckpt.save(1, {"sharded": xs, "plain": jnp.full((3,), 2.5,
                                                   jnp.bfloat16)},
              wait=True)
    back = ckpt.restore(1)
    assert str(back["sharded"].dtype) == "bfloat16"
    onp.testing.assert_array_equal(
        back["sharded"].astype(onp.float32),
        onp.arange(64.0, dtype=onp.float32).reshape(8, 8))
    assert str(back["plain"].dtype) == "bfloat16"
    onp.testing.assert_array_equal(back["plain"].astype(onp.float32),
                                   onp.full((3,), 2.5))


def test_incomplete_multiprocess_checkpoint_is_loud(tmp_path):
    """Missing shards (a writer process died) raise instead of
    zero-filling the resumed model."""
    import json
    from incubator_mxnet_tpu.parallel import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh(dp=8)
    xs = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                        NamedSharding(mesh, P("dp", None)))
    ckpt = AsyncCheckpointManager(tmp_path)
    ckpt.save(1, {"w": xs}, wait=True)
    d = os.path.join(str(tmp_path), "step_00000001")
    with open(os.path.join(d, "index.json")) as f:
        idx = json.load(f)
    idx["params"]["w"]["shards"] = idx["params"]["w"]["shards"][:4]
    with open(os.path.join(d, "index.json"), "w") as f:
        json.dump(idx, f)
    with pytest.raises(RuntimeError, match="incomplete"):
        ckpt.restore(1)


def test_host_numpy_leaf_snapshot_isolated(tmp_path):
    """In-place mutation of a host numpy leaf after save() must not
    leak into the snapshot; plain python scalars are accepted."""
    ckpt = AsyncCheckpointManager(tmp_path)
    ema = onp.ones(4, onp.float32)
    ckpt.save(2, {"ema": ema, "epoch": 3})
    ema *= 100.0
    ckpt.wait()
    back = ckpt.restore(2)
    onp.testing.assert_array_equal(back["ema"], onp.ones(4))
    assert int(back["epoch"]) == 3
