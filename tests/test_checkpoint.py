"""Sharded async checkpointing (checkpoint.py — SURVEY §5.4's
"add sharded async checkpoint" beyond the reference's synchronous
save/load)."""
import os
import threading

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

from incubator_mxnet_tpu.checkpoint import AsyncCheckpointManager


def test_async_save_restore_roundtrip(tmp_path):
    ckpt = AsyncCheckpointManager(tmp_path, keep=5)
    tree = {"w": jnp.arange(12.0).reshape(3, 4),
            "b": jnp.ones((4,), jnp.float32),
            "step_count": onp.int64(7)}
    ckpt.save(3, tree)  # returns immediately; writer thread finishes it
    ckpt.wait()
    assert ckpt.latest_step() == 3
    back = ckpt.restore()
    onp.testing.assert_array_equal(back["w"], onp.arange(12.0).reshape(3, 4))
    onp.testing.assert_array_equal(back["b"], onp.ones(4))
    assert int(back["step_count"]) == 7


def test_sharded_arrays_one_file_per_shard(tmp_path):
    from incubator_mxnet_tpu.parallel import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P
    assert jax.device_count() >= 8
    mesh = make_mesh(dp=8)
    x = jnp.arange(64.0).reshape(8, 8)
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    assert len(xs.addressable_shards) == 8
    ckpt = AsyncCheckpointManager(tmp_path)
    ckpt.save(1, {"sharded": xs, "plain": jnp.ones((2,))}, wait=True)
    d = os.path.join(str(tmp_path), "step_00000001")
    shard_files = [f for f in os.listdir(d) if "_s" in f and
                   f.endswith(".npy")]
    assert len(shard_files) == 8  # one file per unique addressable shard
    back = ckpt.restore(1)
    onp.testing.assert_array_equal(back["sharded"], onp.asarray(x))
    # and the restored global array can be re-sharded to resume
    res = jax.device_put(jnp.asarray(back["sharded"]),
                         NamedSharding(mesh, P("dp", None)))
    onp.testing.assert_array_equal(onp.asarray(res), onp.asarray(x))


def test_replicated_array_saved_once(tmp_path):
    """A fully-replicated sharded array writes ONE copy, not one per
    device (replica_id filter)."""
    from incubator_mxnet_tpu.parallel import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh(dp=8)
    x = jnp.arange(16.0).reshape(4, 4)
    xr = jax.device_put(x, NamedSharding(mesh, P(None, None)))  # replicated
    assert len(xr.addressable_shards) == 8
    ckpt = AsyncCheckpointManager(tmp_path)
    ckpt.save(1, {"rep": xr}, wait=True)
    d = os.path.join(str(tmp_path), "step_00000001")
    data_files = [f for f in os.listdir(d) if f.endswith(".npy")]
    assert len(data_files) == 1, data_files
    onp.testing.assert_array_equal(ckpt.restore(1)["rep"], onp.asarray(x))


def test_donation_cannot_corrupt_snapshot(tmp_path):
    """save() copies on device, so a train step that donates the very
    param buffers (fuse.py default) cannot invalidate the snapshot."""
    ckpt = AsyncCheckpointManager(tmp_path)
    w = jnp.arange(8.0)

    @jax.jit
    def donating_step(w):
        return w * 2.0

    donating_step_d = jax.jit(lambda w: w * 2.0, donate_argnums=(0,))
    ckpt.save(5, {"w": w})
    w2 = donating_step_d(w)  # donates/deletes the original buffer
    ckpt.wait()
    onp.testing.assert_array_equal(ckpt.restore(5)["w"], onp.arange(8.0))
    onp.testing.assert_array_equal(onp.asarray(w2), onp.arange(8.0) * 2)


def test_retention_prunes_oldest(tmp_path):
    ckpt = AsyncCheckpointManager(tmp_path, keep=2)
    for s in (1, 2, 3, 4):
        ckpt.save(s, {"v": jnp.full((2,), float(s))}, wait=True)
    assert ckpt.all_steps() == [3, 4]
    onp.testing.assert_array_equal(ckpt.restore()["v"], [4.0, 4.0])
    onp.testing.assert_array_equal(ckpt.restore(3)["v"], [3.0, 3.0])


def test_snapshot_immune_to_later_updates(tmp_path):
    """The step-N snapshot must hold values as of save() even though
    training keeps producing new arrays (immutability contract)."""
    ckpt = AsyncCheckpointManager(tmp_path)
    w = jnp.zeros((4,))
    ckpt.save(0, {"w": w})
    for _ in range(50):
        w = w + 1.0  # new arrays; old snapshot must stay zeros
    ckpt.wait()
    onp.testing.assert_array_equal(ckpt.restore(0)["w"], onp.zeros(4))


def test_torn_checkpoint_never_published(tmp_path):
    """A failed write leaves no step directory and raises at wait()."""
    ckpt = AsyncCheckpointManager(tmp_path)

    class Boom:
        shape = (2,)
        dtype = onp.float32

        def __array__(self, dtype=None, copy=None):
            raise IOError("disk gone")

    ckpt.save(9, {"bad": Boom()})
    with pytest.raises(RuntimeError, match="checkpoint write failed"):
        ckpt.wait()
    assert ckpt.all_steps() == []
    assert not os.path.exists(os.path.join(str(tmp_path), "step_00000009"))


def test_restore_missing_is_explicit(tmp_path):
    ckpt = AsyncCheckpointManager(tmp_path)
    with pytest.raises(FileNotFoundError):
        ckpt.restore()
