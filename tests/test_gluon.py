"""Gluon layer/block tests (reference tests/python/unittest/test_gluon.py)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, nd
from incubator_mxnet_tpu.gluon import nn, loss as gloss, metric as gmetric
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def _init(net):
    net.initialize()
    return net


def test_dense_forward_shape_and_params():
    net = _init(nn.Dense(4, in_units=3))
    x = nd.random.uniform(shape=(2, 3))
    y = net(x)
    assert y.shape == (2, 4)
    params = net.collect_params()
    assert any("weight" in k for k in params.keys())
    w = net.weight.data()
    assert_almost_equal(y, x.asnumpy() @ w.asnumpy().T
                        + net.bias.data().asnumpy(), rtol=1e-5)


def test_dense_deferred_shape_init():
    net = nn.Dense(4)  # in_units inferred on first call
    net.initialize()
    y = net(nd.ones((5, 7)))
    assert y.shape == (5, 4)
    assert net.weight.shape == (4, 7)


def test_sequential_and_hybrid_sequential():
    for cls in (nn.Sequential, nn.HybridSequential):
        net = cls()
        net.add(nn.Dense(8, activation="relu"))
        net.add(nn.Dense(3))
        net.initialize()
        out = net(nd.ones((2, 5)))
        assert out.shape == (2, 3)
        assert len(net) == 2
        assert isinstance(net[0], nn.Dense)


def test_hybridize_consistency():
    """Eager vs hybridized (traced+jit) outputs must match —
    the CachedOp correctness contract (reference block.py:1044)."""
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(8), nn.LayerNorm(),
            nn.Dense(2))
    net.initialize()
    x = nd.random.uniform(shape=(4, 10))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert_almost_equal(eager, hybrid, rtol=1e-5, atol=1e-6)
    # second call hits the cache
    assert_almost_equal(net(x).asnumpy(), hybrid, rtol=1e-6)


def test_hybridize_static_alloc_grad():
    net = nn.HybridSequential()
    net.add(nn.Dense(6, activation="tanh"), nn.Dense(1))
    net.initialize()
    x = nd.random.uniform(shape=(3, 4))

    def loss_of(net):
        with autograd.record():
            y = net(x)
            l = (y * y).sum()
        l.backward()
        return {k: p.grad().asnumpy() for k, p in
                net.collect_params().items() if p.grad_req != "null"}

    eager_grads = loss_of(net)
    net.hybridize(static_alloc=True)
    hybrid_grads = loss_of(net)
    for k in eager_grads:
        assert_almost_equal(eager_grads[k], hybrid_grads[k], rtol=1e-4,
                            atol=1e-5)


def test_conv2d_block():
    net = _init(nn.Conv2D(4, kernel_size=3, padding=1, in_channels=2))
    y = net(nd.ones((1, 2, 8, 8)))
    assert y.shape == (1, 4, 8, 8)
    net2 = _init(nn.Conv2D(4, kernel_size=3, strides=2))
    assert net2(nd.ones((1, 2, 9, 9))).shape == (1, 4, 4, 4)


def test_conv_transpose_block():
    net = _init(nn.Conv2DTranspose(3, kernel_size=2, strides=2, in_channels=2))
    y = net(nd.ones((1, 2, 4, 4)))
    assert y.shape == (1, 3, 8, 8)


def test_pool_blocks():
    x = nd.random.uniform(shape=(2, 3, 8, 8))
    assert nn.MaxPool2D(2)(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(2)(x).shape == (2, 3, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)
    assert nn.GlobalMaxPool2D()(x).shape == (2, 3, 1, 1)


def test_batchnorm_running_stats_update():
    net = _init(nn.BatchNorm(in_channels=3))
    x = nd.random.uniform(1, 3, shape=(8, 3, 4, 4))
    before = net.running_mean.data().asnumpy().copy()
    with autograd.record():
        net(x)
    after = net.running_mean.data().asnumpy()
    assert not onp.allclose(before, after)  # stats moved toward batch mean
    # inference uses running stats: output differs from training pass
    out_inf = net(x)
    assert out_inf.shape == x.shape


def test_embedding_block():
    net = _init(nn.Embedding(10, 4))
    y = net(nd.array([[1, 2], [3, 4]], dtype="int32"))
    assert y.shape == (2, 2, 4)


def test_dropout_block_train_vs_inference():
    net = _init(nn.Dropout(0.5))
    x = nd.ones((100,))
    assert_almost_equal(net(x), x)  # inference = identity
    with autograd.record():
        y = net(x)
    assert (y.asnumpy() == 0).any()


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(5, in_units=3), nn.Dense(2, in_units=5))
    net.initialize()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(5, in_units=3), nn.Dense(2, in_units=5))
    net2.load_parameters(f)
    x = nd.random.uniform(shape=(2, 3))
    assert_almost_equal(net(x), net2(x).asnumpy())


def test_export_and_symbolblock_import(tmp_path):
    from incubator_mxnet_tpu.gluon import SymbolBlock
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3, activation="relu"), nn.Dense(2, in_units=4))
    net.initialize()
    net.hybridize()
    x = nd.random.uniform(shape=(2, 3))
    ref = net(x).asnumpy()
    prefix = str(tmp_path / "model")
    net.export(prefix, epoch=0, example_inputs=(x,))
    net2 = SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                               prefix + "-0000.params")
    assert_almost_equal(net2(x), ref, rtol=1e-5)


def test_parameter_grad_req_and_shared():
    from incubator_mxnet_tpu.gluon import Parameter
    p = Parameter("w", shape=(2, 2))
    p.initialize()
    p.grad_req = "null"
    shared = _init(nn.Dense(3, in_units=3))
    tied = nn.Dense(3, in_units=3, params=shared.collect_params())
    x = nd.ones((1, 3))
    assert_almost_equal(shared(x), tied(x).asnumpy())


def test_losses_match_formulas():
    pred = nd.array([[1.0, 2.0], [0.5, 0.1]])
    label = nd.array([[0.9, 2.2], [0.0, 0.0]])
    l2 = gloss.L2Loss()(pred, label).asnumpy()
    assert_almost_equal(l2, ((pred.asnumpy() - label.asnumpy()) ** 2)
                        .mean(axis=1) / 2, rtol=1e-5)
    l1 = gloss.L1Loss()(pred, label).asnumpy()
    assert_almost_equal(l1, onp.abs(pred.asnumpy() - label.asnumpy())
                        .mean(axis=1), rtol=1e-5)


def test_softmax_ce_loss():
    pred = nd.array([[5.0, 1.0, 1.0], [1.0, 5.0, 1.0]])
    label = nd.array([0, 1])
    l = gloss.SoftmaxCrossEntropyLoss()(pred, label)
    x = pred.asnumpy()
    lse = onp.log(onp.exp(x).sum(1))
    expect = lse - x[onp.arange(2), [0, 1]]
    assert_almost_equal(l, expect, rtol=1e-5)


def test_sigmoid_bce_and_hinge():
    pred = nd.array([[0.5], [-0.5]])
    label = nd.array([[1.0], [0.0]])
    bce = gloss.SigmoidBinaryCrossEntropyLoss()(pred, label).asnumpy()
    p = 1 / (1 + onp.exp(-pred.asnumpy()))
    expect = -(label.asnumpy() * onp.log(p)
               + (1 - label.asnumpy()) * onp.log(1 - p)).mean(1)
    assert_almost_equal(bce, expect, rtol=1e-4)
    h = gloss.HingeLoss()(nd.array([[0.4]]), nd.array([[1.0]])).asnumpy()
    assert h == pytest.approx([0.6], rel=1e-5)


def test_metrics():
    acc = gmetric.Accuracy()
    acc.update(nd.array([0, 1, 1]), nd.array([[0.9, 0.1], [0.2, 0.8],
                                              [0.7, 0.3]]))
    name, val = acc.get()
    assert val == pytest.approx(2 / 3)
    mse = gmetric.MSE()
    mse.update(nd.array([1.0, 2.0]), nd.array([1.5, 2.0]))
    assert mse.get()[1] == pytest.approx(0.125)
    topk = gmetric.TopKAccuracy(top_k=2)
    topk.update(nd.array([2]), nd.array([[0.1, 0.5, 0.4]]))
    assert topk.get()[1] == 1.0
    comp = gmetric.CompositeEvalMetric()
    comp.add(gmetric.Accuracy())
    comp.update(nd.array([1]), nd.array([[0.1, 0.9]]))
    names, vals = comp.get()
    assert vals[0] == 1.0


def test_block_hooks_and_apply():
    calls = []
    net = _init(nn.Dense(2, in_units=2))
    h = net.register_forward_hook(lambda blk, inp, out: calls.append("post"))
    net.register_forward_pre_hook(lambda blk, inp: calls.append("pre"))
    net(nd.ones((1, 2)))
    assert calls == ["pre", "post"]
    h.detach()
    seen = []
    net.apply(lambda b: seen.append(type(b).__name__))
    assert "Dense" in seen


def test_lambda_blocks():
    lam = nn.HybridLambda(lambda x: x * 2)
    assert lam(nd.ones((2,))).asnumpy().tolist() == [2, 2]
    lam2 = nn.Lambda(lambda x: x + 1)
    assert lam2(nd.ones((2,))).asnumpy().tolist() == [2, 2]


def test_activation_blocks():
    x = nd.array([-1.0, 1.0])
    assert nn.Activation("relu")(x).asnumpy().tolist() == [0, 1]
    assert nn.LeakyReLU(0.1)(x).asnumpy()[0] == pytest.approx(-0.1)
    for blk in (nn.ELU(), nn.SELU(), nn.GELU(), nn.SiLU(), nn.PReLU(),
                nn.Swish()):
        if hasattr(blk, "initialize"):
            blk.initialize()
        assert blk(x).shape == (2,)


def test_norm_blocks():
    x = nd.random.uniform(shape=(2, 4, 3, 3))
    for blk in (nn.LayerNorm(), nn.GroupNorm(num_groups=2),
                nn.InstanceNorm()):
        blk.initialize()
        assert blk(x).shape == x.shape


def test_trainer_sgd_step_decreases_loss():
    from incubator_mxnet_tpu.gluon import Trainer
    net = _init(nn.Dense(1, in_units=2))
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.3})
    x = nd.random.uniform(shape=(16, 2))
    target = (x.asnumpy() @ onp.array([[2.0], [-1.0]])).astype("float32")
    losses = []
    for _ in range(60):
        with autograd.record():
            l = gloss.L2Loss()(net(x), nd.array(target))
            l = l.mean()
        l.backward()
        trainer.step(1)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0] * 0.2


def test_trainer_learning_rate_and_states(tmp_path):
    from incubator_mxnet_tpu.gluon import Trainer
    net = _init(nn.Dense(1, in_units=1))
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    assert tr.learning_rate == pytest.approx(0.01)
    tr.set_learning_rate(0.5)
    assert tr.learning_rate == pytest.approx(0.5)
    with autograd.record():
        l = net(nd.ones((1, 1))).sum()
    l.backward()
    tr.step(1)
    f = str(tmp_path / "trainer.states")
    tr.save_states(f)
    tr.load_states(f)


def test_summary_runs(capsys):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    net.summary(nd.ones((1, 3)))
    assert "Total params" in capsys.readouterr().out


def test_metrics_tail():
    """Round-3 metric additions (reference gluon/metric.py:815-1300)."""
    import numpy as onp
    from incubator_mxnet_tpu.gluon import metric as M

    ba = M.BinaryAccuracy(threshold=0.6)
    ba.update([onp.array([0., 1., 0.])], [onp.array([0.7, 1., 0.55])])
    assert abs(ba.get()[1] - 2.0 / 3.0) < 1e-9  # reference docstring example

    mpd = M.MeanPairwiseDistance()
    mpd.update([onp.array([[1., 0.], [4., 2.]])],
               [onp.array([[1., 2.], [3., 4.]])])
    assert abs(mpd.get()[1] - (2 + onp.sqrt(5.0)) / 2) < 1e-9

    cs = M.MeanCosineSimilarity()
    cs.update([onp.array([[1., 0.], [0., 1.]])],
              [onp.array([[1., 0.], [1., 0.]])])
    assert abs(cs.get()[1] - 0.5) < 1e-9

    fb = M.Fbeta(beta=1.0, threshold=0.5)
    f1 = M.F1(threshold=0.5)
    y = [onp.array([1, 0, 1, 1])]
    p = [onp.array([0.9, 0.8, 0.2, 0.7])]
    fb.update(y, p); f1.update(y, p)
    assert abs(fb.get()[1] - f1.get()[1]) < 1e-12  # beta=1 == F1

    # PCC on perfect 3-class predictions == 1.0
    pcc = M.PCC()
    labels = [onp.array([0, 1, 2, 1, 0])]
    preds = [onp.eye(3)[labels[0]]]
    pcc.update(labels, preds)
    assert abs(pcc.get()[1] - 1.0) < 1e-9

    assert M.create("pcc").name == "pcc"
    assert isinstance(M.Torch(), M.Loss) and isinstance(M.Caffe(), M.Loss)


def test_batch_norm_relu_layer():
    """BatchNormReLU == BatchNorm then relu (reference nn BatchNormReLU)."""
    import numpy as onp
    from incubator_mxnet_tpu import autograd
    bnr = nn.BatchNormReLU(in_channels=3)
    bn = nn.BatchNorm(in_channels=3)
    bnr.initialize()
    bn.initialize()
    x = nd.random.uniform(-2, 2, shape=(2, 3, 4, 4))
    out = bnr(x)
    ref = nd.relu(bn(x))
    onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-5)
    assert float(out.min().asnumpy()) >= 0.0
    # training mode updates moving stats like plain BN
    with autograd.record():
        y = bnr(x)
    y.backward()
    assert float(nd.sum(nd.abs(bnr.running_mean.data())).asnumpy()) > 0


def test_modifier_cell_hierarchy():
    """ModifierCell base + hybrid aliases (reference rnn_cell.py)."""
    from incubator_mxnet_tpu.gluon import rnn
    assert issubclass(rnn.ResidualCell, rnn.ModifierCell)
    assert issubclass(rnn.ZoneoutCell, rnn.ModifierCell)
    assert rnn.HybridRecurrentCell is rnn.RecurrentCell
    assert rnn.HybridSequentialRNNCell is rnn.SequentialRNNCell
    base = rnn.LSTMCell(8, input_size=4)
    res = rnn.ResidualCell(base)
    assert res.state_info(2) == base.state_info(2)


def test_contrib_nn_layers():
    """gluon.contrib.nn (reference contrib/nn/basic_layers.py):
    Concurrent branches, PixelShuffle value parity, SyncBatchNorm."""
    import numpy as onp
    from incubator_mxnet_tpu.gluon.contrib import nn as gcn
    # Concurrent: same input to every branch, concat on axis
    cc = gcn.HybridConcurrent(axis=1)
    cc.add(nn.Dense(3, in_units=4), nn.Dense(5, in_units=4))
    cc.initialize()
    x = nd.random.uniform(shape=(2, 4))
    out = cc(x)
    assert out.shape == (2, 8)
    onp.testing.assert_allclose(out.asnumpy()[:, :3],
                                cc[0](x).asnumpy(), rtol=1e-6)
    # PixelShuffle2D value parity vs a direct numpy rearrangement
    f1, f2, C, H, W = 2, 3, 2, 3, 5
    src = onp.arange(1 * f1 * f2 * C * H * W, dtype=onp.float32) \
        .reshape(1, f1 * f2 * C, H, W)
    got = gcn.PixelShuffle2D((f1, f2))(nd.array(src)).asnumpy()
    want = src.reshape(1, C, f1, f2, H, W).transpose(0, 1, 4, 2, 5, 3) \
        .reshape(1, C, H * f1, W * f2)
    onp.testing.assert_array_equal(got, want)
    # gradients flow through the shuffle (tape-recorded rearrangement)
    from incubator_mxnet_tpu import autograd
    xs = nd.array(src)
    xs.attach_grad()
    with autograd.record():
        y = gcn.PixelShuffle2D((f1, f2))(xs)
        loss = nd.sum(y * y)
    loss.backward()
    onp.testing.assert_allclose(xs.grad.asnumpy(), 2 * src, rtol=1e-6)
    # SyncBatchNorm layer behaves like BatchNorm in-process
    sbn = gcn.SyncBatchNorm(in_channels=3, num_devices=8)
    bn = nn.BatchNorm(in_channels=3)
    sbn.initialize()
    bn.initialize()
    xi = nd.random.uniform(shape=(2, 3, 4, 4))
    onp.testing.assert_allclose(sbn(xi).asnumpy(), bn(xi).asnumpy(),
                                rtol=1e-5)
    # SparseEmbedding is an Embedding with the sparse-grad contract
    emb = gcn.SparseEmbedding(10, 4)
    emb.initialize()
    idx = nd.array(onp.array([1, 3], onp.int32))
    assert emb(idx).shape == (2, 4)


def test_poisson_nll_and_sdml_losses():
    """PoissonNLLLoss + SDMLLoss (reference loss.py:800,935) +
    FilterSampler (data/sampler.py)."""
    import numpy as onp
    from incubator_mxnet_tpu import autograd
    rng = onp.random.RandomState(0)
    # Poisson: from_logits formula exp(pred) - target*pred
    pred = nd.array(rng.randn(4, 3).astype("f"))
    target = nd.array(rng.poisson(2.0, (4, 3)).astype("f"))
    loss = gloss.PoissonNLLLoss(from_logits=True)(pred, target)
    expect = (onp.exp(pred.asnumpy()) - target.asnumpy() * pred.asnumpy()).mean()
    onp.testing.assert_allclose(float(loss.asnumpy()), expect, rtol=1e-5)
    # non-logits + compute_full adds Stirling only for target > 1
    loss2 = gloss.PoissonNLLLoss(from_logits=False, compute_full=True)(
        nd.abs(pred) + 0.5, target)
    assert onp.isfinite(float(loss2.asnumpy()))
    # SDML: aligned batches -> the loss decreases as x2 approaches x1
    x1 = nd.array(rng.rand(4, 8).astype("f"))
    far = nd.array(rng.rand(4, 8).astype("f"))
    sdml = gloss.SDMLLoss(smoothing_parameter=0.1)
    l_far = float(sdml(x1, far).mean().asnumpy())
    l_near = float(sdml(x1, x1 * 1.02).mean().asnumpy())
    assert l_near < l_far
    # and is differentiable
    x2 = nd.array(rng.rand(4, 8).astype("f"))
    x2.attach_grad()
    with autograd.record():
        out = sdml(x1, x2).mean()
    out.backward()
    assert float(nd.sum(nd.abs(x2.grad)).asnumpy()) > 0
    # FilterSampler keeps matching indices only
    from incubator_mxnet_tpu.gluon.data import FilterSampler, ArrayDataset
    ds = ArrayDataset(nd.array(onp.arange(10).astype("f")))
    samp = FilterSampler(lambda v: float(v.asnumpy()) % 2 == 0, ds)
    assert list(samp) == [0, 2, 4, 6, 8] and len(samp) == 5


def test_transforms_tail():
    """Color jitter / crop / rotate transform family (reference
    gluon/data/vision/transforms.py)."""
    import numpy as onp
    from incubator_mxnet_tpu.gluon.data.vision import transforms as T
    onp.random.seed(0)
    img = nd.array((onp.random.rand(20, 24, 3) * 255).astype(onp.uint8))
    # shape-preserving color ops stay uint8 in [0, 255]
    for t in (T.RandomContrast(0.5), T.RandomSaturation(0.5),
              T.RandomHue(0.3), T.RandomLighting(0.1),
              T.RandomColorJitter(0.3, 0.3, 0.3, 0.1), T.RandomGray(1.0)):
        out = t(img)
        assert out.shape == img.shape, type(t).__name__
        a = out.asnumpy()
        assert a.dtype == onp.uint8 and a.min() >= 0 and a.max() <= 255
    # RandomGray(p=1): all three channels equal
    g = T.RandomGray(1.0)(img).asnumpy()
    onp.testing.assert_array_equal(g[..., 0], g[..., 1])
    # crops
    assert T.RandomCrop(8)(img).shape == (8, 8, 3)
    assert T.RandomCrop(8, pad=4)(img).shape == (8, 8, 3)
    # smaller-than-target sources upscale to exactly the target size
    assert T.RandomCrop(32)(img).shape == (32, 32, 3)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="HWC"):
        T.RandomCrop(8)(nd.zeros(shape=(2, 20, 24, 3)))
    with _pytest.raises(NotImplementedError):
        T.Rotate(30.0, zoom_out=True)
    cr = T.CropResize(2, 3, 10, 12)(img)
    assert cr.shape == (12, 10, 3)
    cr2 = T.CropResize(2, 3, 10, 12, size=6)(img)
    assert cr2.shape == (6, 6, 3)
    # rotation: 0 degrees is identity; 90-degree content check on floats
    sq = nd.array(onp.random.rand(9, 9, 1).astype("f"))
    onp.testing.assert_allclose(T.Rotate(0.0)(sq).asnumpy(), sq.asnumpy(),
                                atol=1e-5)
    r90 = T.Rotate(90.0)(sq).asnumpy()[..., 0]
    onp.testing.assert_allclose(r90, onp.rot90(sq.asnumpy()[..., 0], -1),
                                atol=1e-4)
    rr = T.RandomRotation((-30, 30))(sq)
    assert rr.shape == sq.shape
    # RandomApply honors p
    marker = []
    class Tag:
        def __call__(self, x):
            marker.append(1)
            return x
    T.RandomApply(Tag(), p=0.0)(img)
    assert not marker
    T.RandomApply(Tag(), p=1.0)(img)
    assert marker
    # hybrid aliases
    assert T.HybridCompose is T.Compose
    assert T.HybridRandomApply is T.RandomApply


def test_deformable_convolution_layers():
    """contrib.cnn deformable conv v1/v2 (reference
    gluon/contrib/cnn/conv_layers.py + deformable_convolution.cc): with
    zero-initialized offsets BOTH start as the plain conv (the v2 mask
    is sigmoid(0)*2 = 1, conv_layers.py:383); both train end-to-end."""
    from incubator_mxnet_tpu.gluon.contrib.cnn import (
        DeformableConvolution, ModulatedDeformableConvolution)
    x = nd.random.uniform(shape=(2, 4, 8, 8))
    for cls, scale in ((DeformableConvolution, 1.0),
                       (ModulatedDeformableConvolution, 1.0)):
        net = cls(8, kernel_size=3, padding=1)
        net.initialize(ctx=mx.cpu())
        y = net(x)
        assert y.shape == (2, 8, 8, 8)
        ref = nd.Convolution(x, net.weight.data(), kernel=(3, 3),
                             pad=(1, 1), num_filter=8, no_bias=True) * scale \
            + net.bias.data().reshape((1, -1, 1, 1))
        assert_almost_equal(y, ref.asnumpy(), rtol=1e-5, atol=1e-5)
        from incubator_mxnet_tpu import gluon
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        with autograd.record():
            loss = nd.sum(net(x))
        loss.backward()
        tr.step(1)
        assert float(onp.abs(
            net.offset.weight.grad().asnumpy()).max()) > 0


def test_deformable_convolution_shifts_sampling():
    """An integer (+1,+1) offset samples the shifted input (interior
    pixels; borders differ by zero-pad sampling)."""
    R = onp.random.RandomState(0)
    x = R.rand(1, 2, 8, 8).astype("f")
    w = R.randn(3, 2, 3, 3).astype("f") * 0.1
    off = onp.ones((1, 18, 8, 8), "f")
    d = nd.DeformableConvolution(nd.array(x), nd.array(off), nd.array(w),
                                 kernel=(3, 3), pad=(1, 1), num_filter=3,
                                 no_bias=True)
    xs = onp.zeros_like(x)
    xs[:, :, :-1, :-1] = x[:, :, 1:, 1:]
    ref = nd.Convolution(nd.array(xs), nd.array(w), kernel=(3, 3),
                         pad=(1, 1), num_filter=3, no_bias=True)
    assert_almost_equal(d.asnumpy()[:, :, 1:-2, 1:-2],
                        ref.asnumpy()[:, :, 1:-2, 1:-2], rtol=1e-5,
                        atol=1e-5)


def test_interval_sampler():
    from incubator_mxnet_tpu.gluon.contrib.data import IntervalSampler
    assert list(IntervalSampler(13, interval=3)) == \
        [0, 3, 6, 9, 12, 1, 4, 7, 10, 2, 5, 8, 11]
    assert list(IntervalSampler(13, interval=3, rollover=False)) == \
        [0, 3, 6, 9, 12]


def test_wikitext_local_file(tmp_path):
    from incubator_mxnet_tpu.gluon.contrib.data import WikiText2
    text = "the quick brown fox\njumps over the lazy dog\n" * 5
    (tmp_path / "wiki.train.tokens").write_text(text)
    ds = WikiText2(str(tmp_path), segment="train", seq_len=5)
    assert len(ds) > 0
    d, l = ds[0]
    assert d.shape == (5,) and l.shape == (5,)
    # label is data shifted by exactly one token
    flat_d = ds._data.ravel()
    flat_l = ds._label.ravel()
    assert (flat_l[:-1] == flat_d[1:]).all()
    with pytest.raises(OSError, match="not found"):
        WikiText2(str(tmp_path), segment="test")
