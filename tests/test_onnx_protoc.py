"""External validation of the ONNX exporter's wire format (VERDICT r2
weak #9: the exporter/importer shared one hand-rolled codec, so round
trips were self-referential).  protoc is an INDEPENDENT protobuf
implementation: decoding our bytes against the public onnx.proto subset
proves field numbers, wire types, and message nesting are real ONNX."""
import os
import shutil
import subprocess

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, sym

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROTO_DIR = os.path.join(REPO, "incubator_mxnet_tpu", "contrib", "onnx",
                         "schema")


@pytest.fixture(scope="module")
def protoc():
    path = shutil.which("protoc")
    if path is None:
        pytest.skip("protoc not available")
    return path


def _export_model(tmp_path):
    from incubator_mxnet_tpu.contrib.onnx import export_model
    data = sym.var("data")
    out = sym.FullyConnected(data, num_hidden=4, name="fc1")
    out = sym.softmax(out, name="sm")
    params = {"fc1_weight": nd.ones((4, 3)), "fc1_bias": nd.zeros((4,))}
    path = str(tmp_path / "m.onnx")
    export_model(out, params, (2, 3), path)
    return path


def test_protoc_decodes_exported_model(tmp_path, protoc):
    path = _export_model(tmp_path)
    with open(path, "rb") as f:
        raw = f.read()
    proc = subprocess.run(
        [protoc, f"-I{PROTO_DIR}", "--decode=onnx.ModelProto",
         "onnx_subset.proto"],
        input=raw, capture_output=True, timeout=60)
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    text = proc.stdout.decode()
    # structure decoded by an independent parser must show our content
    assert 'producer_name: "incubator_mxnet_tpu"' in text
    assert 'op_type: "Gemm"' in text or 'op_type: "MatMul"' in text
    assert 'op_type: "Softmax"' in text
    assert "initializer" in text and 'name: "fc1_weight"' in text
    assert "opset_import" in text
    assert proc.stderr.strip() == b"", proc.stderr.decode()


def test_protoc_reencodes_identically(tmp_path, protoc):
    # decode -> re-encode through protoc: byte-identical output proves
    # the file contains no unknown/malformed fields at all
    path = _export_model(tmp_path)
    with open(path, "rb") as f:
        raw = f.read()
    dec = subprocess.run(
        [protoc, f"-I{PROTO_DIR}", "--decode=onnx.ModelProto",
         "onnx_subset.proto"],
        input=raw, capture_output=True, timeout=60)
    assert dec.returncode == 0
    enc = subprocess.run(
        [protoc, f"-I{PROTO_DIR}", "--encode=onnx.ModelProto",
         "onnx_subset.proto"],
        input=dec.stdout, capture_output=True, timeout=60)
    assert enc.returncode == 0, enc.stderr.decode()[-500:]
    # field order is free in protobuf, so compare SEMANTICS: the decode
    # of protoc's canonical re-encoding must equal the original decode
    dec2 = subprocess.run(
        [protoc, f"-I{PROTO_DIR}", "--decode=onnx.ModelProto",
         "onnx_subset.proto"],
        input=enc.stdout, capture_output=True, timeout=60)
    assert dec2.returncode == 0
    assert dec2.stdout == dec.stdout, "re-encode lost information"
    # and the sizes must agree (no unknown fields silently dropped)
    assert abs(len(enc.stdout) - len(raw)) <= 16, (len(enc.stdout),
                                                   len(raw))
