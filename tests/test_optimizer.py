"""Optimizer tests (reference tests/python/unittest/test_optimizer.py).

Every registered optimizer must reduce a convex quadratic; specific
update rules are cross-checked against hand-rolled NumPy where the
formula is simple (SGD-momentum, Adam).
"""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, optimizer as opt
from incubator_mxnet_tpu.optimizer import lr_scheduler
from incubator_mxnet_tpu.test_utils import assert_almost_equal

ALL_OPTS = ["sgd", "sgld", "signum", "dcasgd", "nag", "adagrad", "adadelta",
            "adam", "adamw", "adamax", "nadam", "ftrl", "ftml", "lars",
            "lamb", "rmsprop", "lbsgd"]


def _minimize(name, steps=60, lr=0.1, **kw):
    """Run `steps` updates of x on f(x)=0.5*||x-t||^2; return final gap."""
    o = opt.create(name, learning_rate=lr, **kw)
    target = onp.array([1.0, -2.0, 3.0], "float32")
    w = nd.zeros((3,))
    state = o.create_state(0, w)
    for _ in range(steps):
        grad = nd.array(w.asnumpy() - target)
        o.update(0, w, grad, state)
    return float(onp.abs(w.asnumpy() - target).max())


@pytest.mark.parametrize("name", ALL_OPTS)
def test_optimizer_minimizes_quadratic(name):
    start_gap = 3.0
    # adadelta ignores lr (classic rule): needs more steps to accumulate
    gap = _minimize(name, steps=400) if name == "adadelta" else _minimize(name)
    assert gap < start_gap * 0.7, f"{name} failed to make progress: {gap}"


def test_create_unknown_raises():
    with pytest.raises(ValueError):
        opt.create("not_an_optimizer")


def test_sgd_momentum_matches_numpy():
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9)
    w = nd.array([1.0, 2.0])
    g = nd.array([0.5, -0.5])
    state = o.create_state(0, w)
    # step 1: mom = -lr*g ; w += mom
    o.update(0, w, g, state)
    expect_mom = -0.1 * g.asnumpy()
    expect_w = onp.array([1.0, 2.0]) + expect_mom
    assert_almost_equal(w, expect_w, rtol=1e-5)
    # step 2: mom = 0.9*mom - lr*g
    o.update(0, w, g, state)
    expect_mom = 0.9 * expect_mom - 0.1 * g.asnumpy()
    expect_w = expect_w + expect_mom
    assert_almost_equal(w, expect_w, rtol=1e-5)


def test_sgd_weight_decay():
    o = opt.create("sgd", learning_rate=0.1, wd=0.1)
    w = nd.array([1.0])
    o.update(0, w, nd.array([0.0]), o.create_state(0, w))
    # pure decay: w -= lr * wd * w
    assert w.asnumpy()[0] == pytest.approx(1.0 - 0.1 * 0.1, rel=1e-5)


def test_adam_first_step_matches_formula():
    o = opt.create("adam", learning_rate=0.1, beta1=0.9, beta2=0.999,
                   epsilon=1e-8)
    w = nd.array([1.0])
    g = nd.array([2.0])
    state = o.create_state(0, w)
    o.update(0, w, g, state)
    m = 0.1 * 2.0
    v = 0.001 * 4.0
    mhat = m / (1 - 0.9)
    vhat = v / (1 - 0.999)
    expect = 1.0 - 0.1 * mhat / (onp.sqrt(vhat) + 1e-8)
    assert w.asnumpy()[0] == pytest.approx(expect, rel=1e-4)


def test_clip_gradient():
    o = opt.create("sgd", learning_rate=1.0, clip_gradient=0.5)
    w = nd.array([0.0])
    o.update(0, w, nd.array([10.0]), o.create_state(0, w))
    assert w.asnumpy()[0] == pytest.approx(-0.5, rel=1e-5)


def test_rescale_grad():
    o = opt.create("sgd", learning_rate=1.0, rescale_grad=0.25)
    w = nd.array([0.0])
    o.update(0, w, nd.array([4.0]), o.create_state(0, w))
    assert w.asnumpy()[0] == pytest.approx(-1.0, rel=1e-5)


def test_lr_mult_and_wd_mult():
    o = opt.create("sgd", learning_rate=1.0)
    o.set_lr_mult({0: 0.1})
    w = nd.array([0.0])
    o.update(0, w, nd.array([1.0]), o.create_state(0, w))
    assert w.asnumpy()[0] == pytest.approx(-0.1, rel=1e-5)


def test_multi_precision_master_weights():
    o = opt.create("sgd", learning_rate=0.1, multi_precision=True)
    w = nd.ones((4,)).astype("float16")
    state = o.create_state_multi_precision(0, w)
    master = state[0]
    assert str(master.data.dtype) == "float32"
    o.update_multi_precision(0, w, nd.ones((4,)).astype("float16"), state)
    assert str(w.data.dtype) == "float16"
    assert w.asnumpy()[0] == pytest.approx(0.9, rel=1e-2)


def test_factor_scheduler():
    s = lr_scheduler.FactorScheduler(step=2, factor=0.5, base_lr=1.0)
    assert s(0) == 1.0
    assert s(3) == 0.5  # boundary is exclusive (reference semantics)
    assert s(5) == 0.25


def test_multifactor_scheduler():
    s = lr_scheduler.MultiFactorScheduler(step=[3, 6], factor=0.1,
                                          base_lr=1.0)
    assert s(1) == 1.0
    assert s(4) == pytest.approx(0.1)
    assert s(7) == pytest.approx(0.01)


def test_poly_and_cosine_schedulers():
    p = lr_scheduler.PolyScheduler(max_update=10, base_lr=1.0, pwr=2)
    assert p(0) == 1.0
    assert p(10) <= p(5) <= p(0)
    c = lr_scheduler.CosineScheduler(max_update=10, base_lr=1.0,
                                     final_lr=0.0)
    assert c(0) == pytest.approx(1.0)
    assert c(10) == pytest.approx(0.0, abs=1e-6)
    assert 0.0 < c(5) < 1.0


def test_optimizer_with_scheduler_advances():
    sched = lr_scheduler.FactorScheduler(step=1, factor=0.5, base_lr=1.0)
    o = opt.create("sgd", learning_rate=1.0, lr_scheduler=sched)
    w = nd.array([0.0])
    st = o.create_state(0, w)
    o.update(0, w, nd.array([1.0]), st)   # num_update=1: still base lr
    first = w.asnumpy()[0]
    assert first == pytest.approx(-1.0, rel=1e-5)
    o.update(0, w, nd.array([1.0]), st)   # num_update=2: decayed once
    assert w.asnumpy()[0] == pytest.approx(first - 0.5, rel=1e-5)


def test_updater_serialization(tmp_path):
    from incubator_mxnet_tpu.optimizer import Updater
    o = opt.create("adam", learning_rate=0.1)
    u = Updater(o)
    w = nd.array([1.0, 2.0])
    u(0, nd.array([0.1, 0.1]), w)
    blob = u.get_states()
    u2 = Updater(opt.create("adam", learning_rate=0.1))
    u2.set_states(blob)
    assert set(u2.states) == set(u.states)
