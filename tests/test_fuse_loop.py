"""Chunked train loop (fuse_loop.py): lax.scan over K fused steps.

The contract (docs/performance.md "Chunked training loop"): a chunked
run over a batch schedule must land the same weights as the per-step
fused loop over the identical schedule — same PRNG split sequence,
same optimizer math — while dispatching once per K steps; the epoch
tail that does not fill a chunk reuses the per-step program (never a
second loop executable); K=1 degenerates to the existing fused step
exactly.  The graphlint/memlint pins keep the scanned program
zero-finding with donation coverage 1.0 on the scan carry.
"""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, gluon
from incubator_mxnet_tpu.fuse import make_fused_train_step
from incubator_mxnet_tpu.fuse_loop import ChunkedTrainLoop
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.gluon.data.dataloader import DevicePrefetchRing

# the pinned parity tolerance (train_loop_bench quotes the same):
# XLA may re-fuse the scan body, which moves float rounding, not math
RTOL, ATOL = 2e-5, 1e-6


def _net(seed=0, dropout=0.0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"))
    if dropout:
        net.add(nn.Dropout(dropout))
    net.add(nn.Dense(5, in_units=16))
    net.initialize()
    net(nd.random.uniform(shape=(1, 8)))
    return net


def _batches(n, bs=4, seed=1):
    rng = onp.random.RandomState(seed)
    return [(nd.array(rng.rand(bs, 8).astype("f")),
             nd.array(rng.randint(0, 5, (bs,)).astype("i4")))
            for _ in range(n)]


def _step(opt="sgd", dropout=0.0, seed=0, **kw):
    return make_fused_train_step(
        _net(seed, dropout), gluon.loss.SoftmaxCrossEntropyLoss(),
        opt, {"learning_rate": 0.1, "momentum": 0.9}
        if opt in ("sgd", "nag") else {"learning_rate": 0.01}, **kw)


def _leaves(step):
    import jax
    return jax.tree_util.tree_leaves(
        {**step.params, **step.aux, **step.opt_state})


def _assert_state_close(a, b, rtol=RTOL, atol=ATOL):
    for x, y in zip(_leaves(a), _leaves(b)):
        onp.testing.assert_allclose(onp.asarray(x), onp.asarray(y),
                                    rtol=rtol, atol=atol)


# ---------------------------------------------------------------------------
# parity: chunked == sequential fused over the same schedule
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_chunked_matches_sequential_fused(opt):
    batches = _batches(8)
    seq = _step(opt)
    for x, y in batches:
        seq(x, y)

    ch = _step(opt, chunk_steps=4)
    loop = ch.chunked_loop()
    records = loop.run_epoch(batches)
    assert [r["kind"] for r in records] == ["chunk", "chunk"]
    assert loop.chunks_run == 2 and loop.tail_steps_run == 0
    _assert_state_close(seq, ch)
    # the scan split the PRNG key exactly as the host loop did
    assert bool((seq._key == ch._key).all())


def test_chunk_mean_loss_matches_sequential_step_losses():
    batches = _batches(4)
    seq = _step()
    losses = [float(seq(x, y)) for x, y in batches]
    ch = _step(chunk_steps=4)
    rec = ch.chunked_loop().run_epoch(batches)
    assert float(rec[0]["loss"]) == pytest.approx(
        sum(losses) / len(losses), rel=1e-5)


# ---------------------------------------------------------------------------
# the epoch tail (length not divisible by K)
# ---------------------------------------------------------------------------

def test_tail_runs_per_step_and_compiles_no_second_loop():
    batches = _batches(10)
    seq = _step()
    for x, y in batches:
        seq(x, y)

    ch = _step(chunk_steps=4)
    loop = ch.chunked_loop()
    records = loop.run_epoch(batches)
    assert [r["kind"] for r in records] == ["chunk", "chunk", "tail"]
    assert records[-1]["steps"] == 2
    assert loop.chunks_run == 2 and loop.tail_steps_run == 2
    # exactly one loop executable — the 2-step tail reused the
    # per-step program instead of compiling a (2, bucket) loop
    assert loop.compile_count == 1
    _assert_state_close(seq, ch)
    assert bool((seq._key == ch._key).all())


def test_tail_steps_bitwise_equal_to_per_step_continuation():
    """The tail IS the per-step fused program: continuing a chunked
    prefix by hand through step() must land bitwise-identical state to
    what run_epoch's tail produced (same executable, same inputs)."""
    batches = _batches(10)
    full = _step(chunk_steps=4)
    full.chunked_loop().run_epoch(batches)

    manual = _step(chunk_steps=4)           # same seed ⇒ same state
    manual.chunked_loop().run_epoch(batches[:8])
    for x, y in batches[8:]:
        manual(x, y)                        # per-step continuation

    for a, b in zip(_leaves(full), _leaves(manual)):
        assert bool((a == b).all())
    assert bool((full._key == manual._key).all())


# ---------------------------------------------------------------------------
# K=1 degenerates to the existing fused step
# ---------------------------------------------------------------------------

def test_k1_is_the_per_step_fused_path_bitwise():
    batches = _batches(6)
    seq = _step()
    for x, y in batches:
        seq(x, y)

    ch = _step(chunk_steps=1)
    loop = ch.chunked_loop()
    records = loop.run_epoch(batches)
    # no loop program exists at K=1 — nothing scanned, nothing compiled
    assert loop._executor is None and loop.compile_count == 0
    assert all(r["kind"] == "step" for r in records)
    for a, b in zip(_leaves(seq), _leaves(ch)):
        assert bool((a == b).all())
    assert bool((seq._key == ch._key).all())


def test_run_chunk_rejects_k1_and_wrong_block_length():
    ch = _step(chunk_steps=1)
    with pytest.raises(RuntimeError, match="chunk_steps == 1"):
        ch.chunked_loop().run_chunk(None, None)
    ch4 = _step(chunk_steps=4)
    loop = ch4.chunked_loop()
    import jax.numpy as jnp
    with pytest.raises(ValueError, match="loop compiled for"):
        loop.run_chunk(jnp.zeros((2, 4, 8)), jnp.zeros((2, 4), "int32"))
    with pytest.raises(ValueError, match="chunk_steps"):
        ChunkedTrainLoop(ch4, chunk_steps=0)
    with pytest.raises(ValueError, match="chunk_steps"):
        make_fused_train_step(_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                              "sgd", {}, chunk_steps=-2)


# ---------------------------------------------------------------------------
# PRNG stream parity (dropout)
# ---------------------------------------------------------------------------

def test_dropout_sees_identical_keys_chunked_and_sequential():
    """Dropout masks are drawn from the per-step key: the scan must
    split keys exactly as the sequential host loop does, or training
    trajectories silently diverge."""
    batches = _batches(8)
    seq = _step(dropout=0.5)
    for x, y in batches:
        seq(x, y)
    ch = _step(dropout=0.5, chunk_steps=4)
    ch.chunked_loop().run_epoch(batches)
    _assert_state_close(seq, ch)
    assert bool((seq._key == ch._key).all())


def test_key_schedule_is_the_sequential_split_chain():
    import jax
    import jax.numpy as jnp
    ch = _step(chunk_steps=4)
    k0 = jnp.array(ch._key)     # copy: the loop donates the key buffer
    ch.chunked_loop().run_epoch(_batches(4))
    expect = k0
    for _ in range(4):
        expect, _sub = jax.random.split(expect)
    assert bool((ch._key == expect).all())


# ---------------------------------------------------------------------------
# trace-key / sentinel behavior: one executable per (K, bucket)
# ---------------------------------------------------------------------------

def test_one_loop_compile_per_bucket_and_flat_across_epochs():
    ch = _step(chunk_steps=2)
    loop = ch.chunked_loop()
    loop.run_epoch(_batches(4, bs=4))
    assert loop.compile_count == 1
    loop.run_epoch(_batches(4, bs=4, seed=2))   # same bucket: no retrace
    assert loop.compile_count == 1
    loop.run_epoch(_batches(4, bs=2, seed=3))   # new bucket: one more
    assert loop.compile_count == 2
    loop.run_epoch(_batches(4, bs=2, seed=4))
    assert loop.compile_count == 2


def test_chunked_loop_carries_mesh_batch_sharding():
    """A mesh-built step's chunked loop must shard its blocks with the
    step's batch spec (scan axis unsharded) — not silently replicate
    them across the mesh — and still match the unsharded run."""
    import jax
    from jax.sharding import PartitionSpec as P

    if len(jax.devices()) < 2:
        pytest.skip("needs the 8-device CPU dryrun mesh")
    from incubator_mxnet_tpu.parallel import make_mesh

    batches = _batches(4, bs=4)
    seq = _step()
    for x, y in batches:
        seq(x, y)

    mesh = make_mesh(dp=2)
    ch = make_fused_train_step(
        _net(), gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9},
        mesh=mesh, batch_spec=P("dp"), chunk_steps=2)
    loop = ch.chunked_loop()
    # the compiled loop demands dp-sharded blocks on the batch axis
    # (scan axis unsharded) — probe the executable's input shardings
    from jax.sharding import NamedSharding
    sds = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
        (ch.params, ch.aux, ch.opt_state, ch._key))
    xs_sd = jax.ShapeDtypeStruct((2, 4, 8), "float32")
    ys_sd = jax.ShapeDtypeStruct((2, 4), "int32")
    compiled = loop._executor.jfn.lower(*sds, xs_sd, ys_sd).compile()
    want = NamedSharding(mesh, P(None, "dp"))
    block_shardings = compiled.input_shardings[0][-2:]
    assert all(s == want for s in block_shardings), block_shardings
    loop.run_epoch(batches)
    _assert_state_close(seq, ch)
    # value compare via host: the mesh run's key is replicated across
    # devices, the single-device run's is not — == across placements
    # is a jit device error, not a parity statement
    onp.testing.assert_array_equal(onp.asarray(seq._key),
                                   onp.asarray(ch._key))


# ---------------------------------------------------------------------------
# graphlint/memlint pins (satellite): the scanned program analyzes clean
# ---------------------------------------------------------------------------

def test_scanned_loop_zero_findings_and_full_donation_coverage():
    """The fused-step GL-DEAD001 exemption carries into the scan-body
    walk (zero findings on the chunked MLP loop), and memlint sees the
    scan carry fully donated: donation_coverage == 1.0."""
    from incubator_mxnet_tpu.analysis import graphlint as gl

    ch = _step(chunk_steps=4)
    loop = ch.chunked_loop()
    (x0, y0) = _batches(1)[0]
    import jax.numpy as jnp
    xs = jnp.stack([x0.data] * 4)
    ys = jnp.stack([y0.data] * 4)
    args = (ch.params, ch.aux, ch.opt_state, ch._key, xs, ys)
    prev = gl.set_lint_mode("warn")
    try:
        findings, _ = loop._executor.analyze(
            args, graphlint=dict(
                check_donation=True,
                config=gl.Config(ignore={"GL-DEAD001"})))
    finally:
        gl.set_lint_mode(prev)
    assert findings == []

    from incubator_mxnet_tpu.analysis import memlint as ml
    prev = ml.set_mem_mode("warn")
    try:
        _, rep = loop._executor.analyze(
            args, memlint=dict(require_donation=True))
    finally:
        ml.set_mem_mode(prev)
    assert rep is not None
    assert rep.donation_coverage == 1.0
    assert not [f for f in rep.findings if f.severity == "error"]


def test_lint_latch_runs_through_run_chunk():
    """Enabling strict modes before the first chunk must analyze the
    scanned program through the choke point (and pass)."""
    from incubator_mxnet_tpu.analysis import graphlint as gl
    from incubator_mxnet_tpu.analysis import memlint as ml

    ch = _step(chunk_steps=2)
    loop = ch.chunked_loop()
    pg, pm = gl.set_lint_mode("strict"), ml.set_mem_mode("strict")
    try:
        loop.run_epoch(_batches(2))
    finally:
        gl.set_lint_mode(pg)
        ml.set_mem_mode(pm)
    assert loop._lint_done and loop._memlint_done


# ---------------------------------------------------------------------------
# DevicePrefetchRing
# ---------------------------------------------------------------------------

def test_ring_groups_blocks_in_order_with_tail():
    rng = onp.random.RandomState(0)
    batches = [(rng.rand(2, 3).astype("f"), rng.rand(2).astype("f"))
               for _ in range(7)]
    out = list(DevicePrefetchRing(batches, 3))
    assert [b[0] for b in out] == ["chunk", "chunk", "tail"]
    for i, (_, xs, ys) in enumerate(out[:2]):
        assert xs.shape == (3, 2, 3) and ys.shape == (3, 2)
        for k in range(3):
            onp.testing.assert_array_equal(onp.asarray(xs[k]),
                                           batches[3 * i + k][0])
    assert len(out[2][1]) == 1
    onp.testing.assert_array_equal(onp.asarray(out[2][1][0][0]),
                                   batches[6][0])


def test_ring_nd_and_numpy_sources_agree():
    rng = onp.random.RandomState(0)
    np_batches = [(rng.rand(2, 3).astype("f"),
                   rng.randint(0, 4, (2,)).astype("i4"))
                  for _ in range(4)]
    nd_batches = [(nd.array(x), nd.array(y)) for x, y in np_batches]
    a = list(DevicePrefetchRing(np_batches, 2))
    b = list(DevicePrefetchRing(nd_batches, 2))
    assert len(a) == len(b) == 2
    for (ka, xa, ya), (kb, xb, yb) in zip(a, b):
        assert ka == kb == "chunk"
        onp.testing.assert_array_equal(onp.asarray(xa), onp.asarray(xb))
        onp.testing.assert_array_equal(onp.asarray(ya), onp.asarray(yb))


def test_ring_exact_multiple_has_no_tail_and_empty_source_is_empty():
    rng = onp.random.RandomState(0)
    batches = [(rng.rand(2, 3).astype("f"), rng.rand(2).astype("f"))
               for _ in range(4)]
    out = list(DevicePrefetchRing(batches, 2))
    assert [b[0] for b in out] == ["chunk", "chunk"]
    assert list(DevicePrefetchRing([], 2)) == []
    with pytest.raises(ValueError):
        DevicePrefetchRing(batches, 0)
    with pytest.raises(ValueError):
        DevicePrefetchRing(batches, 2, depth=0)


def test_trainer_chunk_steps_env_default(monkeypatch):
    from incubator_mxnet_tpu.gluon import Trainer
    net = _net()
    monkeypatch.setenv("MXNET_TRAIN_CHUNK_STEPS", "5")
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 kvstore=None)
    assert tr._chunk_steps == 5
    assert not tr._at_chunk_boundary() or tr._step_count == 0
    tr._step_count = 4
    assert not tr._at_chunk_boundary()
    tr._step_count = 5
    assert tr._at_chunk_boundary()
    step = make_fused_train_step(
        _net(), gluon.loss.SoftmaxCrossEntropyLoss(), "sgd", {})
    assert step.chunk_steps == 5
    with pytest.raises(ValueError, match="chunk_steps"):
        Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                kvstore=None, chunk_steps=0)
