"""Horovod/BytePS kvstore adapters exercised through STUB transports
(VERDICT r3 Weak #8: the adapters were guard-raise dead code in every
test env because horovod isn't installable here).  The stubs implement
the exact surface the adapters call (horovod.mxnet allreduce/allreduce_/
broadcast/init/rank/size; byteps.mxnet byteps_declare_tensor/
byteps_push_pull), so every adapter line runs; the distributed math
itself belongs to horovod/byteps and is not re-verified."""
import sys
import types

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def _install_fake_hvd(monkeypatch, size=1):
    calls = []
    hvd = types.ModuleType("horovod.mxnet")

    def init():
        calls.append(("init",))

    def rank():
        return 0

    def _size():
        return size

    def allreduce(tensor, average=False, name=None, priority=0):
        calls.append(("allreduce", name, average, priority))
        return tensor * (1 if average else size)

    def allreduce_(tensor, average=False, name=None, priority=0):
        calls.append(("allreduce_", name, average, priority))
        tensor[:] = tensor * (1 if average else size)
        return tensor

    def broadcast(tensor, root_rank=0, name=None, priority=0):
        calls.append(("broadcast", name, root_rank))
        return tensor

    hvd.init, hvd.rank, hvd.size = init, rank, _size
    hvd.allreduce, hvd.allreduce_, hvd.broadcast = \
        allreduce, allreduce_, broadcast
    pkg = types.ModuleType("horovod")
    pkg.mxnet = hvd
    monkeypatch.setitem(sys.modules, "horovod", pkg)
    monkeypatch.setitem(sys.modules, "horovod.mxnet", hvd)
    return calls


def _install_fake_bps(monkeypatch):
    calls = []
    bps = types.ModuleType("byteps.mxnet")
    bps.init = lambda: calls.append(("init",))
    bps.rank = lambda: 0
    bps.size = lambda: 1
    bps.byteps_declare_tensor = \
        lambda name: calls.append(("declare", name))
    def push_pull(tensor, name=None, is_average=False, priority=0):
        calls.append(("push_pull", name, is_average))
        return tensor
    bps.byteps_push_pull = push_pull
    pkg = types.ModuleType("byteps")
    pkg.mxnet = bps
    monkeypatch.setitem(sys.modules, "byteps", pkg)
    monkeypatch.setitem(sys.modules, "byteps.mxnet", bps)
    return calls


def test_horovod_adapter_wiring(monkeypatch):
    calls = _install_fake_hvd(monkeypatch)
    kv = mx.kv.create("horovod")
    assert ("init",) in calls
    assert kv.rank == 0 and kv.num_workers == 1
    assert type(kv).is_capable(type(kv).PUSH_PULL)

    # broadcast: root value lands in every out buffer
    v = nd.array(onp.arange(4, dtype=onp.float32))
    out = nd.zeros((4,))
    kv.broadcast("w", v, out)
    onp.testing.assert_allclose(out.asnumpy(), v.asnumpy())

    # pushpull out-of-place
    out2 = nd.zeros((4,))
    kv.pushpull("w", v, out=out2)
    onp.testing.assert_allclose(out2.asnumpy(), v.asnumpy())
    assert any(c[0] == "allreduce" for c in calls)

    # pushpull in-place
    kv.pushpull("w", v)
    assert any(c[0] == "allreduce_" for c in calls)

    # allreduce stores have no push/pull/server-optimizer
    with pytest.raises(NotImplementedError):
        kv.push("w", v)
    with pytest.raises(NotImplementedError):
        kv.pull("w", out=out)
    with pytest.raises(NotImplementedError):
        kv.set_optimizer(mx.optimizer.SGD())


def test_horovod_trainer_integration(monkeypatch):
    """gluon.Trainer(..., kvstore='horovod') drives grads through the
    adapter's pushpull (the reference horovod workflow)."""
    _install_fake_hvd(monkeypatch)
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.gluon import nn
    net = nn.Dense(3)
    net.initialize()
    x = nd.random.uniform(shape=(4, 5))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore="horovod")
    tr.step(4)  # must not raise; grads ride the stub allreduce


def test_byteps_adapter_wiring(monkeypatch):
    calls = _install_fake_bps(monkeypatch)
    kv = mx.kv.create("byteps")
    assert kv.rank == 0 and kv.num_workers == 1
    v = nd.array(onp.ones(3, onp.float32))
    out = nd.zeros((3,))
    kv.broadcast("p", v, out)
    assert ("declare", "p") in calls
    onp.testing.assert_allclose(out.asnumpy(), 1.0)
    with pytest.raises(NotImplementedError):
        kv.push("p", v)


def test_missing_horovod_raises_clear_error():
    # no stub installed -> ImportError with guidance, not silent fallback
    import importlib
    if "horovod" in sys.modules:
        pytest.skip("a horovod module is importable in this env")
    with pytest.raises(ImportError, match="horovod"):
        mx.kv.create("horovod")
