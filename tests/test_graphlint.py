"""graphlint (IR jaxpr passes) + recompilation sentinel
(docs/graph_analysis.md).

Each rule gets a must-flag and a must-pass fixture; the framework's own
graphs (model zoo forward, Symbol executor, curated op sweep) are
pinned at ZERO findings; the sentinel batteries prove storm detection,
churn diagnosis, bucketed-replay silence and flag-off inertness.
"""
import warnings

import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax import lax

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import error, profiler
from incubator_mxnet_tpu.analysis import graphlint as gl
from incubator_mxnet_tpu.analysis import recompile as rc
from incubator_mxnet_tpu.ops import registry


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# per-rule fixtures
# ---------------------------------------------------------------------------

class TestConstRule:
    def test_baked_constant_flags(self):
        big = onp.ones((600, 600), onp.float32)   # 1.44 MB > 1 MiB

        def f(x):
            return x @ big

        fs = gl.lint_fn(f, jnp.ones((2, 600)))
        assert rules_of(fs) == ["GL-CONST001"]
        assert "600, 600" in fs[0].message

    def test_passed_as_argument_clean(self):
        fs = gl.lint_fn(lambda x, w: x @ w, jnp.ones((2, 600)),
                        jnp.ones((600, 600)))
        assert fs == []

    def test_threshold_configurable(self):
        small = onp.ones((64, 64), onp.float32)   # 16 KB

        def f(x):
            return x @ small

        assert gl.lint_fn(f, jnp.ones((2, 64))) == []
        fs = gl.lint_fn(f, jnp.ones((2, 64)),
                        config=gl.Config(const_bytes=1024))
        assert rules_of(fs) == ["GL-CONST001"]


class TestDeadRule:
    def test_dead_eqn_flags(self):
        def f(x):
            _dead = jnp.sin(x)
            return (x * 2).sum()

        fs = gl.lint_fn(f, jnp.ones((4,)))
        assert rules_of(fs) == ["GL-DEAD001"]
        assert "sin" in fs[0].message

    def test_all_used_clean(self):
        assert gl.lint_fn(lambda x: (jnp.sin(x) + x * 2).sum(),
                          jnp.ones((4,))) == []

    def test_dead_inside_scan_body_located(self):
        def f(x):
            def body(c, t):
                _dead = jnp.cos(t) * 3.0
                return c + t, c

            return lax.scan(body, jnp.zeros_like(x[0]), x)[0]

        fs = gl.lint_fn(f, jnp.ones((4, 4)))
        assert any(f_.rule == "GL-DEAD001" and "/scan" in f_.path
                   for f_ in fs)

    def test_multi_output_partially_used_clean(self):
        """One consumed output keeps a multi-output eqn alive: scan's
        stacked ys go unused, but the carry is — the scan eqn itself
        must not be reported dead."""
        def f(x):
            carry, _ys = lax.scan(lambda c, t: (c + t, c * 2),
                                  jnp.zeros_like(x[0]), x)
            return carry.sum()

        fs = gl.lint_fn(f, jnp.ones((3, 4)))
        assert not any(f_.primitive == "scan" for f_ in fs)


class TestPromotionRule:
    def test_f32_array_promotes_bf16_flags(self):
        def f(x):
            c = jnp.ones((4,), jnp.float32) * 2.0
            return x + c

        fs = gl.lint_fn(f, jnp.ones((4,), jnp.bfloat16))
        assert rules_of(fs) == ["GL-DTYPE002"]

    def test_f32_param_promotes_bf16_flags(self):
        fs = gl.lint_fn(lambda x, w: x * w,
                        jnp.ones((8,), jnp.bfloat16),
                        jnp.ones((8,), jnp.float32))
        assert rules_of(fs) == ["GL-DTYPE002"]

    def test_deliberate_upcast_region_clean(self):
        """A layer_norm-style f32 compute region: the widened value only
        ever meets values derived from itself (taint exemption)."""
        def f(x):
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, keepdims=True)
            return ((xf - mean) ** 2).astype(x.dtype)

        assert gl.lint_fn(f, jnp.ones((64,), jnp.bfloat16)) == []

    def test_weak_python_scalar_clean(self):
        assert gl.lint_fn(lambda x: x * 2.0 + 1.0,
                          jnp.ones((4,), jnp.bfloat16)) == []

    def test_framework_layer_norm_clean(self):
        fs = gl.lint_op("LayerNorm", ((16, 128), "bfloat16"),
                        ((128,), "float32"), ((128,), "float32"))
        assert fs == []


class TestAccumRule:
    def test_bf16_reduce_window_flags(self):
        def f(x):
            return lax.reduce_window(x, 0.0, lax.add, (1024,), (1,),
                                     "VALID")

        fs = gl.lint_fn(f, jnp.ones((2048,), jnp.bfloat16))
        assert rules_of(fs) == ["GL-PREC001"]
        assert "1024" in fs[0].message

    def test_jnp_sum_bf16_clean(self):
        """jnp.sum upcasts bf16 to f32 internally — must not flag."""
        assert gl.lint_fn(lambda x: jnp.sum(x),
                          jnp.ones((4096,), jnp.bfloat16)) == []

    def test_small_window_clean(self):
        """A 3x3 pool window accumulates 9 elements — under threshold."""
        fs = gl.lint_op("Pooling", ((2, 8, 16, 16), "bfloat16"),
                        kernel=(3, 3), pool_type="avg")
        assert fs == []

    def test_f32_reduce_clean(self):
        def f(x):
            return lax.reduce_window(x, 0.0, lax.add, (1024,), (1,),
                                     "VALID")

        assert gl.lint_fn(f, jnp.ones((2048,), jnp.float32)) == []

    def test_pooling_bf16_big_window_fixed(self):
        """The finding this rule surfaced in the framework: avg pooling
        with a big window now accumulates in f32 (lint clean) and its
        value tracks the f32 reference instead of drifting."""
        fs = gl.lint_op("Pooling", ((1, 4, 64, 64), "bfloat16"),
                        kernel=(64, 64), pool_type="avg")
        assert fs == []
        op = registry.get_op("Pooling")
        x32 = jax.random.uniform(jax.random.PRNGKey(7), (1, 2, 64, 64),
                                 jnp.float32)
        ref = op.fn(x32, kernel=(64, 64), pool_type="avg")
        got = op.fn(x32.astype(jnp.bfloat16), kernel=(64, 64),
                    pool_type="avg")
        # a bf16-accumulated 4096-element sum saturates (~88% relative
        # error); f32 accumulation lands within one bf16 ulp of the ref
        assert float(jnp.max(jnp.abs(got.astype(jnp.float32) - ref))) \
            < 8e-3
        assert got.dtype == jnp.bfloat16


class TestHostRule:
    def test_pure_callback_flags(self):
        def f(x):
            return jax.pure_callback(
                lambda a: onp.asarray(a) * 2,
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        fs = gl.lint_fn(f, jnp.ones((4,)))
        assert "GL-HOST001" in rules_of(fs)


class TestTileRule:
    def test_long_skinny_flags(self):
        fs = gl.lint_fn(lambda x: x.reshape(65536, 4) * 2,
                        jnp.ones((4 * 65536,)))
        assert rules_of(fs) == ["GL-TILE001"]
        assert "(65536, 4)" in fs[0].message

    def test_lane_aligned_clean(self):
        assert gl.lint_fn(lambda x: x.reshape(2048, 128) * 2,
                          jnp.ones((2048 * 128,))) == []

    def test_small_array_clean(self):
        assert gl.lint_fn(lambda x: x.reshape(256, 4) * 2,
                          jnp.ones((1024,))) == []


class TestF64Rule:
    def test_f64_flags_under_x64(self):
        with jax.experimental.enable_x64():
            def f(x):
                return (x.astype(jnp.float64) * 2.0).sum()

            fs = gl.lint_fn(f, jnp.ones((4,), jnp.float32))
        assert "GL-DTYPE001" in rules_of(fs)

    def test_f32_clean(self):
        assert gl.lint_fn(lambda x: (x * 2.0).sum(),
                          jnp.ones((4,), jnp.float32)) == []


# ---------------------------------------------------------------------------
# framework surfaces + config plumbing
# ---------------------------------------------------------------------------

class TestEntryPoints:
    def test_ignore_silences(self):
        def f(x):
            _dead = jnp.sin(x)
            return x.sum()

        assert gl.lint_fn(f, jnp.ones((4,)),
                          config=gl.Config(ignore={"GL-DEAD001"})) == []

    def test_render_and_dicts(self):
        def f(x):
            _dead = jnp.sin(x)
            return x.sum()

        fs = gl.lint_fn(f, jnp.ones((4,)), where="toy")
        text = gl.render(fs)
        assert "GL-DEAD001" in text and "toy" in text
        d = fs[0].as_dict()
        assert d["rule"] == "GL-DEAD001" and d["where"] == "toy"

    def test_lint_op_accepts_shape_dtype_specs(self):
        assert gl.lint_op("FullyConnected", ((8, 32), "float32"),
                          ((16, 32), "float32"), ((16,), "float32")) == []

    def test_zoo_block_clean_both_modes(self):
        from incubator_mxnet_tpu.gluon.model_zoo import vision
        net = vision.get_model("resnet18_v1", classes=10)
        net.initialize()
        x = mx.nd.random.uniform(shape=(2, 3, 32, 32))
        net(x)
        assert gl.lint_block(net, x) == []
        assert gl.lint_block(net, x, training=True) == []

    def test_symbol_clean_and_missing_shape_raises(self):
        from incubator_mxnet_tpu import sym
        data = sym.var("data")
        net = sym.FullyConnected(data, num_hidden=16, name="fc1")
        net = sym.Activation(net, act_type="relu")
        shapes = {"data": (4, 8), "fc1_weight": (16, 8),
                  "fc1_bias": (16,)}
        assert gl.lint_symbol(net, shapes) == []
        with pytest.raises(ValueError, match="fc1_weight"):
            gl.lint_symbol(net, {"data": (4, 8)})

    def test_ops_smoke_sweep_clean(self):
        """The CI stage's curated central-op sweep is pinned clean."""
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "_glcli", os.path.join(os.path.dirname(__file__), "..",
                                   "tools", "graphlint.py"))
        cli = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cli)
        for op, specs, kwargs in cli._OPS_SMOKE:
            assert gl.lint_op(op, *specs, **kwargs) == [], \
                f"{op} {kwargs} not clean"

    def test_seeded_violation_fails_cli_path(self):
        """A deliberately dirty graph exits 1 through lint_op, the same
        path the CI graphlint stage uses."""
        from incubator_mxnet_tpu.ops.registry import register, _OPS
        name = "_test_graphlint_dirty"

        @register(name)
        def dirty(x):
            _dead = jnp.sin(x)
            return x * 2

        try:
            fs = gl.lint_op(name, ((8,), "float32"))
            assert rules_of(fs) == ["GL-DEAD001"]
        finally:
            _OPS.pop(name, None)


class TestCallingConvention:
    def test_unused_argument_advisory(self):
        fs = gl.lint_fn(lambda x, unused: x * 2, jnp.ones((4,)),
                        jnp.ones((8,)))
        adv = [f for f in fs if f.rule == "GL-DEAD001"]
        assert adv and adv[0].severity == "advisory"
        assert "argument 1" in adv[0].message

    def test_allow_unused_args_silences(self):
        fs = gl.lint_fn(lambda x, unused: x * 2, jnp.ones((4,)),
                        jnp.ones((8,)), allow_unused_args=(1,))
        assert fs == []

    def test_donation_advisory_and_donated_clean(self):
        def sgd(p, g):
            return p - 0.1 * g

        args = (jnp.ones((1024,)), jnp.ones((1024,)))
        fs = gl.lint_fn(sgd, *args, check_donation=True)
        assert [f.rule for f in fs] == ["GL-DONATE001"]
        assert fs[0].severity == "advisory"
        assert gl.lint_fn(sgd, *args, check_donation=True,
                          donate_argnums=(0,)) == []

    def test_donation_off_by_default(self):
        assert gl.lint_fn(lambda p, g: p - 0.1 * g,
                          jnp.ones((1024,)), jnp.ones((1024,))) == []

    def test_small_buffers_not_advised(self):
        assert gl.lint_fn(lambda p, g: p - 0.1 * g, jnp.ones((8,)),
                          jnp.ones((8,)), check_donation=True) == []


@pytest.fixture()
def lint_off():
    prev = gl.set_lint_mode(None)
    yield
    gl.set_lint_mode(prev)


class TestCheckTraced:
    def test_inert_by_default(self, lint_off):
        assert gl.lint_mode() is None
        assert gl.check_traced(lambda x: (jnp.sin(x), x)[1],
                               (jnp.ones((4,)),)) is None

    def test_warn_mode_warns_and_returns(self, lint_off):
        gl.set_lint_mode("warn")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            fs = gl.check_traced(lambda x: (jnp.sin(x), x.sum())[1],
                                 (jnp.ones((4,)),), name="toy")
        assert [f.rule for f in fs] == ["GL-DEAD001"]
        assert any("GL-DEAD001" in str(x.message) for x in w)

    def test_strict_mode_raises_on_error_severity(self, lint_off):
        gl.set_lint_mode("strict")
        with pytest.raises(error.GraphLintError, match="GL-DEAD001"):
            gl.check_traced(lambda x: (jnp.sin(x), x.sum())[1],
                            (jnp.ones((4,)),), name="toy")

    def test_strict_mode_advisory_only_warns(self, lint_off):
        gl.set_lint_mode("strict")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            fs = gl.check_traced(lambda p, g: p - 0.1 * g,
                                 (jnp.ones((1024,)), jnp.ones((1024,))),
                                 name="toy", check_donation=True)
        assert [f.rule for f in fs] == ["GL-DONATE001"]
        assert any("GL-DONATE001" in str(x.message) for x in w)

    def test_untraceable_fn_warns_never_raises(self, lint_off):
        gl.set_lint_mode("strict")

        def bad(x):
            raise RuntimeError("cannot trace me")

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = gl.check_traced(bad, (jnp.ones((4,)),), name="toy")
        assert out is None
        assert any("could not analyze" in str(x.message) for x in w)

    def test_cachedop_choke_strict_catches_seeded_dirty_block(
            self, lint_off):
        from incubator_mxnet_tpu.gluon import nn

        class Dirty(nn.HybridSequential):
            def forward(self, x):
                _dead = (x * 3).sum()   # seeded dead compute
                return super().forward(x)

        net = Dirty()
        net.add(nn.Dense(4))
        net.initialize()
        net.hybridize()
        x = mx.nd.ones((2, 8))
        net(x)   # first pass (deferred init) runs eagerly, no lint
        gl.set_lint_mode("strict")
        net.hybridize()   # drop the cached op so the build re-lints
        with pytest.raises(error.GraphLintError, match="GL-DEAD001"):
            net(x)
        gl.set_lint_mode(None)

    def test_cachedop_choke_clean_block_quiet(self, lint_off):
        from incubator_mxnet_tpu.gluon import nn
        gl.set_lint_mode("strict")
        net = nn.Dense(4)
        net.initialize()
        net.hybridize()
        out = net(mx.nd.ones((2, 8)))   # deferred-init eager pass
        out = net(mx.nd.ones((2, 8)))   # compiled + linted
        assert out.shape == (2, 4)

    def test_bulking_choke_strict_poisons_segment(self, lint_off):
        from incubator_mxnet_tpu.ops import bulking
        from incubator_mxnet_tpu.ops.registry import register, _OPS
        name = "_test_bulk_dirty"

        @register(name)
        def dirty(x):
            _dead = jnp.sin(x)
            return x * 2

        gl.set_lint_mode("strict")
        try:
            with pytest.raises(error.GraphLintError, match="GL-DEAD001"):
                with bulking.bulk_scope(True):
                    y = registry.invoke(name, mx.nd.ones((4,)))
                    y.asnumpy()
        finally:
            gl.set_lint_mode(None)
            _OPS.pop(name, None)
            bulking.clear_trace_cache()

    def test_fused_step_choke_clean(self, lint_off):
        from incubator_mxnet_tpu import fuse, gluon
        from incubator_mxnet_tpu.gluon import nn
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
        net.initialize()
        x = mx.nd.random.uniform(shape=(4, 6))
        net(x)
        gl.set_lint_mode("strict")
        loss = gluon.loss.SoftmaxCrossEntropyLoss()
        step = fuse.make_fused_train_step(net, loss, "sgd",
                                          {"learning_rate": 0.1})
        val = step(x, mx.nd.array(onp.zeros((4,), onp.float32)))
        assert float(val) > 0


class TestExportIntegration:
    def _export(self, tmp_path, fn, params, example, monkeypatch, mode):
        from incubator_mxnet_tpu import deploy
        monkeypatch.setenv("MXNET_EXPORT_GRAPHLINT", mode)
        prefix = str(tmp_path / "m")
        return deploy.export_model(fn, example, prefix, params=params), \
            prefix

    def test_clean_export_records_zero(self, tmp_path, monkeypatch):
        def fwd(params, x):
            return x @ params["w"]

        meta, _ = self._export(
            tmp_path, fwd, {"w": jnp.ones((8, 4))}, (jnp.ones((2, 8)),),
            monkeypatch, "warn")
        assert meta["graphlint"]["findings"] == 0

    def test_dirty_export_warns_and_records(self, tmp_path, monkeypatch):
        baked = onp.ones((600, 600), onp.float32)

        def fwd(params, x):
            return (x @ baked) * params["s"]

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            meta, _ = self._export(
                tmp_path, fwd, {"s": jnp.ones(())},
                (jnp.ones((2, 600)),), monkeypatch, "warn")
        assert meta["graphlint"]["findings"] >= 1
        assert meta["graphlint"]["by_rule"].get("GL-CONST001", 0) >= 1
        assert any("GL-CONST001" in str(x.message) for x in w)

    def test_raise_mode_fails_export(self, tmp_path, monkeypatch):
        baked = onp.ones((600, 600), onp.float32)

        def fwd(params, x):
            return (x @ baked) * params["s"]

        from incubator_mxnet_tpu import deploy
        monkeypatch.setenv("MXNET_EXPORT_GRAPHLINT", "raise")
        with pytest.raises(error.GraphLintError, match="GL-CONST001"):
            deploy.export_model(fwd, (jnp.ones((2, 600)),),
                                str(tmp_path / "m"),
                                params={"s": jnp.ones(())})

    def test_advisory_only_export_does_not_gate(self, tmp_path,
                                                monkeypatch):
        """Advisories never gate: an unused example input (GL-DEAD001
        advisory) must survive raise-mode and record findings=0."""
        def fwd(params, x, unused):
            return x @ params["w"]

        from incubator_mxnet_tpu import deploy
        monkeypatch.setenv("MXNET_EXPORT_GRAPHLINT", "raise")
        meta = deploy.export_model(
            fwd, (jnp.ones((2, 8)), jnp.ones((3,))),
            str(tmp_path / "m"), params={"w": jnp.ones((8, 4))})
        assert meta["graphlint"]["findings"] == 0
        assert meta["graphlint"]["advisories"] >= 1

    def test_off_mode_skips(self, tmp_path, monkeypatch):
        def fwd(params, x):
            return x @ params["w"]

        meta, _ = self._export(
            tmp_path, fwd, {"w": jnp.ones((8, 4))}, (jnp.ones((2, 8)),),
            monkeypatch, "0")
        assert "graphlint" not in meta


# ---------------------------------------------------------------------------
# recompilation sentinel
# ---------------------------------------------------------------------------

@pytest.fixture()
def clean_sentinel():
    rc.reset()
    registry.clear_caches()
    yield
    rc.reset()
    registry.clear_caches()


class TestSentinel:
    def test_off_instrument_is_identity(self, clean_sentinel):
        def f(x):
            return x

        assert rc.enabled() is None
        assert rc.instrument(f, "site") is f

    def test_varying_batch_storms_and_raises(self, clean_sentinel):
        with rc.sentinel_scope("raise", 3):
            with pytest.raises(error.RecompileStormError,
                               match="varying leading/batch"):
                for n in range(1, 10):
                    mx.nd.ones((n, 4)).sum().asscalar()
        st = rc.stats()
        assert "op:sum" in st["storming_sites"]

    def test_warn_mode_throttled(self, clean_sentinel):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            with rc.sentinel_scope("warn", 2):
                for n in range(1, 7):
                    mx.nd.ones((n, 3)).max().asscalar()
        storm = [x for x in w
                 if "recompile storm" in str(x.message)]
        assert 1 <= len(storm) < 4   # crossing + power-of-two throttle
        assert "op:max" in str(storm[0].message)

    def test_bucketed_replay_stays_quiet(self, clean_sentinel):
        buckets = [1, 2, 4, 8]
        with rc.sentinel_scope("raise", len(buckets) + 1):
            for _ in range(3):
                for b in buckets:
                    mx.nd.ones((b, 8)).sum().asscalar()
        st = rc.stats()
        assert st["storming_sites"] == []
        site = st["per_site"]["op:sum"]
        assert site["compiles"] == len(buckets)
        assert site["distinct_signatures"] == len(buckets)
        assert site["retraces"] == 0

    def test_static_arg_churn_diagnosed(self, clean_sentinel):
        with rc.sentinel_scope("warn", 100):
            rc.record_compile("s", (("arr", (4,), "float32"),
                                    ("static", "1")))
            rc.record_compile("s", (("arr", (4,), "float32"),
                                    ("static", "2")))
        assert "static arg" in rc.stats()["per_site"]["s"]["last_change"]

    def test_retrace_of_same_signature_diagnosed(self, clean_sentinel):
        sig = (("arr", (4,), "float32"),)
        with rc.sentinel_scope("warn", 100):
            rc.record_compile("s", sig)
            rc.record_compile("s", sig)
        site = rc.stats()["per_site"]["s"]
        assert site["retraces"] == 1
        assert "re-traced" in site["last_change"]

    def test_varying_static_kwarg_diagnosed(self, clean_sentinel):
        """The flagship churn case: a per-call static kwarg.  The
        signature keeps the kw name AND the inner kind, so the
        diagnosis names the kwarg and the hoist-it remedy."""
        with rc.sentinel_scope("warn", 100):
            rc.record_compile("s", rc.signature_of(
                (jax.ShapeDtypeStruct((4,), jnp.float32),), {"axis": 0}))
            rc.record_compile("s", rc.signature_of(
                (jax.ShapeDtypeStruct((4,), jnp.float32),), {"axis": 1}))
        change = rc.stats()["per_site"]["s"]["last_change"]
        assert "kwarg axis" in change and "static" in change

    def test_kwarg_array_shape_churn_diagnosed(self, clean_sentinel):
        with rc.sentinel_scope("warn", 100):
            rc.record_compile("s", rc.signature_of(
                (), {"x": jax.ShapeDtypeStruct((2, 8), jnp.float32)}))
            rc.record_compile("s", rc.signature_of(
                (), {"x": jax.ShapeDtypeStruct((3, 8), jnp.float32)}))
        change = rc.stats()["per_site"]["s"]["last_change"]
        assert "kwarg x" in change and "varying leading/batch" in change

    def test_bulk_kwarg_variants_are_distinct_sites(self, clean_sentinel):
        """Same op chain + shapes, different static kwargs = genuinely
        different PROGRAMS: each segment structure gets its own site
        (its own storm budget, like op:{name}) — the sentinel must not
        call them a re-traced signature nor pool them into one budget."""
        from incubator_mxnet_tpu.ops import bulking
        with rc.sentinel_scope("warn", 100):
            for axis in (0, 1):
                with bulking.bulk_scope(True):
                    x = mx.nd.ones((4, 6))
                    (x * 2).sum(axis=axis).asnumpy()
            st = rc.stats()["per_site"]
            sites = [k for k in st if k.startswith("bulk:segment:")]
            assert len(sites) == 2
            for s in sites:
                assert st[s]["compiles"] == 1
                assert st[s]["retraces"] == 0

    def test_dtype_flip_diagnosed(self, clean_sentinel):
        with rc.sentinel_scope("warn", 100):
            rc.record_compile("s", (("arr", (4,), "float32"),))
            rc.record_compile("s", (("arr", (4,), "bfloat16"),))
        assert "dtype" in rc.stats()["per_site"]["s"]["last_change"]

    def test_profiler_provider_registered_while_on(self, clean_sentinel):
        with rc.sentinel_scope("warn", 100):
            rc.record_compile("s", (("arr", (4,), "float32"),))
            stats = profiler.provider_stats()
            assert stats["recompile"]["compiles_total"] == 1
        assert "recompile" not in profiler.provider_stats()

    def test_cachedop_site_observed(self, clean_sentinel):
        from incubator_mxnet_tpu.gluon import nn
        with rc.sentinel_scope("warn", 100):
            net = nn.Dense(4)
            net.initialize()
            net.hybridize()
            net(mx.nd.ones((2, 8)))
            net(mx.nd.ones((2, 8)))   # warm replay: no second compile
            st = rc.stats()["per_site"]
            (site,) = [k for k in st if k.startswith("cachedop:")]
            assert st[site]["compiles"] == 1

    def test_bulk_segment_site_observed(self, clean_sentinel):
        from incubator_mxnet_tpu.ops import bulking
        with rc.sentinel_scope("warn", 100):
            for _ in range(2):   # second pass replays the trace cache
                with bulking.bulk_scope(True):
                    x = mx.nd.ones((4, 4))
                    y = ((x * 2) + 1).sum()
                    y.asscalar()
            st = rc.stats()["per_site"]
            sites = [k for k in st if k.startswith("bulk:segment:")]
            assert len(sites) == 1
            assert st[sites[0]]["compiles"] == 1

    def test_scope_restores_mode_and_limit(self, clean_sentinel):
        prev_mode = rc.enabled()
        with rc.sentinel_scope("raise", 2):
            assert rc.enabled() == "raise"
            assert rc.limit() == 2
        assert rc.enabled() == prev_mode

    def test_instrument_preserves_signature(self, clean_sentinel):
        """static_argnames must keep resolving through the wrapper."""
        with rc.sentinel_scope("warn", 100):
            def f(x, k=2):
                return x * k

            traced = rc.instrument(f, "sig-site")
            assert traced is not f
            jfn = jax.jit(traced, static_argnames=("k",))
            out = jfn(jnp.ones((2,)), k=3)
            assert float(out.sum()) == 6.0
            assert rc.stats()["per_site"]["sig-site"]["compiles"] == 1


class TestFusedStepLint:
    def test_fused_step_lints_with_dead_ignored(self):
        """Gradient graphs carry AD-transposition dead primals
        (documented scope limit) — with GL-DEAD001 ignored the whole
        resnet fused train step is clean."""
        from incubator_mxnet_tpu import fuse, gluon
        from incubator_mxnet_tpu.gluon.model_zoo import vision
        net = vision.get_model("resnet18_v1", classes=10)
        net.initialize()
        x = mx.nd.random.uniform(shape=(2, 3, 32, 32))
        net(x)
        loss = gluon.loss.SoftmaxCrossEntropyLoss()
        step = fuse.make_fused_train_step(net, loss, "sgd",
                                          {"learning_rate": 0.1})
        fs = gl.lint_fn(step._step_fn, step.params, step.aux,
                        step.opt_state, x.data,
                        jnp.zeros((2,), jnp.float32),
                        jax.random.PRNGKey(0), where="fused",
                        config=gl.Config(ignore={"GL-DEAD001"}))
        assert fs == []
