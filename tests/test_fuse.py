"""Fused train step (fuse.py) — the performance path bench.py runs.

The whole-step program (forward + backward + optimizer + BN stat
updates, donated buffers) must match the eager Trainer path formula-
for-formula; these tests pin that equivalence per optimizer and the
BN-stat round-trip that bench.py's throughput claims rest on.
"""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd, gluon
from incubator_mxnet_tpu.fuse import make_fused_train_step
from incubator_mxnet_tpu.gluon import nn


def _net(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    # use_bias=False: BN exactly cancels a conv bias, so its gradient
    # is numerical noise and Adam would amplify path-dependent rounding
    # into full-size steps — not a real divergence, just ill-posed
    net.add(nn.Conv2D(4, 3, padding=1, in_channels=3, use_bias=False),
            nn.BatchNorm(in_channels=4), nn.Activation("relu"),
            nn.GlobalAvgPool2D(), nn.Flatten(), nn.Dense(5, in_units=4))
    net.initialize()
    net(nd.random.uniform(shape=(1, 3, 8, 8)))  # materialize shapes
    return net


def _data(bs=4, seed=1):
    rng = onp.random.RandomState(seed)
    x = nd.array(rng.rand(bs, 3, 8, 8).astype("f"))
    y = nd.array(rng.randint(0, 5, (bs,)).astype("i4"))
    return x, y


@pytest.mark.parametrize("opt,params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
    ("adamw", {"learning_rate": 0.01, "wd": 0.01}),
])
def test_fused_step_matches_eager_trainer(opt, params):
    """N fused steps == N eager record/backward/Trainer.step steps."""
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _data()

    net_e = _net()
    trainer = gluon.Trainer(net_e.collect_params(), opt, dict(params))
    for _ in range(3):
        with autograd.record():
            loss_e = loss_fn(net_e(x), y).mean()
        loss_e.backward()
        trainer.step(1)  # fused grads are means; batch already averaged

    net_f = _net()
    step = make_fused_train_step(net_f, loss_fn, opt, dict(params))
    for _ in range(3):
        loss_f = step(x, y)
    step.write_back()

    onp.testing.assert_allclose(float(loss_f), float(loss_e.asnumpy()),
                                rtol=1e-4)
    for (n1, p1), (n2, p2) in zip(net_e.collect_params().items(),
                                  net_f.collect_params().items()):
        onp.testing.assert_allclose(p1.data().asnumpy(),
                                    p2.data().asnumpy(), rtol=2e-3,
                                    atol=2e-4, err_msg=f"{opt}:{n1}")


def test_fused_step_updates_bn_stats():
    net = _net()
    step = make_fused_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1})
    x, y = _data()
    mean_before = {k: v.copy() for k, v in step.aux.items()
                   if "running_mean" in k or "moving_mean" in k}
    assert mean_before, "expected BN aux states in the fused step"
    for _ in range(2):
        step(x, y)
    for k, v0 in mean_before.items():
        assert float(abs(step.aux[k] - v0).sum()) > 0, k
    # write_back pushes aux into the Block
    step.write_back()
    for name, p in net.collect_params().items():
        if name in mean_before:
            onp.testing.assert_allclose(p.data().asnumpy(),
                                        onp.asarray(step.aux[name]))


def test_fused_step_loss_decreases():
    net = _net()
    step = make_fused_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "adam", {"learning_rate": 1e-2})
    x, y = _data(bs=8)
    first = float(step(x, y))
    last = first
    for _ in range(80):
        last = float(step(x, y))
        if last < first * 0.7:
            break
    assert last < first * 0.7, (first, last)


@pytest.mark.parametrize("remat", ["dots", "nothing"])
def test_fused_step_remat_matches_plain(remat):
    """Rematerialization must not change the computed update — only the
    schedule.  Same seed, same data: identical loss trajectory."""
    import incubator_mxnet_tpu as mx

    def run(r):
        mx.random.seed(0)
        net = _net()
        step = make_fused_train_step(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1, "momentum": 0.9}, remat=r)
        x, y = _data(bs=8)
        return [float(step(x, y)) for _ in range(3)]

    plain, rem = run(None), run(remat)
    assert plain == pytest.approx(rem, rel=1e-5), (plain, rem)


def test_fused_step_rejects_unknown_optimizer():
    net = _net()
    with pytest.raises(ValueError, match="fused step supports"):
        make_fused_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              "ftrl", {})
