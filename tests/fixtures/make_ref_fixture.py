"""Generate a reference-format MXNet checkpoint fixture.

Writes refmlp-symbol.json + refmlp-0000.params byte-for-byte in the
reference's on-disk formats, using ONLY the stdlib (no framework code)
— the .params layout follows src/ndarray/ndarray.cc:1679-1924 /
include/mxnet/tuple.h:731 / include/mxnet/base.h:145, and the symbol
JSON follows the nnvm graph JSON the reference's model.save_checkpoint
emits (python/mxnet/model.py:189).  Regenerate with:

    python tests/fixtures/make_ref_fixture.py
"""
import json
import os
import struct

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
PREFIX = os.path.join(HERE, "refmlp")


def tshape(shape):
    return struct.pack("<i", len(shape)) + \
        struct.pack(f"<{len(shape)}q", *shape)


def dense_record(arr):
    out = struct.pack("<I", 0xF993FAC9)       # NDARRAY_V2_MAGIC
    out += struct.pack("<i", 0)               # kDefaultStorage
    out += tshape(arr.shape)
    out += struct.pack("<ii", 1, 0)           # Context: kCPU, dev 0
    flag = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
            "int32": 4, "int8": 5, "int64": 6}[arr.dtype.name]
    out += struct.pack("<i", flag)
    out += arr.tobytes()
    return out


def row_sparse_record(values, indices, dense_shape):
    out = struct.pack("<I", 0xF993FAC9)
    out += struct.pack("<i", 1)               # kRowSparseStorage
    out += tshape(values.shape)               # storage shape
    out += tshape(dense_shape)                # logical shape
    out += struct.pack("<ii", 1, 0)
    out += struct.pack("<i", 0)               # values f32
    out += struct.pack("<i", 6)               # aux idx int64
    out += tshape(indices.shape)
    out += values.tobytes()
    out += indices.tobytes()
    return out


def main():
    rng = np.random.RandomState(42)
    w1 = rng.randn(16, 8).astype(np.float32)
    b1 = rng.randn(16).astype(np.float32)
    w2 = rng.randn(4, 16).astype(np.float32)
    b2 = rng.randn(4).astype(np.float32)
    emb = rng.randn(6, 8).astype(np.float32)   # row_sparse-stored weight
    emb_rows = np.array([0, 2, 5], dtype=np.int64)

    items = [
        ("arg:fc1_weight", dense_record(w1)),
        ("arg:fc1_bias", dense_record(b1)),
        ("arg:fc2_weight", dense_record(w2)),
        ("arg:fc2_bias", dense_record(b2)),
        ("arg:embed_weight",
         row_sparse_record(emb[emb_rows], emb_rows, emb.shape)),
    ]
    buf = struct.pack("<QQ", 0x112, 0)        # kMXAPINDArrayListMagic
    buf += struct.pack("<Q", len(items))
    for _, rec in items:
        buf += rec
    buf += struct.pack("<Q", len(items))
    for name, _ in items:
        nb = name.encode()
        buf += struct.pack("<Q", len(nb)) + nb
    with open(PREFIX + "-0000.params", "wb") as f:
        f.write(buf)
    np.savez(PREFIX + "-expected.npz", fc1_weight=w1, fc1_bias=b1,
             fc2_weight=w2, fc2_bias=b2, embed_weight_vals=emb[emb_rows],
             embed_weight_rows=emb_rows)

    # nnvm graph JSON exactly as the reference serializes an MLP
    graph = {
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "fc1_weight", "attrs":
                {"__dtype__": "0"}, "inputs": []},
            {"op": "null", "name": "fc1_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc1",
             "attrs": {"num_hidden": "16"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
            {"op": "Activation", "name": "relu1",
             "attrs": {"act_type": "relu"}, "inputs": [[3, 0, 0]]},
            {"op": "null", "name": "fc2_weight", "inputs": []},
            {"op": "null", "name": "fc2_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc2",
             "attrs": {"num_hidden": "4", "no_bias": "False"},
             "inputs": [[4, 0, 0], [5, 0, 0], [6, 0, 0]]},
            {"op": "softmax", "name": "out", "attrs": {"axis": "-1"},
             "inputs": [[7, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2, 5, 6],
        "node_row_ptr": list(range(10)),
        "heads": [[8, 0, 0]],
        "attrs": {"mxnet_version": ["int", 10600]},
    }
    with open(PREFIX + "-symbol.json", "w") as f:
        json.dump(graph, f, indent=2)
    print("wrote", PREFIX + "-{symbol.json,0000.params,expected.npz}")


if __name__ == "__main__":
    main()
