"""CPU↔TPU check_consistency battery (SURVEY §4: the cross-backend
oracle, reference test_utils.py:1428 run with ctx_list=[cpu, gpu]).

Runs a small subset of scripts/tpu_consistency.py in a subprocess with
the accelerator platform enabled; skips when no accelerator is
reachable or the axon tunnel is wedged (first device op hangs — the
subprocess timeout is the only safe guard).  The full 279-op battery
runs via scripts/chip_queue.sh; this test proves the harness against a
live chip without monopolizing it.
"""
import json
import os
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SUBSET = "relu,dot,Convolution,BatchNorm,softmax,LayerNorm,take,topk"


def test_cpu_tpu_consistency_battery():
    env = dict(os.environ)
    # APPEND to PYTHONPATH: the axon plugin registers via a
    # sitecustomize on the existing path (/root/.axon_site); replacing
    # the variable would silently de-register the accelerator platform
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the axon plugin only registers under JAX_PLATFORMS=axon exactly;
    # the host CPU backend stays reachable via backend="cpu" (the same
    # split bench.py uses to stage setup off-chip)
    env["JAX_PLATFORMS"] = "axon"
    env.pop("XLA_FLAGS", None)
    out_path = os.path.join(tempfile.mkdtemp(), "consistency.json")
    try:
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "tpu_consistency.py"),
             "--ops", SUBSET, "--deadline", "360", "--out", out_path],
            capture_output=True, text=True, timeout=420, env=env)
    except subprocess.TimeoutExpired:
        pytest.skip("accelerator tunnel unresponsive (wedged) — "
                    "consistency battery needs a live chip")
    out = proc.stdout
    if "no accelerator visible" in out:
        pytest.skip("no accelerator visible to JAX")
    if ("Unable to initialize backend" in proc.stderr
            or "Unable to initialize backend 'axon'" in out):
        # the axon plugin only registers when its tunnel answers at
        # import; a wedged tunnel surfaces as an unknown backend.  The
        # init failure can also land on stdout: the harness folds a
        # child's crash traceback into its RESULT line, so a child that
        # died at backend init (before touching any op) shows up there
        pytest.skip("accelerator plugin failed to register (tunnel down)")
    # wedge → skip; crash → FAIL (the parent labels a finished-but-
    # silent child "child crashed", which must stay red).  The round-5
    # harness distinguishes them itself: a chunk timeout triggers a
    # liveness re-probe, and a dead chip aborts the battery with the
    # ops marked UNKNOWN (retried on resume) instead of fake FAILs.
    if "chip wedged — aborting battery" in out:
        pytest.skip("chip wedged mid-battery (liveness re-probe failed)")
    if out.count("no result (hang/timeout") == len(SUBSET.split(",")):
        pytest.skip("chip never answered inside the chunk budget "
                    "(wedged tunnel)")
    assert proc.returncode == 0, (out[-1500:], proc.stderr[-500:])
    with open(out_path) as f:
        doc = json.load(f)
    assert doc["failed"] == 0 and doc["passed"] >= 1, doc
