"""contrib.text tests (reference tests/python/unittest/test_contrib_text)."""
import collections

import numpy as onp

from incubator_mxnet_tpu.contrib import text


def test_count_tokens():
    c = text.utils.count_tokens_from_str("a b b c\nc c d", to_lower=False)
    assert c == collections.Counter({"c": 3, "b": 2, "a": 1, "d": 1})


def test_vocabulary_ordering_and_lookup():
    counter = collections.Counter({"the": 10, "cat": 5, "sat": 5, "rare": 1})
    v = text.Vocabulary(counter, min_freq=2, reserved_tokens=["<pad>"])
    assert v.idx_to_token[0] == "<unk>"
    assert v.idx_to_token[1] == "<pad>"
    assert v.to_indices("the") == 2       # most frequent first
    assert v.to_indices("rare") == 0      # below min_freq -> unk
    assert v.to_tokens([2]) == ["the"]
    assert len(v) == 5


def test_custom_embedding_roundtrip(tmp_path):
    p = tmp_path / "emb.txt"
    p.write_text("cat 1.0 2.0 3.0\ndog 4.0 5.0 6.0\n")
    emb = text.embedding.CustomEmbedding(str(p))
    assert emb.vec_len == 3 and len(emb) == 3
    onp.testing.assert_array_equal(
        emb.get_vecs_by_tokens("dog").asnumpy(), [4.0, 5.0, 6.0])
    out = emb.get_vecs_by_tokens(["cat", "unknown!"])
    onp.testing.assert_array_equal(out.asnumpy()[1], onp.zeros(3))
    emb.update_token_vectors("cat", __import__(
        "incubator_mxnet_tpu").nd.array([[9.0, 9.0, 9.0]]))
    onp.testing.assert_array_equal(
        emb.get_vecs_by_tokens("cat").asnumpy(), [9.0, 9.0, 9.0])


def test_composite_embedding(tmp_path):
    p1 = tmp_path / "a.txt"; p1.write_text("cat 1.0 2.0\ndog 3.0 4.0\n")
    p2 = tmp_path / "b.txt"; p2.write_text("cat 7.0\n")
    v = text.Vocabulary(collections.Counter({"cat": 2, "dog": 1}))
    comp = text.embedding.CompositeEmbedding(
        v, [text.embedding.CustomEmbedding(str(p1)),
            text.embedding.CustomEmbedding(str(p2))])
    vec = comp.get_vecs_by_tokens("cat").asnumpy()
    onp.testing.assert_array_equal(vec, [1.0, 2.0, 7.0])
    assert comp.get_vecs_by_tokens("dog").asnumpy()[2] == 0.0


def test_custom_embedding_skips_fasttext_header(tmp_path):
    p = tmp_path / "ft.vec"
    p.write_text("2 3\ncat 1.0 2.0 3.0\ndog 4.0 5.0 6.0\n")
    emb = text.embedding.CustomEmbedding(str(p))
    assert emb.vec_len == 3 and len(emb) == 3
    onp.testing.assert_array_equal(
        emb.get_vecs_by_tokens("cat").asnumpy(), [1.0, 2.0, 3.0])


def test_custom_embedding_ragged_rows_error(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("cat 1.0 2.0 3.0\ndog 4.0 5.0\n")
    import pytest as _pytest
    with _pytest.raises(ValueError, match="inconsistent"):
        text.embedding.CustomEmbedding(str(p))
