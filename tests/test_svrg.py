"""SVRG optimization tests (reference contrib/svrg_optimization +
tests/python/unittest/test_contrib_svrg_*)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, sym
from incubator_mxnet_tpu.contrib.svrg_optimization import (SVRGModule,
                                                           SVRGOptimizer)
from incubator_mxnet_tpu.io import NDArrayIter


def _linreg_module(update_freq=2):
    data = sym.var("data")
    out = sym.FullyConnected(data, num_hidden=1, no_bias=True, name="fc")
    out = sym.LinearRegressionOutput(out, name="softmax")
    return SVRGModule(out, data_names=("data",),
                      label_names=("softmax_label",),
                      update_freq=update_freq)


def _toy_data(n=64, d=4, seed=0):
    rng = onp.random.RandomState(seed)
    x = rng.randn(n, d).astype(onp.float32)
    w = rng.randn(d, 1).astype(onp.float32)
    y = (x @ w).ravel()
    return x, y, w


def test_svrg_single_batch_equals_sgd():
    # with the whole dataset in ONE batch, mu == g(w_snap) on that batch,
    # so the variance-reduced gradient equals the plain gradient and the
    # trajectories must match exactly
    x, y, _ = _toy_data(n=16)
    def run(module_cls):
        if module_cls is SVRGModule:
            mod = _linreg_module(update_freq=1)
        else:
            from incubator_mxnet_tpu.module import Module
            data = sym.var("data")
            out = sym.FullyConnected(data, num_hidden=1, no_bias=True,
                                     name="fc")
            out = sym.LinearRegressionOutput(out, name="softmax")
            mod = Module(out, data_names=("data",),
                         label_names=("softmax_label",))
        it = NDArrayIter(x, y, batch_size=16)
        first = next(iter(it)); it.reset()
        mod.bind(data_shapes=[("data", first.data[0].shape)],
                 label_shapes=[("softmax_label", first.label[0].shape)],
                 for_training=True)
        mx.random.seed(7)
        mod.init_params()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params=(("learning_rate", 0.05),))
        if module_cls is SVRGModule:
            mod.update_full_grads(it)
        for _ in range(3):
            it.reset()
            for batch in it:
                mod.forward(batch, is_train=True)
                mod.backward()
                if module_cls is SVRGModule:
                    mod.update_svrg()
                else:
                    mod.update()
        return mod.get_params()[0]["fc_weight"].asnumpy()

    w_svrg = run(SVRGModule)
    from incubator_mxnet_tpu.module import Module
    w_sgd = run(Module)
    onp.testing.assert_allclose(w_svrg, w_sgd, rtol=1e-5, atol=1e-6)


def test_svrg_fit_converges():
    x, y, w_true = _toy_data(n=64)
    mod = _linreg_module(update_freq=2)
    it = NDArrayIter(x, y, batch_size=16, shuffle=False)
    # lr set for the reference's rescale_grad=1/batch convention
    # (module.py:506-518): per-sample-mean gradients need a larger step
    mod.fit(it, eval_metric="mse", num_epoch=30,
            optimizer_params=(("learning_rate", 0.4),))
    w = mod.get_params()[0]["fc_weight"].asnumpy().ravel()
    onp.testing.assert_allclose(w, w_true.ravel(), rtol=0.05, atol=0.05)


def test_svrg_optimizer_delegates():
    opt = SVRGOptimizer("sgd", learning_rate=0.1)
    w = nd.ones((3,))
    g = nd.ones((3,))
    gs = nd.zeros((3,))
    mu = nd.zeros((3,))
    opt.update_svrg(0, w, g, gs, mu, opt.create_state(0, w))
    onp.testing.assert_allclose(w.asnumpy(), 0.9 * onp.ones(3), rtol=1e-6)


def test_svrg_fit_honors_optimizer_params_and_metric():
    x, y, _ = _toy_data(n=32)
    mod = _linreg_module(update_freq=1)
    it = NDArrayIter(x, y, batch_size=16)
    mx.random.seed(11)
    m = mod.fit(it, eval_metric="mse", num_epoch=2, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.0),))
    # lr=0 -> weights must not move; proves optimizer_params reach the
    # optimizer instead of being swallowed (round-3 review regression)
    w0 = mod.get_params()[0]["fc_weight"].asnumpy()
    mod2 = _linreg_module(update_freq=1)
    it.reset()
    mx.random.seed(11)
    mod2.fit(it, eval_metric="mse", num_epoch=2, optimizer="sgd",
             optimizer_params=(("learning_rate", 0.0),))
    onp.testing.assert_allclose(
        w0, mod2.get_params()[0]["fc_weight"].asnumpy(), rtol=1e-6)
    assert onp.isfinite(m.get()[1])
