"""HA router tier tests (ISSUE 17): leased membership, consistent-hash
affinity, forward hops, crash takeover, and the restore-race fix.

The contract under test (docs/serving.md "Router high availability"):
N routers share one view of the fleet and of session ownership through
a leased membership store; a router crash mid-stream re-homes its
session affinities to the survivors, which resume the streams through
the SAME snapshot-restore path a replica death uses (re-base visible
in ``session_steps``, continuation bitwise, zero chunk resends).  A
single-router deployment is bit-for-bit unaffected: no HA thread, no
lease traffic, pinned bare shapes.  The ``routerha`` CI stage re-runs
this file under the pinned seeded chaos spec.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as onp
import pytest

from incubator_mxnet_tpu import flightrec
from incubator_mxnet_tpu.error import (RouterForwardError,
                                       SessionLostError)
from incubator_mxnet_tpu.serving import ReplicaFleet, FleetRouter
from incubator_mxnet_tpu.serving import routerha
from incubator_mxnet_tpu.serving.routerha import (FileLeaseStore,
                                                  HashRing,
                                                  MemoryLeaseStore,
                                                  RouterHA,
                                                  parse_forward_header)
from incubator_mxnet_tpu.serving.sessions import (SessionManager,
                                                  toy_decoder)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
POSTMORTEM = os.path.join(REPO, "tools", "postmortem.py")

DIM = 8
SPEC = "toy_decoder:dim=8,max_len=64"


def _x(v=0.1):
    return (onp.full(DIM, v, onp.float32),)


_REF = {"mgr": None, "n": 0}


def _ref_chunks(n_steps, v=0.1):
    """Unbroken single-session reference run (same registry spec)."""
    mgr = _REF["mgr"]
    if mgr is None:
        mgr = _REF["mgr"] = SessionManager(
            "ref", toy_decoder(dim=DIM, max_len=64), buckets=[1],
            warmup=False)
    _REF["n"] += 1
    sid = f"ref{_REF['n']}"
    mgr.create(sid)
    chunks, _ = mgr.step(sid, _x(v), steps=n_steps)
    mgr.close(sid)
    return [onp.asarray(c[0]) for c in chunks]


def _assert_continuation(cont_chunks, timing, v=0.1):
    """Re-base-aware bitwise check: wherever the resumed session
    continued from (``session_steps`` makes the re-base VISIBLE), the
    continuation equals an unbroken run from that step — and never
    re-sends earlier chunks."""
    base = timing["session_steps"] - timing["steps"]
    assert base >= 0
    ref = _ref_chunks(base + timing["steps"], v=v)
    assert len(cont_chunks) == timing["steps"]
    for got, want in zip(cont_chunks, ref[base:]):
        assert (onp.asarray(got[0]) == want).all(), \
            f"continuation diverged from unbroken run (base {base})"
    return base


def _mk_router(tmp_path, rid, store, lease_ttl_s=0.5):
    fleet = ReplicaFleet({}, n=1, backend="thread", warmup=False,
                         probe_ms=60000.0, buckets=[1, 2],
                         session_models={"dec": SPEC},
                         session_dir=str(tmp_path / "snaps")).spawn()
    for r in fleet.replicas:
        r.sessions.get("dec").snapshot_steps = 2
    ha = RouterHA(rid, store, lease_ttl_s=lease_ttl_s)
    return FleetRouter(fleet, ha=ha), ha


def _await_durable_snapshot(tmp_path, sid, nudge=None, deadline_s=20):
    d = tmp_path / "snaps" / "dec" / sid
    end = time.monotonic() + deadline_s
    last_nudge = 0.0
    while time.monotonic() < end:
        if d.is_dir() and any((p / "index.json").exists()
                              for p in d.glob("step_*")):
            return
        now = time.monotonic()
        if nudge is not None and now - last_nudge > 0.5:
            last_nudge = now
            nudge()
        time.sleep(0.05)
    raise AssertionError(f"no durable snapshot for {sid!r}")


# ---------------------------------------------------------------------------
# forward-header hygiene: garbled input is ignored, never an error
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("raw", [
    None, "", "garbage", ";", "x;y", "-1;r1", "1e9;r1", "9999;r1",
    "1;" + "v" * 600, "NaN;a,b", "2",  # bare hops, no via: fine
])
def test_parse_forward_header_garbled_or_edge(raw):
    hops, via = parse_forward_header(raw)
    assert isinstance(hops, int) and hops >= 0
    assert isinstance(via, tuple)
    if raw in (None, "", "garbage", ";", "x;y", "-1;r1", "9999;r1",
               "NaN;a,b") or (raw and len(raw) > 512):
        assert (hops, via) == (0, ())


def test_forward_header_roundtrip():
    raw = routerha.forward_header_value(2, ("rA", "rB"))
    assert parse_forward_header(raw) == (2, ("rA", "rB"))


# ---------------------------------------------------------------------------
# consistent-hash ring: the ~K/N movement bound
# ---------------------------------------------------------------------------

def test_hash_ring_removal_moves_only_dead_members_keys():
    members = [f"router-{i}" for i in range(4)]
    ring = HashRing(members)
    keys = [f"sid-{i:04d}" for i in range(2000)]
    before = {k: ring.owner(k) for k in keys}
    # removal: every key NOT owned by the removed member keeps its
    # owner exactly (the defining consistent-hashing property)
    ring3 = HashRing([m for m in members if m != "router-2"])
    for k in keys:
        if before[k] != "router-2":
            assert ring3.owner(k) == before[k]
    moved = sum(1 for k in keys if before[k] == "router-2")
    # the dead member's share is ~K/N; allow 2x slack on 64 vnodes
    assert moved <= 2 * len(keys) / len(members)


def test_hash_ring_addition_moves_about_k_over_n():
    members = [f"router-{i}" for i in range(4)]
    ring = HashRing(members)
    keys = [f"sid-{i:04d}" for i in range(2000)]
    before = {k: ring.owner(k) for k in keys}
    ring5 = HashRing(members + ["router-new"])
    moved = sum(1 for k in keys if ring5.owner(k) != before[k])
    # only keys claimed by the newcomer move, ~K/(N+1); 2x slack
    assert 0 < moved <= 2 * len(keys) / (len(members) + 1)
    for k in keys:
        if ring5.owner(k) != before[k]:
            assert ring5.owner(k) == "router-new"


def test_hash_ring_stable_across_instances_and_empty():
    a = HashRing(["r1", "r2"])
    b = HashRing(["r2", "r1"])   # order-independent
    for i in range(100):
        assert a.owner(f"s{i}") == b.owner(f"s{i}")
    assert HashRing([]).owner("anything") is None


# ---------------------------------------------------------------------------
# lease stores + membership lifecycle
# ---------------------------------------------------------------------------

def test_file_lease_store_roundtrip_and_torn_reads(tmp_path):
    store = FileLeaseStore(tmp_path / "ha")
    store.publish({"router_id": "rA", "addr": "127.0.0.1:1",
                   "deadline": time.monotonic() + 5})
    store.publish({"router_id": "r/B", "deadline": 0})  # sanitized
    # a torn/garbage file is skipped, never a crash
    (tmp_path / "ha" / "torn.lease.json").write_text("{not json")
    (tmp_path / "ha" / "noise.txt").write_text("ignored")
    entries = store.read_all()
    assert set(entries) == {"rA", "r/B"}
    store.remove("rA")
    store.remove("rA")           # idempotent
    assert set(store.read_all()) == {"r/B"}


def test_lease_expire_and_rejoin_announced_once(tmp_path):
    store = MemoryLeaseStore()
    a = RouterHA("rA", store, lease_ttl_s=0.2)
    b = RouterHA("rB", store, lease_ttl_s=5.0)
    a.beat_once()
    b.beat_once()
    assert set(b.members(refresh=True)) == {"rA", "rB"}
    time.sleep(0.3)              # rA misses its beats
    assert set(b.members(refresh=True)) == {"rB"}
    b.sweep_once()
    assert "rA" in b._announced_dead
    assert b.describe()["expired"] == ["rA"]
    # rejoin with the SAME id clears the obituary: a later death is
    # announced again
    a.beat_once()
    b.sweep_once()
    assert "rA" not in b._announced_dead
    assert set(b.members(refresh=True)) == {"rA", "rB"}


def test_beat_failure_is_typed_and_counted(tmp_path):
    class BrokenStore(MemoryLeaseStore):
        def publish(self, entry):
            raise OSError("disk gone")

    ha = RouterHA("rA", BrokenStore(), lease_ttl_s=1.0)
    from incubator_mxnet_tpu.error import RouterLeaseError
    with pytest.raises(RouterLeaseError):
        ha.beat_once()
    assert isinstance(RouterLeaseError("x"), ConnectionError)
    assert ha.describe()["counters"]["beat_failures"] == 1


# ---------------------------------------------------------------------------
# in-process takeover: the tentpole invariant without subprocesses
# ---------------------------------------------------------------------------

def test_router_crash_takeover_resumes_bitwise(tmp_path):
    store = MemoryLeaseStore()
    rA, haA = _mk_router(tmp_path, "rA", store, lease_ttl_s=0.4)
    rB, haB = _mk_router(tmp_path, "rB", store, lease_ttl_s=5.0)
    try:
        haA.beat_once()
        haB.beat_once()
        sid = rA.session_create("dec", "tko1")["session_id"]
        rA.session_step("dec", sid, _x(), steps=6)
        _await_durable_snapshot(
            tmp_path, sid,
            nudge=lambda: rA.session_step("dec", sid, _x(), steps=1))
        haA.beat_once()          # registry with sid + fresh deadline
        # "crash": rA simply stops beating; its lease ages out
        time.sleep(0.6)
        adopted = haB.sweep_once()
        assert adopted == 1
        cont, t2 = rB.session_step("dec", sid, _x(), steps=3)
        base = _assert_continuation(cont, t2)
        assert base >= 2         # resumed FROM a snapshot, re-based
        assert rB.metrics.snapshot()["migrations"] >= 1
        d = haB.describe()
        assert d["counters"]["takeovers"] == 1
        assert d["counters"]["adopted_sessions"] == 1
        # close works on the adopted session too
        assert rB.session_close("dec", sid)["closed"] is True
    finally:
        rB.shutdown()
        rA.shutdown()


def test_request_path_claim_beats_the_sweep(tmp_path):
    """A step can arrive for a dead router's sid BEFORE any periodic
    sweep ran — the request path itself claims the orphan (ring-owner
    gated) instead of 404ing."""
    store = MemoryLeaseStore()
    rA, haA = _mk_router(tmp_path, "rA", store, lease_ttl_s=0.3)
    rB, haB = _mk_router(tmp_path, "rB", store, lease_ttl_s=5.0)
    try:
        haB.beat_once()
        # find a sid the SURVIVOR will ring-own once rA is dead (the
        # ring then only has rB, so any sid works — but pin the claim
        # gate too: with rA alive the ring may disagree)
        haA.beat_once()
        sid = rA.session_create("dec", "claim1")["session_id"]
        rA.session_step("dec", sid, _x(), steps=4)
        _await_durable_snapshot(
            tmp_path, sid,
            nudge=lambda: rA.session_step("dec", sid, _x(), steps=1))
        haA.beat_once()
        time.sleep(0.5)          # rA's lease expires; NO sweep on rB
        cont, t2 = rB.session_step("dec", sid, _x(), steps=2)
        _assert_continuation(cont, t2)
    finally:
        rB.shutdown()
        rA.shutdown()


def test_clean_stop_leaves_membership(tmp_path):
    store = MemoryLeaseStore()
    flightrec.configure(ring=256, proc="test")
    try:
        ha = RouterHA("rZ", store, lease_ttl_s=5.0)
        ha.beat_once()
        assert "rZ" in store.read_all()
        ha.stop(leave=True)
        assert "rZ" not in store.read_all()
        names = [e.name for e in flightrec.events()]
        assert "router.exited" in names
        assert "router.lease.acquired" in names
    finally:
        flightrec.reset()


# ---------------------------------------------------------------------------
# HTTP tier: forward hop, garbled headers, loop bound, shapes
# ---------------------------------------------------------------------------

def _post(port, path, body, headers=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


@pytest.fixture
def http_pair(tmp_path):
    store = MemoryLeaseStore()
    rA, haA = _mk_router(tmp_path, "rA", store, lease_ttl_s=5.0)
    rB, haB = _mk_router(tmp_path, "rB", store, lease_ttl_s=5.0)
    pa = rA.start()
    pb = rB.start()
    yield rA, haA, pa, rB, haB, pb
    rB.shutdown()
    rA.shutdown()


def test_forward_hop_routes_to_owner(http_pair):
    rA, haA, pa, rB, haB, pb = http_pair
    code, d = _post(pa, "/v1/sessions/dec:create", {"session_id": "f1"})
    assert code == 200
    # the NON-owning router serves the step by proxying to the owner
    code, d = _post(pb, "/v1/sessions/dec/f1:step",
                    {"inputs": [_x()[0].tolist()], "steps": 3})
    assert code == 200 and d["steps"] == 3
    assert d["timing"]["session_steps"] == 3
    assert haB.describe()["counters"]["forwards"] >= 1
    # streaming forwards too, chunk for chunk
    req = urllib.request.Request(
        f"http://127.0.0.1:{pb}/v1/sessions/dec/f1:step",
        data=json.dumps({"inputs": [_x()[0].tolist()], "steps": 2,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    lines = []
    with urllib.request.urlopen(req, timeout=60) as resp:
        for line in resp:
            if line.strip():
                lines.append(json.loads(line))
    assert lines[-1].get("done") is True
    assert sum(1 for ln in lines if "outputs" in ln) == 2
    # the owner served 5 steps total, all one session
    assert rA._session_homes["f1"][1] is not None


@pytest.mark.parametrize("raw", ["garbage", ";;;", "-5;rQ",
                                 "1;unknown-router", "NaN;x,y,z"])
def test_garbled_forward_headers_ignored_never_500(http_pair, raw):
    rA, haA, pa, rB, haB, pb = http_pair
    _post(pa, "/v1/sessions/dec:create", {"session_id": "g1"})
    # garbled hop headers on BOTH the owner and the forwarder parse as
    # hop 0 and the request just works — never a 500
    for port in (pa, pb):
        code, d = _post(port, "/v1/sessions/dec/g1:step",
                        {"inputs": [_x()[0].tolist()], "steps": 1},
                        headers={routerha.HEADER: raw})
        assert code == 200


def test_forward_loop_bounded_typed_508(http_pair):
    rA, haA, pa, rB, haB, pb = http_pair
    _post(pa, "/v1/sessions/dec:create", {"session_id": "loop1"})
    # a request arriving at the non-owner with the hop budget already
    # spent must die typed (508), not hop forever
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(pb, "/v1/sessions/dec/loop1:step",
              {"inputs": [_x()[0].tolist()], "steps": 1},
              headers={routerha.HEADER:
                       routerha.forward_header_value(
                           haB.forward_hops, ("rX", "rY"))})
    assert ei.value.code == 508
    payload = json.loads(ei.value.read())
    assert payload["error"] == "RouterForwardError"
    # the self-in-via loop check trips even below the hop budget
    with pytest.raises(urllib.error.HTTPError) as ei2:
        _post(pb, "/v1/sessions/dec/loop1:step",
              {"inputs": [_x()[0].tolist()], "steps": 1},
              headers={routerha.HEADER: "1;rB"})
    assert ei2.value.code == 508


def test_router_ha_block_shape_and_healthz(http_pair):
    rA, haA, pa, rB, haB, pb = http_pair
    with urllib.request.urlopen(
            f"http://127.0.0.1:{pa}/healthz", timeout=30) as resp:
        health = json.loads(resp.read())
    blk = health["router_ha"]
    assert set(blk) == {"router_id", "addr", "lease_ttl_s",
                        "forward_hops", "leased", "lease_remaining_s",
                        "peers", "expired", "counters"}
    assert blk["router_id"] == "rA" and blk["leased"] is True
    assert set(blk["peers"]) == {"rB"}
    assert blk["peers"]["rB"]["fleet"]["replicas"] == 1
    assert set(blk["counters"]) == {"beats", "beat_failures",
                                    "takeovers", "adopted_sessions",
                                    "forwards"}
    assert rA.describe()["router_ha"]["router_id"] == "rA"


def test_bare_router_is_bitwise_unaffected(tmp_path, monkeypatch):
    """No HA configured ⇒ no HA object, no HA thread, no lease
    traffic, and the PINNED bare shapes (the PR 12/14/15 additive
    discipline)."""
    monkeypatch.delenv("MXNET_SERVING_ROUTER_HA_DIR", raising=False)
    fleet = ReplicaFleet({}, n=1, backend="thread", warmup=False,
                         probe_ms=60000.0,
                         session_models={"dec": SPEC}).spawn()
    router = FleetRouter(fleet)
    try:
        assert router.ha is None
        assert fleet.membership is None
        router.start()
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("router-ha-")]
        _, health = router.health()
        assert "router_ha" not in health
        assert "router_ha" not in router.describe()
    finally:
        router.shutdown()


def test_from_env_wiring(tmp_path, monkeypatch):
    assert routerha.from_env() is None
    monkeypatch.setenv("MXNET_SERVING_ROUTER_HA_DIR",
                       str(tmp_path / "ha"))
    monkeypatch.setenv("MXNET_SERVING_ROUTER_ID", "env-r1")
    monkeypatch.setenv("MXNET_SERVING_ROUTER_LEASE_TTL_S", "1.5")
    monkeypatch.setenv("MXNET_SERVING_ROUTER_FORWARD_HOPS", "5")
    ha = routerha.from_env(host="127.0.0.1", port=80)
    assert ha.router_id == "env-r1"
    assert ha.lease_ttl_s == 1.5
    assert ha.forward_hops == 5
    assert ha.addr == "127.0.0.1:80"
    assert isinstance(ha.store, FileLeaseStore)


# ---------------------------------------------------------------------------
# the known flake, dead: restore vs the async snapshotter
# ---------------------------------------------------------------------------

def test_restore_race_with_async_snapshotter_20_of_20(tmp_path):
    """ISSUE 17 satellite: a restore that looks at the snapshot dir
    while the source's async snapshotter is mid-publish (staging dir
    present, committed rename an instant away) must WAIT for the
    commit, not fail the adopt.  The interleaving is forced 20/20
    times: the committed step dir is renamed to its ``.tmp`` staging
    name, the restore starts, and the rename is undone mid-restore."""
    snap = tmp_path / "snaps"
    # snapshot_steps is large on purpose: the ONLY snapshot is the
    # explicit synchronous one below, so the forced rename owns the
    # staging-dir name outright (no background writer racing the race)
    src = SessionManager("dec", toy_decoder(dim=DIM, max_len=64),
                         buckets=[1], warmup=False,
                         snapshot_dir=str(snap), snapshot_steps=100)
    dst = SessionManager("dec", toy_decoder(dim=DIM, max_len=64),
                         buckets=[1], warmup=False,
                         snapshot_dir=str(snap), snapshot_steps=100)
    for i in range(20):
        sid = f"race{i}"
        src.create(sid)
        src.step(sid, _x(), steps=4)
        src.snapshot_all(sync=True)
        d = snap / "dec" / sid
        steps_dirs = sorted(p for p in d.glob("step_*")
                            if not p.name.endswith(".tmp"))
        assert steps_dirs, f"trial {i}: no committed snapshot"
        committed = steps_dirs[-1]
        staged = committed.with_name(committed.name + ".tmp")
        committed.rename(staged)          # snapshotter "mid-publish"

        result = {}

        def adopt():
            try:
                result["info"] = dst.restore(sid)
            except Exception as e:  # noqa: BLE001 - recorded for the assert
                result["err"] = e

        t = threading.Thread(target=adopt)
        t.start()
        time.sleep(0.15)                  # restore is inside the race
        staged.rename(committed)          # the "atomic publish" lands
        t.join(timeout=30)
        assert not t.is_alive(), f"trial {i}: restore hung"
        assert "err" not in result, \
            f"trial {i}: restore failed under the race: " \
            f"{result.get('err')!r}"
        assert result["info"]["steps"] >= 2
        dst.close(sid)
        src.close(sid)
    # the race actually happened every trial (first look always saw
    # only the staging dir) — retries prove the fix engaged, the flake
    # did not just get lucky
    assert dst._counters["restore_retries"] >= 20


def test_restore_without_race_evidence_fails_fast(tmp_path):
    """No staging dir, no snapshot ⇒ the typed failure stays IMMEDIATE
    (the retry budget must not add latency to hopeless restores)."""
    snap = tmp_path / "snaps"
    mgr = SessionManager("dec", toy_decoder(dim=DIM, max_len=64),
                         buckets=[1], warmup=False,
                         snapshot_dir=str(snap))
    (snap / "dec" / "ghost").mkdir(parents=True)
    t0 = time.monotonic()
    with pytest.raises(SessionLostError):
        mgr.restore("ghost")
    assert time.monotonic() - t0 < SessionManager.RESTORE_RACE_WAIT_S


# ---------------------------------------------------------------------------
# THE acceptance chaos proof: SIGKILL one of 2 subprocess routers
# mid-stream (slow; the `routerha` CI stage and the `slow` stage run
# it, tier-1 skips it — same split as the replica-kill e2e)
# ---------------------------------------------------------------------------

def _spawn_router(tmp_path, rid, env):
    proc = subprocess.Popen(
        [sys.executable, "-m", "incubator_mxnet_tpu.serving.router",
         "--session-model", f"dec={SPEC}",
         "--session-dir", str(tmp_path / "snaps"),
         "--backend", "thread", "--replicas", "1",
         "--host", "127.0.0.1", "--port", "0", "--no-warmup",
         "--ha-dir", str(tmp_path / "ha"), "--router-id", rid,
         "--lease-ttl", "1.0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, start_new_session=True, cwd=REPO)
    port = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(f"router {rid} died at startup")
        if "routing on" in line:
            port = int(line.rsplit(":", 1)[1].split()[0])
            break
    assert port, f"router {rid} never reported its port"
    return proc, port


def _post_retry(port, path, body, deadline_s=30, headers=None):
    """POST with bounded retry over the takeover window: 503s and
    refused sockets are the EXPECTED transient while the dead
    router's lease ages out — a lost stream is anything that still
    fails past the deadline."""
    end = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < end:
        try:
            return _post(port, path, body, headers=headers,
                         timeout=60)
        except urllib.error.HTTPError as e:
            last = e
            if e.code not in (503,):
                raise
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            last = e
        time.sleep(0.25)
    raise AssertionError(f"request did not land within {deadline_s}s: "
                         f"{last!r}")


@pytest.mark.slow
def test_sigkill_router_midstream_takeover_postmortem(tmp_path):
    """ISSUE 17 acceptance: SIGKILL one of 2 subprocess routers with
    an active mid-stream session.  The survivor must adopt the dead
    router's session (lease expiry → takeover), resume it bitwise
    from its snapshot (re-base visible, zero resends), keep serving
    fresh requests, and `postmortem --gate` must reconstruct
    ``lease.expired → takeover.started → session.restored`` from the
    survivor's flight dump."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                "MXNET_SERVING_SESSION_SNAPSHOT_STEPS": "2",
                "MXNET_FLIGHT_RING": "2048"})
    # the CI stage's spec targets the in-process battery; the
    # subprocess routers get exactly the faults this test stages
    env.pop("MXNET_FAULT_SPEC", None)
    # rA's chunk writes are slowed so the 64-step stream is genuinely
    # in flight when the SIGKILL lands — without the delay the toy
    # decode drains into the socket buffer before the signal arrives
    env_a = dict(env)
    env_a["MXNET_FAULT_SPEC"] = "serving.stream_write:delay:ms=100"
    pa = pb = None
    try:
        pa, port_a = _spawn_router(tmp_path, "rA", env_a)
        pb, port_b = _spawn_router(tmp_path, "rB", env)

        code, d = _post_retry(port_a, "/v1/sessions/dec:create",
                              {"session_id": "kill1"}, deadline_s=60)
        assert code == 200
        code, d = _post(port_a, "/v1/sessions/dec/kill1:step",
                        {"inputs": [_x()[0].tolist()], "steps": 6},
                        timeout=120)
        assert code == 200 and d["timing"]["session_steps"] == 6
        _await_durable_snapshot(
            tmp_path, "kill1",
            nudge=lambda: _post(port_a, "/v1/sessions/dec/kill1:step",
                                {"inputs": [_x()[0].tolist()],
                                 "steps": 1}, timeout=60))

        # mid-stream: a long streaming step is in flight on rA when it
        # dies — the client sees the break VISIBLY, never a hang and
        # never a stream that pretends to complete (the ``done``
        # terminator line is the completeness signal; a SIGKILLed
        # router can only truncate before it)
        stream = {"lines": []}

        def stream_and_die():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port_a}/v1/sessions/dec/"
                f"kill1:step",
                data=json.dumps({"inputs": [_x()[0].tolist()],
                                 "steps": 40,
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    for n, line in enumerate(resp):
                        if line.strip():
                            stream["lines"].append(json.loads(line))
                        if n == 1:
                            os.killpg(pa.pid, signal.SIGKILL)
            except Exception as e:  # noqa: BLE001 - a reset IS a visible break
                stream["err"] = e

        t = threading.Thread(target=stream_and_die)
        t.start()
        t.join(timeout=90)
        assert not t.is_alive(), "stream client hung through the kill"
        assert "err" in stream or (
            len(stream["lines"]) < 40
            and not any(ln.get("done") for ln in stream["lines"])), \
            "killed router's stream must break visibly (truncated " \
            "before its done line), not complete"
        pa.wait(timeout=30)

        # ... and the SURVIVOR resumes the session bitwise from its
        # last durable snapshot once rA's lease ages out (zero lost
        # streams: the retry window IS the takeover window)
        code, d = _post_retry(port_b, "/v1/sessions/dec/kill1:step",
                              {"inputs": [_x()[0].tolist()],
                               "steps": 3}, deadline_s=45)
        assert code == 200
        timing = d["timing"]
        base = timing["session_steps"] - d["steps"]
        assert base >= 2, "resume must re-base from a snapshot"
        ref = _ref_chunks(base + d["steps"])
        for got, want in zip(d["outputs"], ref[base:]):
            assert (onp.asarray(got[0]) == want).all(), \
                "takeover continuation diverged from unbroken run"

        # fresh requests keep landing on the survivor
        code, d2 = _post_retry(port_b, "/v1/sessions/dec:create",
                               {"session_id": "fresh1"},
                               deadline_s=30)
        assert code == 200
        code, _ = _post(port_b, "/v1/sessions/dec/fresh1:step",
                        {"inputs": [_x()[0].tolist()], "steps": 2},
                        timeout=60)
        assert code == 200

        # the survivor's healthz names the dead peer + the takeover
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port_b}/healthz",
                timeout=30) as resp:
            blk = json.loads(resp.read())["router_ha"]
        assert blk["counters"]["takeovers"] >= 1
        assert "rA" in blk["expired"] or not blk["peers"]

        # postmortem: the causal chain from the survivor's black box
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port_b}/v1/flight",
                timeout=30) as resp:
            dump = tmp_path / "rB.flight.json"
            dump.write_bytes(resp.read())
        gate = subprocess.run(
            [sys.executable, POSTMORTEM, str(dump), "--gate",
             "router.lease.expired,router.takeover.started,"
             "session.restored"],
            capture_output=True, text=True)
        assert gate.returncode == 0, \
            f"postmortem gate failed:\n{gate.stdout}\n{gate.stderr}"
        assert "gate ok" in gate.stdout
    finally:
        for proc in (pa, pb):
            if proc is not None and proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
        for proc in (pa, pb):
            if proc is not None:
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    pass


def test_routerforwarderror_is_typed_not_connectionerror():
    # 508 must NOT be retried as transient by generic failover layers
    assert not isinstance(RouterForwardError("x"), ConnectionError)
