"""Profiler (§5.1: reference src/profiler/ + python/mxnet/profiler.py):
chrome-trace dumps, aggregate tables, scoped events, and the
storage/HBM memory counter hooks."""
import json
import time

import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, profiler


def test_scope_events_and_dump(tmp_path):
    out = tmp_path / "profile.json"
    profiler.set_config(filename=str(out), profile_memory=False)
    profiler.start()
    with profiler.scope("fwd"):
        nd.ones((8, 8)).sum().asscalar()
    with profiler.scope("bwd"):
        time.sleep(0.002)
    profiler.stop()
    path = profiler.dump()
    trace = json.loads(open(path).read())
    names = [e["name"] for e in trace["traceEvents"]]
    assert "fwd" in names and "bwd" in names
    ev = next(e for e in trace["traceEvents"] if e["name"] == "bwd")
    assert ev["ph"] == "X" and ev["dur"] >= 1000  # >= 1ms in us


def test_aggregate_table():
    profiler.set_config(profile_memory=False)
    profiler.start()
    for _ in range(3):
        with profiler.scope("agg_op"):
            pass
    profiler.stop()
    table = profiler.dumps(reset=True)
    line = next(l for l in table.splitlines() if l.startswith("agg_op"))
    assert " 3 " in " ".join(line.split())


def test_memory_counter_events(tmp_path, monkeypatch):
    """profile_memory samples HBM/host-pool counters into the trace
    (reference storage_profiler.cc role)."""
    monkeypatch.setenv("MXNET_PROFILER_MEM_INTERVAL_MS", "10")
    out = tmp_path / "mem_profile.json"
    profiler.set_config(filename=str(out), profile_memory=True)
    profiler.start()
    arrays = [nd.ones((64, 64)) for _ in range(4)]
    for a in arrays:
        a.asnumpy()
    time.sleep(0.1)
    profiler.stop()
    trace = json.loads(open(profiler.dump()).read())
    counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    assert counters, "no memory counter events sampled"
    assert all(e["cat"] == "memory" for e in counters)
    # values are numeric byte counts
    for e in counters:
        for v in e["args"].values():
            assert isinstance(v, int) and v >= 0


def test_counter_and_task_api():
    profiler.set_config(profile_memory=False)
    profiler.start()
    c = profiler.Counter(None, "items", 0)
    c.increment(5)
    c.decrement(2)
    t = profiler.Task(None, "phase")
    t.start()
    t.stop()
    profiler.stop()


def test_device_memory_profile_shape():
    prof = profiler.device_memory_profile()
    assert isinstance(prof, dict)  # may be empty on hosts without stats
    for dev, st in prof.items():
        assert "bytes_in_use" in st


def test_memory_allocation_attribution():
    """Per-allocation scope tagging (reference storage_profiler.cc
    GpuMemoryProfiler CSV role)."""
    from incubator_mxnet_tpu import nd, profiler
    profiler.set_config(profile_memory=True)
    profiler.start()
    try:
        with profiler.scope("alloc_test_init"):
            a = nd.ones((64, 64))
        with profiler.scope("alloc_test_fwd"):
            with profiler.scope("inner"):
                (a * 2 + 1).wait_to_read()
    finally:
        profiler.stop()
    csv = profiler.dump_memory_allocations(reset=True)
    assert '"alloc_test_init",16384' in csv
    assert "alloc_test_fwd:inner" in csv  # nested scope join
    assert "Scope,Total bytes" in csv
    # tracking is off after stop(): no new rows
    b = nd.ones((8, 8))
    b.wait_to_read()
    assert "(8, 8)" not in profiler.dump_memory_allocations(reset=True)
