"""NDArray core tests (reference tests/python/unittest/test_ndarray.py)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def test_creation_and_numpy_roundtrip():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == onp.float32
    assert_almost_equal(a, onp.array([[1, 2], [3, 4]], "float32"))
    b = nd.array(onp.arange(6).reshape(2, 3), dtype="int32")
    assert b.dtype == onp.int32
    assert b.asnumpy().tolist() == [[0, 1, 2], [3, 4, 5]]


def test_creation_helpers():
    assert nd.zeros((2, 3)).asnumpy().sum() == 0
    assert nd.ones((2, 3)).asnumpy().sum() == 6
    assert nd.full((2,), 7).asnumpy().tolist() == [7, 7]
    assert nd.arange(0, 5).asnumpy().tolist() == [0, 1, 2, 3, 4]
    assert nd.eye(3).asnumpy().trace() == 3


def test_arithmetic_broadcast():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([10.0, 20.0])
    assert_almost_equal(a + b, onp.array([[11, 22], [13, 24]], "float32"))
    assert_almost_equal(a - 1, onp.array([[0, 1], [2, 3]], "float32"))
    assert_almost_equal(2 * a, onp.array([[2, 4], [6, 8]], "float32"))
    assert_almost_equal(a / b, onp.array([[0.1, 0.1], [0.3, 0.2]], "float32"))
    assert_almost_equal(a ** 2, onp.array([[1, 4], [9, 16]], "float32"))
    assert_almost_equal(-a, -a.asnumpy())


def test_comparison_ops():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert (a < b).asnumpy().tolist() == [1, 0, 0]
    assert (a == b).asnumpy().tolist() == [0, 1, 0]
    assert (a >= b).asnumpy().tolist() == [0, 1, 1]


def test_inplace_ops_mutate_chunk():
    a = nd.ones((3,))
    version0 = a._chunk.var.version
    a += 2
    assert a.asnumpy().tolist() == [3, 3, 3]
    assert a._chunk.var.version > version0
    a *= 2
    assert a.asnumpy().tolist() == [6, 6, 6]


def test_slice_view_semantics():
    """Views share the chunk: writes through either side are visible
    (reference NDArray slice-view semantics, ndarray.h views)."""
    a = nd.zeros((4, 4))
    v = a[1:3]
    v[:] = 7.0
    assert a.asnumpy()[1:3].tolist() == [[7] * 4, [7] * 4]
    a[2] = 3.0
    assert v.asnumpy()[1].tolist() == [3] * 4


def test_setitem_basic_and_advanced():
    a = nd.zeros((3, 3))
    a[0, 0] = 5
    a[1] = nd.ones((3,))
    assert a.asnumpy()[0, 0] == 5
    assert a.asnumpy()[1].tolist() == [1, 1, 1]


def test_reshape_view():
    a = nd.arange(0, 6).reshape((2, 3))
    r = a.reshape((3, 2))
    assert r.shape == (3, 2)
    r2 = a.reshape((-1,))
    assert r2.shape == (6,)
    # reshape with 0 (copy dim) and -1
    b = nd.zeros((2, 3, 4))
    assert b.reshape((0, -1)).shape == (2, 12)


def test_reductions_and_methods():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert a.sum().asscalar() == 10
    assert a.mean().asscalar() == 2.5
    assert a.max(axis=0).asnumpy().tolist() == [3, 4]
    assert a.argmax(axis=1).asnumpy().tolist() == [1, 1]
    assert abs(a.norm().asscalar() - onp.sqrt(30)) < 1e-5


def test_dtype_cast_and_context():
    a = nd.ones((2, 2))
    b = a.astype("float16")
    assert b.dtype == onp.float16
    c = a.as_in_context(mx.cpu())
    assert c.ctx.device_type == "cpu"
    bf = a.astype("bfloat16")
    assert "bfloat16" in str(bf.data.dtype)


def test_save_load_roundtrip(tmp_path):
    fname = str(tmp_path / "arrays.params")
    d = {"w": nd.ones((2, 3)), "b": nd.arange(0, 4, dtype="int32")}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert_almost_equal(loaded["w"], d["w"])
    assert loaded["b"].asnumpy().tolist() == [0, 1, 2, 3]
    # list form
    nd.save(fname, [nd.zeros((2,)), nd.ones((3,))])
    lst = nd.load(fname)
    assert isinstance(lst, list) and len(lst) == 2


def test_concat_split_stack():
    a, b = nd.ones((2, 3)), nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert parts[0].shape == (2, 3)


def test_wait_to_read_and_waitall():
    a = nd.ones((4,)) * 3
    a.wait_to_read()
    nd.waitall()
    assert a.asnumpy().tolist() == [3, 3, 3, 3]


def test_scalar_conversions():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert a.asscalar() == 3.5
    assert int(nd.array([7])) == 7
    with pytest.raises(ValueError):
        nd.ones((2,)).asscalar()


def test_sparse_row_sparse():
    from incubator_mxnet_tpu.ndarray import sparse
    dense = nd.array([[0, 0], [1, 2], [0, 0], [3, 4]])
    rs = sparse.cast_storage(dense, "row_sparse")
    assert rs.stype == "row_sparse"
    assert rs.indices.asnumpy().tolist() == [1, 3]
    assert_almost_equal(rs.todense(), dense)
    back = rs.tostype("default")
    assert back.stype == "default"


def test_sparse_csr():
    from incubator_mxnet_tpu.ndarray import sparse
    dense = nd.array([[0, 1.0], [2.0, 0]])
    csr = sparse.cast_storage(dense, "csr")
    assert csr.stype == "csr"
    assert_almost_equal(csr.todense(), dense)


def test_one_hot_take_pick():
    idx = nd.array([0, 2], dtype="int32")
    oh = nd.one_hot(idx, depth=3)
    assert oh.asnumpy().tolist() == [[1, 0, 0], [0, 0, 1]]
    data = nd.array([[1.0, 2, 3], [4, 5, 6]])
    assert nd.take(data, nd.array([1], dtype="int32"),
                   axis=1).asnumpy().ravel().tolist() == [2, 5]
    assert nd.pick(data, nd.array([0, 2]), axis=1).asnumpy().tolist() == [1, 6]


def test_np_grad_with_leading_scalar():
    """Cotangent slot routing when non-arrays precede NDArrays
    (round-3 review regression: np.subtract(1.0, x) handed x the
    scalar's gradient)."""
    import numpy as onp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    x = mx.np.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = mx.np.subtract(1.0, x)
        loss = (y * y).sum()
    loss.backward()
    onp.testing.assert_allclose(
        x.grad.asnumpy(), -2.0 * (1.0 - onp.array([1., 2., 3.])),
        rtol=1e-6)


def test_array_function_protocol():
    """onp.mean/concatenate/stack on NDArray dispatch to the framework
    numpy namespace and stay NDArray (reference
    test_numpy_interoperability.py / numpy_dispatch_protocol.py)."""
    import numpy as onp
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([4.0, 5.0, 6.0])
    m = onp.mean(a)
    assert isinstance(m, nd.NDArray) and float(m.asnumpy()) == 2.0
    c = onp.concatenate([a, b])
    assert isinstance(c, nd.NDArray)
    assert c.asnumpy().tolist() == [1, 2, 3, 4, 5, 6]
    s = onp.stack([a, b])
    assert isinstance(s, nd.NDArray) and s.shape == (2, 3)
