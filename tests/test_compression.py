"""Gradient compression on a real collective (VERDICT r2 task #10).

Asserts (a) the cross-rank traffic is genuinely uint8 2-bit-packed
codes, (b) the quantize→gather→dequantize algebra matches a hand
computation, (c) error feedback makes compressed data-parallel SGD
converge on a toy model over the 8-device mesh, (d) the measured wire
bytes are 16× below fp32.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

from incubator_mxnet_tpu.parallel.mesh import make_mesh
from incubator_mxnet_tpu.kvstore.gradient_compression import (
    GradientCompression, make_compressed_allreduce,
    make_compressed_dp_train_step, _quantize_2bit, _dequantize_2bit)


def setup_module():
    assert jax.device_count() >= 8


def test_pack_unpack_roundtrip():
    x = jnp.asarray([0.7, -0.9, 0.1, -0.2, 0.5, 0.0, -0.5], jnp.float32)
    packed = _quantize_2bit(x, 0.5)
    assert packed.dtype == jnp.uint8
    assert packed.shape[0] == 2           # ceil(7/4) bytes
    back = _dequantize_2bit(packed, 7, 0.5, jnp.float32)
    onp.testing.assert_array_equal(
        onp.asarray(back), [0.5, -0.5, 0.0, 0.0, 0.5, 0.0, -0.5])


def test_wire_dtype_is_uint8():
    mesh = make_mesh(dp=8)
    fn = make_compressed_allreduce(mesh, threshold=0.5)
    grads = {"w": jnp.zeros((8, 4, 4), jnp.float32)}   # stacked per-rank
    res = {"w": jnp.zeros((8, 4, 4), jnp.float32)}
    jaxpr = str(jax.make_jaxpr(fn)(grads, res))
    # the only collective result is the packed uint8 code buffer
    gathers = [l for l in jaxpr.splitlines() if "= all_gather" in l]
    assert gathers and all("u8[" in l for l in gathers), gathers


def test_compressed_allreduce_matches_manual():
    mesh = make_mesh(dp=8)
    rng = onp.random.RandomState(0)
    per_rank = rng.randn(8, 6).astype(onp.float32)
    grads = {"w": jnp.asarray(per_rank)}
    res = {"w": jnp.zeros((8, 6), jnp.float32)}
    fn = make_compressed_allreduce(mesh, threshold=0.5)
    mean, new_res = fn(grads, res)
    # manual: quantize each rank to {-.5, 0, .5}, average
    q = onp.where(per_rank >= 0.5, 0.5,
                  onp.where(per_rank <= -0.5, -0.5, 0.0))
    onp.testing.assert_allclose(onp.asarray(mean["w"]), q.mean(axis=0),
                                rtol=1e-6)
    onp.testing.assert_allclose(onp.asarray(new_res["w"]), per_rank - q,
                                rtol=1e-6)


def test_error_feedback_accumulates():
    mesh = make_mesh(dp=8)
    fn = make_compressed_allreduce(mesh, threshold=0.5)
    # constant small gradient 0.2 < threshold: first step quantizes to 0,
    # residual builds until it crosses the threshold and fires
    grads = {"w": jnp.full((8, 4), 0.2, jnp.float32)}
    res = {"w": jnp.zeros((8, 4), jnp.float32)}
    mean1, res1 = fn(grads, res)
    assert float(jnp.abs(mean1["w"]).max()) == 0.0          # all dropped
    onp.testing.assert_allclose(onp.asarray(res1["w"]), 0.2, rtol=1e-6)
    mean2, res2 = fn(grads, res1)
    assert float(jnp.abs(mean2["w"]).max()) == 0.0          # 0.4 < 0.5
    mean3, res3 = fn(grads, res2)                           # 0.6 fires
    onp.testing.assert_allclose(onp.asarray(mean3["w"]), 0.5, rtol=1e-6)
    onp.testing.assert_allclose(onp.asarray(res3["w"]), 0.1, rtol=1e-5,
                                atol=1e-6)


def test_compressed_dp_training_converges():
    mesh = make_mesh(dp=8)
    rng = onp.random.RandomState(1)
    d = 4
    w_true = rng.randn(d).astype(onp.float32)
    X = rng.randn(64, d).astype(onp.float32)
    y = X @ w_true

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean(jnp.square(pred - batch["y"]))

    step = make_compressed_dp_train_step(loss_fn, mesh, lr=0.5,
                                         threshold=0.1)
    params = {"w": jnp.zeros((d,), jnp.float32)}
    residuals = {"w": jnp.zeros((8, d), jnp.float32)}
    batch = {"x": jnp.asarray(X), "y": jnp.asarray(y)}
    first = None
    for i in range(500):
        params, residuals, loss = step(params, residuals, batch)
        if first is None:
            first = float(loss)
    final = float(loss)
    assert final < 0.01 * first, (first, final)
    onp.testing.assert_allclose(onp.asarray(params["w"]), w_true,
                                rtol=0.2, atol=0.1)


def test_wire_bytes_reduction():
    n = 1024
    packed = _quantize_2bit(jnp.zeros((n,), jnp.float32), 0.5)
    fp32_bytes = n * 4
    wire_bytes = packed.size * packed.dtype.itemsize
    assert wire_bytes * 16 == fp32_bytes


def test_legacy_roundtrip_api_still_works():
    gc = GradientCompression(type="2bit", threshold=0.5)
    g = jnp.asarray([1.0, 0.2, -0.7], jnp.float32)
    q = gc.compress_decompress(g, key="k")
    onp.testing.assert_array_equal(onp.asarray(q), [0.5, 0.0, -0.5])
