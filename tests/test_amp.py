"""AMP list-driven conversion tests (VERDICT r2 task #7).

Covers the reference's convert_symbol/convert_model surface
(contrib/amp/amp.py:389-477 + lists/) and the Gluon convert_block path:
the op lists must actually steer per-op dtypes, and the fp32_ops /
target_dtype_ops arguments must be honored rather than discarded.
"""
import numpy as onp
import pytest

import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import amp, nd, gluon, sym
from incubator_mxnet_tpu.ops.registry import get_op, invoke


# ---------------------------------------------------------------------------
# CastPolicy unit behavior
# ---------------------------------------------------------------------------

def test_policy_classes_from_lists():
    pol = amp.CastPolicy("bfloat16")
    assert pol.op_class("Convolution") == "lp16"
    assert pol.op_class("softmax") == "fp32"
    assert pol.op_class("add") == "widest"
    assert pol.op_class("relu") is None  # unlisted: untouched


def test_policy_override_args_honored():
    # fp32_ops overrides the default listing — the round-2 bug was that
    # this argument was accepted and ignored
    pol = amp.CastPolicy("bfloat16", fp32_ops=["Convolution"],
                         target_dtype_ops=["FullyConnected"])
    assert pol.op_class("Convolution") == "fp32"
    assert pol.op_class("FullyConnected") == "lp16"
    assert pol.op_class("softmax") is None  # replaced default list


def test_policy_conflicting_lists_rejected():
    with pytest.raises(ValueError):
        amp.CastPolicy("bfloat16", target_dtype_ops=["dot"], fp32_ops=["dot"])


def test_policy_cast_args_dtypes():
    pol = amp.CastPolicy("bfloat16")
    f32 = jnp.ones((4, 4), jnp.float32)
    bf16 = jnp.ones((4, 4), jnp.bfloat16)
    ints = jnp.ones((4,), jnp.int32)
    out = pol.cast_args("dot", [f32, bf16, ints])
    assert out[0].dtype == jnp.bfloat16
    assert out[1].dtype == jnp.bfloat16
    assert out[2].dtype == jnp.int32  # non-float passes through
    out = pol.cast_args("softmax", [bf16])
    assert out[0].dtype == jnp.float32
    out = pol.cast_args("add", [f32, bf16])
    assert out[0].dtype == jnp.float32 and out[1].dtype == jnp.float32


# ---------------------------------------------------------------------------
# Eager/Gluon path: policy active during forward
# ---------------------------------------------------------------------------

def test_convert_block_policy_steers_op_dtypes():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, in_units=8))
    net.add(gluon.nn.Dense(4, in_units=16))
    net.initialize()
    amp.convert_block(net, "bfloat16")
    x = nd.random.uniform(shape=(2, 8))  # fp32 input
    out = net(x)
    # params cast + lp16 list => FullyConnected computes in bf16
    assert out.dtype == jnp.bfloat16

    # now force Dense to fp32 via the fp32_ops argument
    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(16, in_units=8))
    net2.initialize()
    amp.convert_block(net2, "bfloat16", fp32_ops=["FullyConnected"],
                      target_dtype_ops=[])
    out2 = net2(x)
    assert out2.dtype == jnp.float32


def test_convert_block_keeps_norm_params_fp32():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, in_channels=3))
    net.add(gluon.nn.BatchNorm(in_channels=8))
    net.initialize()
    net(nd.random.uniform(shape=(1, 3, 8, 8)))
    amp.convert_block(net, "bfloat16")
    params = dict(net.collect_params().items())
    conv_w = [v for k, v in params.items() if k.endswith("weight")][0]
    gammas = [v for k, v in params.items() if k.endswith("gamma")]
    assert conv_w.dtype == jnp.bfloat16
    assert all(g.dtype == jnp.float32 for g in gammas)


def test_policy_scope_restores():
    pol = amp.CastPolicy("bfloat16")
    assert amp.current_policy() is None
    with amp.policy_scope(pol):
        assert amp.current_policy() is pol
    assert amp.current_policy() is None


# ---------------------------------------------------------------------------
# amp_cast / amp_multicast ops
# ---------------------------------------------------------------------------

def test_amp_cast_op():
    op = get_op("amp_cast")
    x = jnp.ones((3,), jnp.float32)
    assert op.fn(x, dtype="bfloat16").dtype == jnp.bfloat16
    ints = jnp.ones((3,), jnp.int32)
    assert op.fn(ints, dtype="bfloat16").dtype == jnp.int32


def test_amp_multicast_op():
    op = get_op("amp_multicast")
    a = jnp.ones((3,), jnp.bfloat16)
    b = jnp.ones((3,), jnp.float32)
    oa, ob = op.fn(a, b, num_outputs=2)
    assert oa.dtype == jnp.float32 and ob.dtype == jnp.float32


# ---------------------------------------------------------------------------
# Symbolic path: convert_symbol graph rewrite
# ---------------------------------------------------------------------------

def _count_ops(s, name):
    return sum(1 for n in s._topo_order() if n.op_name == name)


def test_convert_symbol_inserts_casts():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=8, name="fc1")
    out = sym.softmax(fc, name="sm")
    conv = amp.convert_symbol(out, "bfloat16")
    # fc inputs (data, weight, bias) wrapped in amp_cast->bf16;
    # softmax input wrapped in amp_cast->fp32
    casts = [n for n in conv._topo_order() if n.op_name == "amp_cast"]
    assert len(casts) == 4
    tgt = {n.kwargs["dtype"] for n in casts}
    assert tgt == {"bfloat16", "float32"}
    # original symbol untouched
    assert _count_ops(out, "amp_cast") == 0


def test_convert_symbol_execution_dtypes():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=8, name="fc1")
    conv = amp.convert_symbol(fc, "bfloat16")
    w = jnp.ones((8, 4), jnp.float32)
    b = jnp.zeros((8,), jnp.float32)
    x = jnp.ones((2, 4), jnp.float32)
    outs = conv._evaluate({"data": x, "fc1_weight": w, "fc1_bias": b})
    assert outs[0].dtype == jnp.bfloat16
    # numerics match the fp32 graph within bf16 tolerance
    ref = fc._evaluate({"data": x, "fc1_weight": w, "fc1_bias": b})
    onp.testing.assert_allclose(onp.asarray(outs[0], onp.float32),
                                onp.asarray(ref[0]), rtol=2e-2)


def test_convert_symbol_excluded_names():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=8, name="fc1")
    conv = amp.convert_symbol(fc, "bfloat16", excluded_sym_names=["fc1"])
    assert _count_ops(conv, "amp_cast") == 0


def test_convert_symbol_widest_multicast():
    a = sym.var("a")
    b = sym.var("b")
    s = a + b
    conv = amp.convert_symbol(s, "bfloat16")
    assert _count_ops(conv, "amp_multicast") == 1
    out = conv._evaluate({"a": jnp.ones((3,), jnp.bfloat16),
                          "b": jnp.ones((3,), jnp.float32)})
    assert out[0].dtype == jnp.float32


def test_convert_model_casts_optional_params():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=8, name="fc1")
    arg_params = {"fc1_weight": nd.ones((8, 4)).data,
                  "fc1_bias": nd.zeros((8,)).data}
    new_sym, new_args, _ = amp.convert_model(
        fc, arg_params, {}, "bfloat16", cast_optional_params=True)
    assert new_args["fc1_weight"].dtype == jnp.bfloat16
    # default: params stay fp32 (runtime amp_cast downcasts)
    _, args2, _ = amp.convert_model(fc, arg_params, {}, "bfloat16")
    assert args2["fc1_weight"].dtype == jnp.float32


def test_converted_symbol_roundtrips_json():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=8, name="fc1")
    conv = amp.convert_symbol(fc, "bfloat16")
    j = conv.tojson()
    re = sym.load_json(j)
    assert _count_ops(re, "amp_cast") == _count_ops(conv, "amp_cast")


# ---------------------------------------------------------------------------
# Review-pass regressions (round-3 code review findings)
# ---------------------------------------------------------------------------

def test_converted_symbol_infers_param_shapes():
    # amp_cast between a param variable and its layer op must not break
    # backward shape inference in simple_bind
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=8, name="fc1")
    conv = amp.convert_symbol(fc, "bfloat16")
    ex = conv.simple_bind(data=(2, 4))
    out = ex.forward()
    assert out[0].shape == (2, 8)


def test_converted_symbol_keeps_aux_updates():
    # fp16 lists put BatchNorm in fp32; the cast insertion must leave
    # moving stats (aux) as direct variable inputs so training-mode
    # aux updates still map back
    data = sym.var("data")
    bn = sym.BatchNorm(data, name="bn0")
    conv = amp.convert_symbol(bn, "float16")
    binds = {"data": jnp.ones((4, 3, 2, 2), jnp.float32) * 2.0,
             "bn0_gamma": jnp.ones((3,)), "bn0_beta": jnp.zeros((3,)),
             "bn0_moving_mean": jnp.zeros((3,)),
             "bn0_moving_var": jnp.ones((3,))}
    aux = {}
    conv._evaluate(binds, training=True, aux_updates=aux)
    assert set(aux) == {"bn0_moving_mean", "bn0_moving_var"}
    assert float(aux["bn0_moving_mean"][0]) != 0.0


def test_convert_symbol_dedups_casts():
    # one variable feeding two lp16 ops gets ONE amp_cast node
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=4, name="fca")
    fc2 = sym.FullyConnected(data, num_hidden=4, name="fcb")
    g = sym.Group([fc1, fc2])
    conv = amp.convert_symbol(g, "bfloat16")
    casts = [n for n in conv._topo_order() if n.op_name == "amp_cast"]
    data_casts = [n for n in casts if n.inputs[0].name == "data"]
    assert len(data_casts) == 1
    names = [n.name for n in conv._topo_order()]
    assert len(names) == len(set(names)), "duplicate node names"


def test_convert_model_excluded_params_stay_fp32():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=8, name="fc1")
    arg_params = {"fc1_weight": nd.ones((8, 4)).data,
                  "fc1_bias": nd.zeros((8,)).data}
    _, args, _ = amp.convert_model(
        fc, arg_params, {}, "bfloat16", excluded_sym_names=["fc1"],
        cast_optional_params=True)
    assert args["fc1_weight"].dtype == jnp.float32
