"""int8 quantization flow (reference tests/python/quantization/
test_quantization.py coverage, TPU-native int8 ops)."""
import jax.numpy as jnp
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.contrib.quantization import (
    CalibrationCollector, optimal_threshold_kl, quantize_net)
from incubator_mxnet_tpu.ops import quantization_ops as qops


def test_quantize_dequantize_roundtrip():
    x = jnp.asarray(onp.random.RandomState(0).randn(64, 32), jnp.float32)
    q, lo, hi = qops.quantize.fn(x)
    assert q.dtype == jnp.int8
    back = qops.dequantize.fn(q, lo, hi)
    # max quantization error is one scale step
    scale = float(max(abs(float(lo)), abs(float(hi)))) / 127.0
    assert float(jnp.abs(back - x).max()) <= scale * 0.51


def test_quantize_respects_calibrated_range():
    x = jnp.asarray([[-10.0, 0.0, 10.0, 100.0]], jnp.float32)
    q, lo, hi = qops.quantize.fn(x, -10.0, 10.0)   # clip outliers
    assert int(q[0, 3]) == 127                      # clipped to range max
    back = qops.dequantize.fn(q, lo, hi)
    onp.testing.assert_allclose(onp.asarray(back[0, :3]), [-10, 0, 10],
                                atol=0.1)


def test_requantize_int32_to_int8():
    rs = onp.random.RandomState(1)
    acc = jnp.asarray(rs.randint(-(2 ** 20), 2 ** 20, (16, 16)), jnp.int32)
    q, lo, hi = qops.requantize.fn(acc, -1.0, 1.0)
    assert q.dtype == jnp.int8
    real = acc.astype(jnp.float32) * (1.0 / float(2 ** 31 - 1))
    back = qops.dequantize.fn(q, lo, hi)
    assert float(jnp.abs(back - real).max()) <= \
        float(jnp.abs(real).max()) / 127 + 1e-9


def test_quantized_dense_matches_fp32():
    rs = onp.random.RandomState(2)
    x = jnp.asarray(rs.rand(8, 32) * 2 - 1, jnp.float32)
    w = jnp.asarray(rs.randn(16, 32) * 0.2, jnp.float32)
    b = jnp.asarray(rs.randn(16) * 0.1, jnp.float32)
    xq, xmin, xmax = qops.quantize.fn(x)
    wq, wmin, wmax = qops.quantize.fn(w)
    acc, omin, omax = qops.quantized_dense.fn(xq, wq, b, xmin, xmax,
                                              wmin, wmax)
    got = qops.dequantize.fn(acc, omin, omax)
    want = x @ w.T + b
    err = float(jnp.abs(got - want).max())
    assert err < 0.05, err


def test_quantized_conv_matches_fp32():
    import jax
    rs = onp.random.RandomState(3)
    x = jnp.asarray(rs.rand(2, 3, 8, 8) * 2 - 1, jnp.float32)
    w = jnp.asarray(rs.randn(4, 3, 3, 3) * 0.2, jnp.float32)
    xq, xmin, xmax = qops.quantize.fn(x)
    wq, wmin, wmax = qops.quantize.fn(w)
    acc, omin, omax = qops.quantized_conv2d.fn(
        xq, wq, None, xmin, xmax, wmin, wmax, stride=(1, 1), pad=(1, 1))
    got = qops.dequantize.fn(acc, omin, omax)
    want = jax.lax.conv_general_dilated(
        x, w, (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    err = float(jnp.abs(got - want).max()) / float(jnp.abs(want).max())
    assert err < 0.05, err


def test_optimal_threshold_kl_clips_outliers():
    rs = onp.random.RandomState(4)
    arr = onp.concatenate([rs.randn(100000), [1000.0]])  # one huge outlier
    t = optimal_threshold_kl(arr)
    assert t < 100.0  # clipped far below the outlier
    assert t > 1.0    # but keeps the bulk of the distribution


def test_calibration_collector_modes():
    c = CalibrationCollector("naive")
    c.collect("l1", onp.array([-2.0, 3.0]))
    c.collect("l1", onp.array([-5.0, 1.0]))
    assert c.thresholds("l1") == (-5.0, 3.0)
    ce = CalibrationCollector("entropy")
    rs = onp.random.RandomState(5)
    ce.collect("l1", rs.randn(10000))
    lo, hi = ce.thresholds("l1")
    assert lo == -hi and 0 < hi < 10


def test_calibration_entropy_range_grows_past_degenerate_first_batch():
    """A near-zero first batch must not freeze the histogram range: the
    collector widens and rebins when later batches exceed it, so the
    threshold reflects the real activation scale."""
    ce = CalibrationCollector("entropy")
    ce.collect("l1", onp.full(64, 1e-7))          # degenerate first batch
    rs = onp.random.RandomState(7)
    for _ in range(4):
        ce.collect("l1", rs.randn(10000))          # real scale ~N(0,1)
    lo, hi = ce.thresholds("l1")
    assert lo == -hi and 0.5 < hi < 10             # not ~2e-7
    # histogram range covers the real data, not the first batch
    assert ce.edges["l1"][-1] > 1.0
    # total mass preserved through the rebinning (64 + 4*10000 samples)
    assert abs(ce.hists["l1"].sum() - (64 + 40000)) < 1e-6


@pytest.mark.parametrize("mode", ["naive", "entropy"])
def test_quantize_net_accuracy(mode):
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"), nn.MaxPool2D(2),
            nn.Flatten(), nn.Dense(32, activation="relu"), nn.Dense(10))
    net.initialize()
    x = nd.random.uniform(shape=(64, 1, 16, 16))
    y = nd.random.randint(0, 10, shape=(64,))
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 3e-3})
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(80):
        with autograd.record():
            loss = lf(net(x), y)
        loss.backward()
        tr.step(64)
    fp32_out = net(x).asnumpy()
    fp32_acc = (fp32_out.argmax(1) == y.asnumpy()).mean()
    # entropy clipping distorts outlier logits by design; keep the last
    # classifier layer fp32 as the reference's excluded_sym_names default
    exclude = ("4",) if mode == "entropy" else ()
    qnet = quantize_net(net, calib_data=[x], calib_mode=mode,
                        exclude_layers=exclude)
    q_out = qnet(x).asnumpy()
    q_acc = (q_out.argmax(1) == y.asnumpy()).mean()
    rel = onp.abs(q_out - fp32_out).mean() / (onp.abs(fp32_out).mean() + 1e-9)
    assert q_acc >= fp32_acc - 0.05
    assert rel < 0.15, rel


def test_int8_dot_reaches_xla():
    """The quantized dense path must keep int8 operands into the
    dot_general (int8xint8->int32 on hardware), not silently upcast
    before the contraction — asserted on the traced jaxpr."""
    import jax

    def run(xq, wq, xmin, xmax, wmin, wmax):
        acc, omin, omax = qops.quantized_dense.fn(xq, wq, None, xmin, xmax,
                                                  wmin, wmax)
        return acc

    rng = onp.random.RandomState(0)
    xq = jnp.asarray(rng.randint(-127, 127, (4, 16)), jnp.int8)
    wq = jnp.asarray(rng.randint(-127, 127, (8, 16)), jnp.int8)
    scal = lambda v: jnp.asarray(v, jnp.float32)  # noqa: E731
    jaxpr = jax.make_jaxpr(run)(xq, wq, scal(-1), scal(1), scal(-1),
                                scal(1))
    dots = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "dot_general"]
    assert dots, "quantized_dense lowered without any dot_general"
    for eq in dots:
        in_dtypes = [v.aval.dtype for v in eq.invars]
        assert all(str(d) == "int8" for d in in_dtypes), in_dtypes
        assert str(eq.outvars[0].aval.dtype) == "int32"


@pytest.mark.slow   # ~39 s fresh-python example subprocess: tier-1
                    # budget relief (ISSUE 15); the `slow` CI stage keeps it
def test_quantize_resnet_example_end_to_end():
    """VERDICT r3 Next #5: the full calibrate -> int8-convert -> infer
    flow at model-zoo scale, via the shipped example (reduced size for
    CI).  Asserts top-1 agreement with the float model for both calib
    modes and that both throughput numbers were measured."""
    import json
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "examples",
                                      "quantize_resnet50.py"),
         "--cpu", "--model", "resnet18_v1", "--batch", "4",
         "--image-size", "64", "--eval-batches", "2",
         "--calib-batches", "1"],
        capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, proc.stderr[-500:]
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()]
    assert {r["calib_mode"] for r in lines} == {"naive", "entropy"}
    for r in lines:
        assert r["top1_agreement_vs_float"] >= 0.85, r
        assert r["int8_img_per_sec"] > 0 and r["float_img_per_sec"] > 0
