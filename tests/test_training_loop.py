"""Training-loop plumbing: estimator, callbacks, monitor, prefetcher —
the reference's gluon/contrib/estimator + callback.py + monitor.py
surfaces exercised end-to-end."""
import io as _io
import sys

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, gluon
from incubator_mxnet_tpu.io import NDArrayIter, PrefetchingIter


def _toy_iter(n=64, bs=16):
    rng = onp.random.RandomState(0)
    x = rng.rand(n, 8).astype(onp.float32)
    y = (x.sum(axis=1) > 4).astype(onp.float32)
    return NDArrayIter(x, y, batch_size=bs)


def test_estimator_fit_with_checkpoint(tmp_path):
    from incubator_mxnet_tpu.gluon.contrib.estimator import (
        Estimator, CheckpointHandler)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, in_units=8, activation="relu"),
            gluon.nn.Dense(2, in_units=16))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})
    est = Estimator(net=net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                    train_metrics=[gluon.metric.Accuracy()],
                    trainer=trainer)
    handler = CheckpointHandler(model_dir=str(tmp_path),
                                model_prefix="toy", save_best=False)
    # gluon DataLoader-style iterable of (data, label)
    rng = onp.random.RandomState(1)
    data = [(nd.array(rng.rand(16, 8).astype(onp.float32)),
             nd.array(rng.randint(0, 2, (16,)).astype(onp.float32)))
            for _ in range(4)]
    est.fit(train_data=data, epochs=2, event_handlers=[handler])
    import os
    saved = [f for f in os.listdir(tmp_path) if f.endswith(".params")]
    assert saved, "CheckpointHandler wrote nothing"


def test_speedometer_callback_logs():
    from incubator_mxnet_tpu.callback import Speedometer
    from incubator_mxnet_tpu.model import BatchEndParam
    m = gluon.metric.Accuracy()
    m.update([nd.array([1.0, 0.0])],
             [nd.array([[0.1, 0.9], [0.8, 0.2]])])
    cb = Speedometer(batch_size=2, frequent=1)
    import logging
    records = []
    handler = logging.Handler()
    handler.emit = lambda rec: records.append(rec.getMessage())
    root = logging.getLogger()
    old_level = root.level
    root.addHandler(handler)
    root.setLevel(logging.INFO)
    try:
        for i in range(3):
            cb(BatchEndParam(epoch=0, nbatch=i + 1, eval_metric=m))
    finally:
        root.removeHandler(handler)
        root.setLevel(old_level)
    assert any("Speed" in r or "samples/sec" in r for r in records), records


def test_monitor_taps_outputs():
    from incubator_mxnet_tpu.monitor import Monitor
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4, in_units=8))
    net.initialize()
    mon = Monitor(interval=1, pattern=".*")
    mon.install(net)
    mon.tic()
    net(nd.ones((2, 8)))
    rows = mon.toc()
    assert rows, "monitor captured nothing"
    name_stat = [(r[1], r[2]) for r in rows]
    assert any(isinstance(s, (float, onp.floating)) or hasattr(s, "shape")
               for _, s in name_stat)


def test_prefetching_iter_matches_plain():
    base = _toy_iter()
    plain = [b.data[0].asnumpy() for b in base]
    base.reset()
    pre = PrefetchingIter(base)
    got = [b.data[0].asnumpy() for b in pre]
    assert len(got) == len(plain)
    for a, b in zip(plain, got):
        onp.testing.assert_array_equal(a, b)


def test_initializer_load_and_fused_rnn(tmp_path):
    """Load + FusedRNN + InitDesc initializers (reference
    initializer.py:36,318,719)."""
    import numpy as onp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, initializer
    # Load: round-trip through a saved .params file
    w = nd.array(onp.full((3, 2), 5.0, onp.float32))
    nd.save(str(tmp_path / "w.params"), {"arg:dense_weight": w})
    init = initializer.Load(str(tmp_path / "w.params"),
                            default_init=initializer.Zero())
    target = nd.zeros(shape=(3, 2))
    target.attach_grad()
    init("dense_weight", target)
    onp.testing.assert_array_equal(target.asnumpy(), w.asnumpy())
    other = nd.ones(shape=(4,))
    init("unknown_bias", other)  # falls back to Zero
    assert float(other.asnumpy().sum()) == 0.0
    # shape mismatch is an error, not silent truncation
    import pytest
    with pytest.raises(ValueError, match="shape mismatch"):
        init("dense_weight", nd.zeros(shape=(2, 2)))
    # FusedRNN: weights via inner init, lstm bias gets forget_bias
    fr = initializer.FusedRNN(initializer.One(), num_hidden=4,
                              num_layers=1, mode="lstm", forget_bias=2.0)
    wgt = nd.zeros(shape=(16, 8))
    fr("lstm_i2h_weight", wgt)
    assert float(wgt.asnumpy().mean()) == 1.0
    bias = nd.zeros(shape=(16,))
    fr("lstm_i2h_bias", bias)
    b = bias.asnumpy()
    onp.testing.assert_array_equal(b[4:8], onp.full(4, 2.0))
    assert b[:4].sum() == 0 and b[8:].sum() == 0
    # InitDesc carries attrs + global_init and remains a str
    d = initializer.InitDesc("conv_weight", attrs={"lr_mult": "2"},
                             global_init=initializer.Zero())
    assert d == "conv_weight" and d.attrs["lr_mult"] == "2"


def test_libinfo_error_log_modules():
    """Top-level module tail: libinfo/error/log (reference
    python/mxnet/{libinfo,error,log}.py)."""
    import logging
    import incubator_mxnet_tpu as mx
    libs = mx.libinfo.find_lib_path()
    assert libs and all(p.endswith(".so") for p in libs)
    inc = mx.libinfo.find_include_path()
    import os
    assert os.path.exists(os.path.join(inc, "mxt", "c_api.h"))
    # error hierarchy roots at MXNetError
    assert issubclass(mx.error.InternalError, mx.MXNetError)
    try:
        raise mx.error.ValueError("bad value")
    except mx.MXNetError as e:
        assert "bad value" in str(e)
    # log helper configures once, honors level updates, leaves root alone
    lg = mx.log.get_logger("mxt-test", level=logging.INFO)
    assert lg.level == logging.INFO
    lg2 = mx.log.get_logger("mxt-test")
    assert lg2 is lg and len(lg.handlers) == 1
    root_handlers = list(logging.getLogger().handlers)
    mx.log.get_logger()  # name=None must NOT mutate the root logger
    assert logging.getLogger().handlers == root_handlers
    # one version source of truth
    assert mx.__version__ == mx.libinfo.__version__
    # error classes dual-inherit builtins and native errors dispatch
    try:
        raise mx.error.TypeError("t")
    except TypeError:
        pass
    import ctypes
    from incubator_mxnet_tpu.native import lib, check_call
    rc = lib.MXTRecordIOReaderCreate(b"/definitely/missing.rec",
                                     ctypes.byref(ctypes.c_void_p()))
    import pytest as _pytest
    with _pytest.raises(mx.MXNetError):
        check_call(rc)


def test_batch_processor_and_gradient_update_handler():
    """BatchProcessor customizes the per-batch flow; GradientUpdateHandler
    owns the optimizer step (reference estimator/batch_processor.py,
    event_handler.py GradientUpdateHandler) — gradient accumulation by
    subclassing steps every N batches."""
    import numpy as onp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, gluon
    from incubator_mxnet_tpu.gluon import nn, loss as gloss
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    from incubator_mxnet_tpu.gluon.contrib.estimator import (
        Estimator, BatchProcessor, GradientUpdateHandler)

    X = nd.random.uniform(shape=(32, 6))
    Y = nd.random.uniform(shape=(32, 2))
    loader = DataLoader(ArrayDataset(X, Y), batch_size=8)

    # custom processor: scales the loss (observable through train_loss)
    class HalfLoss(BatchProcessor):
        def fit_batch(self, estimator, batch, batch_axis=0):
            d, l, p, loss = super().fit_batch(estimator, batch, batch_axis)
            return d, l, p, loss * 0.5

    net = nn.Dense(2, in_units=6)
    net.initialize()
    est = Estimator(net, gloss.L2Loss(),
                    trainer=gluon.Trainer(net.collect_params(), "sgd",
                                          {"learning_rate": 0.1}),
                    batch_processor=HalfLoss())
    est.fit(loader, epochs=1)

    # accumulation handler: steps every 2 batches only
    class Accum(GradientUpdateHandler):
        def __init__(self):
            super().__init__()
            self.calls = 0
            self.steps = 0

        def batch_end(self, estimator, *args, **kwargs):
            self.calls += 1
            if self.calls % 2 == 0:
                estimator.trainer.step(estimator._last_batch_size * 2)
                self.steps += 1

    net2 = nn.Dense(2, in_units=6)
    net2.initialize()
    accum = Accum()
    est2 = Estimator(net2, gloss.L2Loss(),
                     trainer=gluon.Trainer(net2.collect_params(), "sgd",
                                           {"learning_rate": 0.1}))
    est2.fit(loader, epochs=1, event_handlers=[accum])
    assert accum.calls == 4 and accum.steps == 2
