"""Tests for the C++ native runtime (src/): recordio wire format, the
dependency engine, the pooled storage manager, and the image-record
pipeline. Mirrors the reference's C++ gtest coverage
(tests/cpp/engine/threaded_engine_test.cc, storage/storage_test.cc) plus
recordio round-trips from tests/python/unittest/test_recordio.py.
"""
import io as pyio
import os
import struct
import threading

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import native, recordio

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library not built")


# ---------------- recordio ---------------------------------------------

def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    records = [b"hello", b"x" * 1000, b"", b"tail-unaligned-7"]
    for r in records:
        w.write(r)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    got = []
    while True:
        rec = r.read()
        if rec is None:
            break
        got.append(rec)
    r.close()
    assert got == records


def test_recordio_magic_payload(tmp_path):
    """Payloads containing the magic word are split + rejoined."""
    magic = struct.pack("<I", 0xced7230a)
    payload = b"abcd" + magic + b"efgh" + magic + magic + b"z"
    path = str(tmp_path / "m.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(payload)
    w.write(magic)  # record that IS the magic
    w.close()
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == payload
    assert r.read() == magic
    assert r.read() is None
    r.close()


def test_recordio_python_native_interop(tmp_path):
    """Native writer → Python reader and vice versa (wire compat)."""
    payload = [b"one", b"two" * 123, struct.pack("<I", 0xced7230a) + b"x"]
    npath = str(tmp_path / "n.rec")
    w = recordio.MXRecordIO(npath, "w")  # native path
    for p in payload:
        w.write(p)
    w.close()
    os.environ["MXNET_NATIVE_LIB_DISABLE"] = "1"
    try:
        import importlib
        # force the pure-python branch by reloading with the lib disabled
        r = recordio.MXRecordIO.__new__(recordio.MXRecordIO)
        r.uri, r.flag = npath, "r"
        r.record = open(npath, "rb")
        r.writable = False
        r._nh = None
        got = [r.read() for _ in range(3)]
        assert got == payload
        r.record.close()
    finally:
        del os.environ["MXNET_NATIVE_LIB_DISABLE"]


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "i.rec")
    idx = str(tmp_path / "i.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(10):
        w.write_idx(i, f"record-{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    assert r.read_idx(7) == b"record-7"
    assert r.read_idx(2) == b"record-2"
    r.close()


def test_pack_unpack_roundtrip(tmp_path):
    header = recordio.IRHeader(0, 3.0, 42, 0)
    s = recordio.pack(header, b"imagebytes")
    h2, body = recordio.unpack(s)
    assert h2.label == 3.0 and h2.id == 42 and body == b"imagebytes"
    # multi-label
    header = recordio.IRHeader(3, [1.0, 2.0, 3.0], 7, 0)
    h3, body = recordio.unpack(recordio.pack(header, b"xy"))
    assert list(h3.label) == [1.0, 2.0, 3.0] and body == b"xy"


# ---------------- engine ------------------------------------------------

def _make_engine():
    from incubator_mxnet_tpu.engine import NativeEngine
    return NativeEngine(num_workers=4)


def test_native_engine_write_ordering():
    eng = _make_engine()
    v = eng.new_variable("x")
    acc = []
    for i in range(50):
        eng.push(lambda i=i: acc.append(i), mutable_vars=(v,))
    eng.wait_for_var(v)
    assert acc == list(range(50))


def test_native_engine_parallel_reads():
    eng = _make_engine()
    v = eng.new_variable("x")
    barrier = threading.Barrier(3, timeout=10)
    hits = []

    def reader():
        barrier.wait()  # all three readers must be in flight at once
        hits.append(1)

    for _ in range(3):
        eng.push(reader, const_vars=(v,))
    eng.wait_for_all()
    assert len(hits) == 3


def test_native_engine_read_write_exclusion():
    eng = _make_engine()
    v = eng.new_variable("x")
    state = {"val": 0}
    seen = []
    eng.push(lambda: state.__setitem__("val", 1), mutable_vars=(v,))
    eng.push(lambda: seen.append(state["val"]), const_vars=(v,))
    eng.push(lambda: state.__setitem__("val", 2), mutable_vars=(v,))
    eng.push(lambda: seen.append(state["val"]), const_vars=(v,))
    eng.wait_for_all()
    assert seen == [1, 2]


def test_native_engine_exception_propagation():
    eng = _make_engine()
    v = eng.new_variable("x")

    def boom():
        raise ValueError("deliberate")

    eng.push(boom, mutable_vars=(v,))
    # dependent op must be skipped, not run
    ran = []
    eng.push(lambda: ran.append(1), const_vars=(v,))
    with pytest.raises(RuntimeError, match="deliberate"):
        eng.wait_for_var(v)
    assert ran == []


def test_native_engine_version_counter():
    import ctypes
    eng = _make_engine()
    v = eng.new_variable("x")
    for _ in range(5):
        eng.push(lambda: None, mutable_vars=(v,))
    eng.wait_for_all()
    out = ctypes.c_uint64()
    native.check_call(native.lib.MXTEngineVarVersion(
        eng._h, v.handle, ctypes.byref(out)))
    assert out.value == 5


# ---------------- storage ----------------------------------------------

def test_storage_pool_recycles():
    import ctypes
    p1 = ctypes.c_void_p()
    native.check_call(native.lib.MXTStorageAlloc(1 << 20, ctypes.byref(p1)))
    native.check_call(native.lib.MXTStorageFree(p1, 1 << 20))
    p2 = ctypes.c_void_p()
    native.check_call(native.lib.MXTStorageAlloc(1 << 20, ctypes.byref(p2)))
    assert p1.value == p2.value  # same buffer came back from the pool
    native.check_call(native.lib.MXTStorageFree(p2, 1 << 20))
    alloc = ctypes.c_uint64()
    pooled = ctypes.c_uint64()
    native.check_call(native.lib.MXTStorageStats(ctypes.byref(alloc),
                                                 ctypes.byref(pooled)))
    assert pooled.value >= 1 << 20
    native.check_call(native.lib.MXTStorageReleaseAll())


# ---------------- image pipeline ---------------------------------------

def _write_jpeg_rec(tmp_path, n=12, size=(40, 32)):
    """Pack n solid-color JPEGs (label = red value / 10) into a .rec."""
    from PIL import Image
    path = str(tmp_path / "img.rec")
    w = recordio.MXRecordIO(path, "w")
    colors = []
    for i in range(n):
        rgb = (i * 10 % 256, (i * 30 + 5) % 256, (i * 7 + 99) % 256)
        img = Image.new("RGB", size, rgb)
        buf = pyio.BytesIO()
        img.save(buf, format="JPEG", quality=95)
        w.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                              buf.getvalue()))
        colors.append(rgb)
    w.close()
    return path, colors


def test_image_record_iter(tmp_path):
    path, colors = _write_jpeg_rec(tmp_path, n=12)
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 16, 16),
                               batch_size=4, shuffle=False,
                               preprocess_threads=2)
    assert it.num_samples == 12
    batches = list(it)
    assert len(batches) == 3
    for b_idx, batch in enumerate(batches):
        data = batch.data[0].asnumpy()
        label = batch.label[0].asnumpy()
        assert data.shape == (4, 3, 16, 16)
        for s in range(4):
            i = b_idx * 4 + s
            assert label[s] == i
            # solid color survives decode+resize to ~the same value
            r, g, b = colors[i]
            got = data[s].mean(axis=(1, 2))
            assert abs(got[0] - r) < 3 and abs(got[1] - g) < 3 \
                and abs(got[2] - b) < 3
    # reset → same again
    it.reset()
    again = list(it)
    assert len(again) == 3


def test_image_record_iter_raw_passthrough(tmp_path):
    """pack_raw "MXTR" records skip JPEG decode in the native pipeline
    (pre-decoded datasets / the IO-overlap bench) — exact passthrough at
    matching geometry, auto-detected per record alongside JPEG."""
    rng = onp.random.RandomState(3)
    path = str(tmp_path / "raw.rec")
    w = recordio.MXRecordIO(path, "w")
    imgs = []
    for i in range(8):
        img = rng.randint(0, 255, (16, 16, 3), dtype=onp.uint8)
        w.write(recordio.pack_raw(recordio.IRHeader(0, float(i), i, 0),
                                  img))
        imgs.append(img)
    w.close()
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 16, 16),
                               batch_size=4, shuffle=False,
                               preprocess_threads=2)
    batch = next(iter(it))
    data = batch.data[0].asnumpy()
    for s in range(4):
        ref = imgs[s].transpose(2, 0, 1).astype(onp.float32)
        onp.testing.assert_array_equal(data[s], ref)
    # python-side inverse
    hdr, img = recordio.unpack_raw(
        recordio.pack_raw(recordio.IRHeader(0, 5.0, 7, 0), imgs[0]))
    assert hdr.label == 5.0 and (img == imgs[0]).all()


def test_image_record_iter_augment_normalize(tmp_path):
    path, colors = _write_jpeg_rec(tmp_path, n=4)
    # reference semantics (iter_normalize.h): out = (px - mean) * scale / std
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 16, 16),
                               batch_size=4, shuffle=False, scale=1 / 255.0,
                               mean_r=127.5, mean_g=127.5, mean_b=127.5,
                               std_r=0.5, std_g=0.5, std_b=0.5)
    batch = next(iter(it))
    data = batch.data[0].asnumpy()
    r0 = colors[0][0]
    expect = (r0 - 127.5) / 255.0 / 0.5
    assert abs(data[0, 0].mean() - expect) < 0.05


def test_image_record_iter_shuffle_epoch(tmp_path):
    path, _ = _write_jpeg_rec(tmp_path, n=16)
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                               batch_size=8, shuffle=True, seed=3)
    labels1 = onp.concatenate([b.label[0].asnumpy() for b in it])
    it.reset()
    labels2 = onp.concatenate([b.label[0].asnumpy() for b in it])
    assert sorted(labels1) == list(range(16))
    assert sorted(labels2) == list(range(16))
    # different epoch order (shuffled), same sample set
    assert not onp.array_equal(labels1, labels2)


def test_imdecode_native():
    from PIL import Image
    import ctypes
    img = Image.new("RGB", (20, 10), (200, 100, 50))
    buf = pyio.BytesIO()
    img.save(buf, format="JPEG", quality=95)
    raw = buf.getvalue()
    h = ctypes.c_int(0)
    w = ctypes.c_int(0)
    native.check_call(native.lib.MXTImdecode(raw, len(raw), None,
                                             ctypes.byref(h), ctypes.byref(w)))
    assert (h.value, w.value) == (10, 20)
    out = onp.empty((10, 20, 3), dtype=onp.uint8)
    native.check_call(native.lib.MXTImdecode(
        raw, len(raw), out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.byref(h), ctypes.byref(w)))
    assert abs(int(out[:, :, 0].mean()) - 200) < 3


# ---------------- im2rec tool ------------------------------------------

def test_im2rec_tool(tmp_path):
    from PIL import Image
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    binary = os.path.join(repo, "tools", "bin", "im2rec")
    if not os.path.exists(binary):
        pytest.skip("im2rec not built")
    imgdir = tmp_path / "imgs"
    imgdir.mkdir()
    lines = []
    for i in range(5):
        name = f"im{i}.jpg"
        Image.new("RGB", (30 + i, 25), (i * 40, 10, 200)).save(
            str(imgdir / name), quality=95)
        lines.append(f"{i}\t{float(i)}\t{name}")
    lst = tmp_path / "list.lst"
    lst.write_text("\n".join(lines) + "\n")
    rec = tmp_path / "out.rec"
    subprocess.run([binary, str(lst), str(imgdir), str(rec)], check=True,
                   capture_output=True)
    # readable by the iterator
    it = mx.io.ImageRecordIter(path_imgrec=str(rec), data_shape=(3, 16, 16),
                               batch_size=5, shuffle=False)
    batch = next(iter(it))
    assert sorted(batch.label[0].asnumpy().tolist()) == [0, 1, 2, 3, 4]


def test_native_engine_push_sync_raises():
    eng = _make_engine()

    def boom():
        raise ValueError("sync-boom")

    with pytest.raises(ValueError, match="sync-boom"):
        eng.push_sync(boom)


def test_native_engine_exception_cleared_after_rethrow():
    eng = _make_engine()
    v = eng.new_variable("x")
    eng.push(lambda: (_ for _ in ()).throw(ValueError("once")),
             mutable_vars=(v,))
    with pytest.raises(RuntimeError, match="once"):
        eng.wait_for_var(v)
    # handled: later waits on the same var succeed (no sticky poison)
    eng.push(lambda: None, mutable_vars=(v,))
    eng.wait_for_var(v)
    eng.wait_for_all()


def test_native_engine_var_deletion():
    eng = _make_engine()
    v = eng.new_variable("tmp")
    hits = []
    eng.push(lambda: hits.append(1), mutable_vars=(v,))
    eng.wait_for_all()
    del v  # __del__ → MXTEngineDeleteVar; freed natively after drain
    eng.wait_for_all()
    assert hits == [1]


def test_image_record_iter_round_batch_pad(tmp_path):
    path, _ = _write_jpeg_rec(tmp_path, n=10)
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                               batch_size=8, shuffle=False, round_batch=True)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].pad == 0
    # tail: 2 real + 6 wrap-around duplicates → pad 6 (num_batch_padd)
    assert batches[1].pad == 6
    assert batches[1].data[0].shape == (8, 3, 8, 8)


def test_native_engine_wait_after_upstream_failure_releases():
    """A waiter on an op skipped due to upstream failure must not hang
    (the callback always fires; engine.cc WorkerLoop)."""
    eng = _make_engine()
    v = eng.new_variable("x")

    def boom():
        raise ValueError("upstream")

    eng.push(boom, mutable_vars=(v,))
    op = eng.push(lambda: None, const_vars=(v,))
    assert op.done.wait(timeout=10), "skipped op never released its waiter"
    assert isinstance(op.exc, RuntimeError)
    with pytest.raises(RuntimeError, match="upstream"):
        eng.wait_for_var(v)
    assert not eng._ops  # no leaked callback registrations


def test_recordio_empty_first_record(tmp_path):
    """Zero-length record at file start must not read as EOF."""
    path = str(tmp_path / "e.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(b"")
    w.write(b"hello")
    w.close()
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == b""
    assert r.read() == b"hello"
    assert r.read() is None
    r.close()


def test_image_record_iter_python_fallback_parity(tmp_path, monkeypatch):
    """The pure-Python fallback applies the same scale/mean/std as the
    native pipeline (no silent behavior drift when the lib is absent)."""
    path, colors = _write_jpeg_rec(tmp_path, n=4)
    kwargs = dict(path_imgrec=path, data_shape=(3, 16, 16), batch_size=4,
                  shuffle=False, scale=1 / 255.0, mean_r=127.5, mean_g=127.5,
                  mean_b=127.5, std_r=0.5, std_g=0.5, std_b=0.5)
    nat = next(iter(mx.io.ImageRecordIter(**kwargs))).data[0].asnumpy()
    import incubator_mxnet_tpu.native as native_mod
    monkeypatch.setattr(native_mod, "lib", None)
    fb_iter = mx.io.ImageRecordIter(**kwargs)
    assert not isinstance(fb_iter, mx.io.NativeImageRecordIter)
    fb = next(iter(fb_iter)).data[0].asnumpy()
    assert fb.shape == nat.shape
    # same normalization applied (decode/resize differ slightly per path)
    onp.testing.assert_allclose(fb.mean(axis=(0, 2, 3)),
                                nat.mean(axis=(0, 2, 3)), atol=0.05)


def test_threadsafe_hybridized_inference():
    """Concurrent inference through one hybridized block (reference
    src/imperative/cached_op_threadsafe.cc +
    tests/cpp/thread_safety/thread_safety_test.cc): N threads share a
    compiled CachedOp; every result must match the single-thread
    output."""
    import threading
    import numpy as onp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, gluon

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, in_units=16, activation="relu"),
            gluon.nn.Dense(8, in_units=32))
    net.initialize()
    net.hybridize()
    xs = [nd.random.uniform(shape=(4, 16)) for _ in range(8)]
    refs = [net(x).asnumpy() for x in xs]

    errors = []
    results = [None] * 64

    def worker(tid):
        try:
            for i in range(8):
                idx = tid * 8 + i
                out = net(xs[i]).asnumpy()
                results[idx] = (i, out)
        except Exception as e:  # pragma: no cover - failure path
            errors.append((tid, e))

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    for idx, (i, out) in enumerate(results):
        onp.testing.assert_allclose(out, refs[i], rtol=1e-5, atol=1e-6,
                                    err_msg=f"slot {idx}")


def test_engine_fork_safety():
    """A forked child gets a fresh engine (reference initialize.h fork
    handlers): host-side scheduling in DataLoader-style workers must not
    deadlock on the parent's worker threads/locks.  (JAX device compute
    is not fork-safe by design — children do host work only.)"""
    import multiprocessing as mp
    from incubator_mxnet_tpu import nd

    nd.ones((2, 2)).asnumpy()  # engine active in the parent

    def child(q):
        from incubator_mxnet_tpu import engine
        eng = engine.get_engine()
        out = []
        v = eng.new_variable("t")
        eng.push_sync(lambda: out.append(21), const_vars=[],
                      mutable_vars=[v])
        eng.wait_for_all()
        q.put(out[0] * 2)

    ctx = mp.get_context("fork")
    q = ctx.Queue()
    p = ctx.Process(target=child, args=(q,))
    p.start()
    p.join(60)
    assert q.get(timeout=10) == 42


def test_cpp_selftest_binary(tmp_path):
    """Pure-C++ runtime self-test (reference tests/cpp role): engine
    ordering/exclusion/exceptions under native threads, storage pool
    recycling, recordio wire, packed-func FFI — no interpreter in the
    loop."""
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bin_path = os.path.join(repo, "tools", "bin", "mxt_selftest")
    try:
        proc = subprocess.run(["make", "-C", os.path.join(repo, "src"),
                               "selftest"], capture_output=True, text=True,
                              timeout=300)
    except FileNotFoundError:
        pytest.skip("no native toolchain (make) available")
    except subprocess.TimeoutExpired:
        raise AssertionError("native selftest build hung (>300s) with a "
                             "working toolchain")
    if proc.returncode != 0:
        # toolchain present: a compile error in checked-in sources is a
        # FAILURE, not a skip (it would otherwise ship silently)
        raise AssertionError(
            f"native selftest failed to build:\n{proc.stderr[-800:]}")
    assert os.path.exists(bin_path)
    run = subprocess.run([bin_path, str(tmp_path)], capture_output=True,
                         text=True, timeout=120)
    assert run.returncode == 0, (run.stdout, run.stderr[-500:])
    assert "native selftest ok" in run.stdout
