"""Packed-function FFI tests (src/ffi.cc + incubator_mxnet_tpu/_ffi).

Reference: the TVM-style new FFI (src/runtime/packed_func.h,
registry.h; python/mxnet/_ffi/) — one calling convention both ways
across the C boundary.
"""
import ctypes

import pytest

from incubator_mxnet_tpu import _ffi

pytestmark = pytest.mark.skipif(not _ffi.available(),
                                reason="native runtime library unavailable")


def test_native_builtins():
    ver = _ffi.get_global_func("mxt.runtime.version")
    assert ver() == 20000
    names = _ffi.list_global_func_names()
    assert {"mxt.runtime.version", "mxt.echo", "mxt.strcat",
            "mxt.storage.allocated"} <= set(names)
    assert isinstance(_ffi.get_global_func("mxt.storage.allocated")(), int)


def test_marshalling_roundtrip():
    echo = _ffi.get_global_func("mxt.echo")
    assert echo(42) == 42
    assert echo(-7) == -7
    assert echo(2.5) == 2.5
    assert echo("hello") == "hello"
    assert echo(None) is None
    assert echo(True) == 1  # bools travel as ints, like the reference


def test_string_ownership_across_boundary():
    strcat = _ffi.get_global_func("mxt.strcat")
    a = strcat("foo", "bar")
    b = strcat("baz", "qux")  # overwrites the thread-local return slot
    assert b == "bazqux"
    assert a == "foobar"      # a was decoded before the second call


def test_unknown_function_errors():
    with pytest.raises(RuntimeError, match="no function"):
        _ffi.get_global_func("mxt.definitely_missing")


def test_native_error_propagates():
    strcat = _ffi.get_global_func("mxt.strcat")
    with pytest.raises(RuntimeError, match="expects"):
        strcat("only-one")


def test_register_python_func_and_call_via_table():
    @_ffi.register_func("test.pyscale", override=True)
    def pyscale(x, k):
        return x * k

    f = _ffi.get_global_func("test.pyscale")
    assert f(6, 7) == 42
    assert f(1.5, 2.0) == 3.0


def test_python_func_callable_from_native_side():
    """C++ code calls frontend-registered functions via
    MXTFuncCallByName — drive that exact entry point."""
    from incubator_mxnet_tpu.native import lib

    @_ffi.register_func("test.greet", override=True)
    def greet(name):
        return "hello " + name

    _ffi._declare()
    vals = (_ffi.MXTValue * 1)()
    codes = (ctypes.c_int * 1)(_ffi.TYPE_STR)
    arg = b"tpu"
    vals[0].v_str = arg
    ret = _ffi.MXTValue()
    ret_code = ctypes.c_int(_ffi.TYPE_NULL)
    lib.MXTFuncCallByName.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(_ffi.MXTValue),
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(_ffi.MXTValue), ctypes.POINTER(ctypes.c_int)]
    rc = lib.MXTFuncCallByName(b"test.greet", vals, codes, 1,
                               ctypes.byref(ret), ctypes.byref(ret_code))
    assert rc == 0
    assert ret_code.value == _ffi.TYPE_STR
    assert ret.v_str == b"hello tpu"


def test_python_exception_becomes_ffi_error():
    @_ffi.register_func("test.boom", override=True)
    def boom():
        raise ValueError("kaput")

    f = _ffi.get_global_func("test.boom")
    with pytest.raises(RuntimeError, match="kaput"):
        f()


def test_double_registration_guard():
    @_ffi.register_func("test.once", override=True)
    def once():
        return 1

    with pytest.raises(RuntimeError, match="already registered"):
        _ffi.register_func("test.once", lambda: 2)
    # override replaces
    _ffi.register_func("test.once", lambda: 3, override=True)
    assert _ffi.get_global_func("test.once")() == 3
