"""Accuracy-parity convergence test (VERDICT r4 Next #4).

Reference analog: tests/python/train/test_conv.py trains LeNet-MNIST to
an asserted 0.98 top-1.  Offline (zero-egress) real-data analog here:
scikit-learn's 1797 genuine handwritten digits, trained through the
full stack (HybridBlock -> hybridize -> DataLoader -> Trainer(kvstore
'device')) to an asserted >=0.97 held-out top-1.

Nightly-gated (~2.5 min CPU) like the reference's train suite; the
committed artifact from a full run is artifacts/r5/accuracy_digits_*.txt.
A fast 8-epoch sanity leg always runs: real data must reach >=0.80 —
random guessing is 0.10, so this still proves genuine convergence.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _train_digits(epochs, target):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "train_mnist.py"),
         "--dataset", "digits", "--epochs", str(epochs),
         "--target-acc", str(target)],
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, (proc.stdout[-500:], proc.stderr[-500:])
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("RESULT digits_test_top1")][0]
    return float(line.split()[2])


def test_digits_quick_convergence():
    acc = _train_digits(epochs=8, target=0.80)
    assert acc >= 0.80, acc


@pytest.mark.skipif(os.environ.get("MXNET_TEST_NIGHTLY") != "1",
                    reason="nightly: full 40-epoch accuracy-parity run")
def test_digits_accuracy_parity_nightly():
    acc = _train_digits(epochs=40, target=0.97)
    assert acc >= 0.97, acc
