"""tools/bandwidth.py — collective-bandwidth probe (reference
tools/bandwidth/measure.py role) on the 8-device CPU mesh."""
import os
import sys

import jax
import numpy as onp
from jax.sharding import Mesh

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import bandwidth  # noqa: E402


def _mesh():
    return Mesh(onp.array(jax.devices()), ("x",))


def test_psum_collective_correct_and_timed():
    import jax.numpy as jnp
    mesh = _mesh()
    n = mesh.shape["x"]
    jitted = bandwidth._mk_collective("psum", mesh)
    x = jnp.arange(8 * n, dtype=jnp.float32)
    out = jitted(x)
    # psum over the mesh axis: every shard becomes the sum of all shards
    shards = onp.asarray(x).reshape(n, -1)
    expect = onp.tile(shards.sum(0), n)
    onp.testing.assert_allclose(onp.asarray(out), expect, rtol=1e-6)
    dt = bandwidth._time_collective(jitted, x, iters=2, warmup=1)
    assert dt > 0


def test_sweep_rows_and_algo_factors():
    args = bandwidth.parse_args(
        ["--min-mb", "0.05", "--max-mb", "0.05", "--iters", "2",
         "--warmup", "1", "--collectives", "psum,all_gather"])
    rows = bandwidth.run_sweep(args, _mesh())
    assert {r["collective"] for r in rows} == {"psum", "all_gather"}
    assert all(r["algo_gb_s"] > 0 for r in rows)
    n = 8
    assert bandwidth.ALGO_FACTOR["psum"](n) == 2 * (n - 1) / n
    assert bandwidth.ALGO_FACTOR["all_gather"](n) == (n - 1) / n
