"""Autoscaling control-plane tests (ISSUE 12): scale-from-zero, HBM
bin-packing with LRU eviction, SLO classes, session-aware shrink.

The contract under test (docs/serving.md "Autoscaling"): a
level-triggered loop over the router's own metrics grows/shrinks the
fleet per model — idle models unload (scale-to-zero) and the first
request after pays a sub-second AOT reload; models pack onto replicas
under memlint's peak-HBM budget with LRU eviction (higher SLO tiers
are never the victim); a replica holding sessions drains via
snapshot-migrate before a shrink closes it.  The ``autoscale`` CI
stage re-runs this file under a pinned seeded chaos spec with errors
on ``serving.scale`` — every convergence assertion below loops with a
deadline instead of counting ticks, so a dropped decision only delays
it.

Kept deliberately lean for the tier-1 budget: thread backend only,
two 16-wide MLP artifacts exported once per module (AOT buckets, so
every load in this file is deserialization), buckets [1, 2].
"""
import json
import threading
import time

import numpy as onp
import pytest

from incubator_mxnet_tpu import fault
from incubator_mxnet_tpu.error import ModelEvictedError
from incubator_mxnet_tpu.serving import (Autoscaler, FleetRouter,
                                         ModelPolicy, Placer,
                                         ReplicaFleet)
from incubator_mxnet_tpu.serving.admission import (Admission,
                                                   QueueFullError,
                                                   slo_class)
from incubator_mxnet_tpu.serving.batcher import WeightedFairGate
from incubator_mxnet_tpu.serving.placement import (Placer as _Placer,
                                                   model_footprint_bytes)

WIDTH = 16
BUCKETS = [1, 2]


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """Two tiny AOT-covered artifacts: every load below is
    deserialization, which is what makes scale-from-zero cheap."""
    import jax.numpy as jnp
    from incubator_mxnet_tpu import deploy

    tmp = tmp_path_factory.mktemp("autoscale_artifacts")

    def export(name, seed):
        def fwd(params, x):
            return jnp.tanh(x @ params["w"])
        rng = onp.random.RandomState(seed)
        p = {"w": rng.randn(WIDTH, WIDTH).astype(onp.float32)}
        x = rng.randn(1, WIDTH).astype(onp.float32)
        prefix = str(tmp / name)
        deploy.export_model(fwd, (x,), prefix, params=p,
                            aot_buckets=BUCKETS)
        return prefix

    return {"a": export("a", 0), "b": export("b", 1)}


def _x(seed=3):
    return (onp.random.RandomState(seed)
            .randn(WIDTH).astype(onp.float32),)


def _stack(artifacts, budget_bytes=0, max_replicas=2, n=1,
           idle_unload_s=300.0, policies=("a", "b"), slos=None):
    """Fleet + router + autoscaler, prober parked, tick driven by the
    tests (run_once) — deterministic under chaos."""
    fleet = ReplicaFleet({}, n=n, backend="thread", buckets=BUCKETS,
                         probe_ms=60000.0).spawn()
    router = FleetRouter(fleet)
    scaler = Autoscaler(fleet, router=router,
                        placer=Placer(budget_bytes=budget_bytes),
                        interval_s=0.05, idle_unload_s=idle_unload_s,
                        queue_high=4.0, max_replicas=max_replicas,
                        min_fleet=1)
    slos = slos or {}
    for name in policies:
        scaler.add_policy(ModelPolicy(
            name, artifacts[name],
            slo=slos.get(name, "standard"), min_replicas=0))
    return fleet, router, scaler


def _converge(cond, scaler=None, deadline_s=15.0, what="condition"):
    """Level-triggered convergence: tick until ``cond()`` — under the
    chaos spec a decision may drop, so we never count ticks."""
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        if cond():
            return
        if scaler is not None:
            scaler.run_once()
        time.sleep(0.02)
    raise AssertionError(f"{what} did not converge in {deadline_s}s")


# ---------------------------------------------------------------------------
# placement: footprints + bin-packing (pure, no fleet)
# ---------------------------------------------------------------------------

def test_footprint_from_memlint_meta(tmp_path, artifacts):
    # a real export carries its memlint peak-HBM plan
    nbytes = model_footprint_bytes(artifacts["a"])
    assert nbytes > 0
    with open(artifacts["a"] + ".meta.json") as f:
        assert nbytes == json.load(f)["memlint"]["peak_hbm_bytes"]
    # no meta / no plan -> the documented default
    assert model_footprint_bytes(
        str(tmp_path / "nope"), default=123) == 123
    (tmp_path / "bare.meta.json").write_text("{}")
    assert model_footprint_bytes(
        str(tmp_path / "bare"), default=77) == 77


def test_placer_best_fit_under_budget():
    p = _Placer(budget_bytes=100)
    p.register_replica("r0")
    p.register_replica("r1")
    p.record_load("r0", "m0", 70)
    # best-fit: r0 has 30 free, r1 has 100 — a 25-byte model goes to
    # the tighter hole, keeping r1's big hole for big models
    rid, ev = p.choose("m1", 25, ["r0", "r1"])
    assert (rid, ev) == ("r0", [])
    rid, ev = p.choose("m2", 80, ["r0", "r1"])
    assert (rid, ev) == ("r1", [])
    p.record_load("r1", "m2", 80)
    # no fit and evict=False: spawn-beats-evict probe answers None
    rid, ev = p.choose("m3", 50, ["r0", "r1"], evict=False)
    assert rid is None and ev == []
    # larger than the whole budget: never placeable
    rid, ev = p.choose("huge", 101, ["r0", "r1"])
    assert rid is None


def test_placer_lru_eviction_and_protection():
    p = _Placer(budget_bytes=100)
    p.register_replica("r0")
    p.record_load("r0", "old", 60)
    p.record_load("r0", "hot", 40)
    idle = {"old": 500.0, "hot": 1.0}
    rid, ev = p.choose("new", 30, ["r0"],
                       idle_s_fn=lambda m: idle[m])
    assert rid == "r0" and ev == ["old"]   # LRU goes first
    # a protected tenant is never the victim, even if idler
    rid, ev = p.choose("new", 30, ["r0"],
                       idle_s_fn=lambda m: idle[m],
                       protected={"old"})
    assert rid == "r0" and ev == ["hot"]
    rid, ev = p.choose("new", 30, ["r0"],
                       idle_s_fn=lambda m: idle[m],
                       protected={"old", "hot"})
    assert rid is None                      # nothing evictable
    # evicting more than needed never happens: one victim sufficed
    rid, ev = p.choose("big", 90, ["r0"],
                       idle_s_fn=lambda m: idle[m])
    assert rid == "r0" and ev == ["old", "hot"]  # both must go


# ---------------------------------------------------------------------------
# SLO classes: shed order + weighted fair queueing (pure)
# ---------------------------------------------------------------------------

def test_slo_depth_bounds_shed_low_first():
    adm = Admission(queue_depth=8)
    assert adm.shed_fraction == 0.5
    hi = slo_class("interactive")
    std = slo_class("standard")
    low = slo_class("batch")
    # the default class admits at the FULL bound — loading a model
    # without an slo must not change pre-SLO admission behavior
    assert slo_class(None) is std
    assert hi.depth_bound(8, 0.5) == 8
    assert std.depth_bound(8, 0.5) == 8
    assert low.depth_bound(8, 0.5) == 4
    # at depth 4: batch sheds 429, interactive + standard admit
    with pytest.raises(QueueFullError):
        adm.gate("m", slo=low)(4)
    adm.gate("m", slo=std)(4)
    adm.gate("m", slo=hi)(4)
    adm.gate("m", slo=std)(7)
    with pytest.raises(QueueFullError):
        adm.gate("m", slo=std)(8)
    with pytest.raises(QueueFullError):
        adm.gate("m", slo=hi)(8)
    # unknown class is a 400-shaped error at the policy boundary
    from incubator_mxnet_tpu.serving.admission import BadRequest
    with pytest.raises(BadRequest):
        slo_class("platinum")


def test_wfq_gate_weighted_order():
    gate = WeightedFairGate()
    hold = gate.acquire("warm", 1.0)      # park the gate
    order = []
    started = []

    def worker(key, weight):
        started.append(key)
        tok = gate.acquire(key, weight)
        order.append(key)
        gate.release(tok)

    threads = []
    # three heavy batch-tier launches enqueue FIRST...
    for i in range(3):
        t = threading.Thread(target=worker, args=("batch", 1.0))
        t.start()
        threads.append(t)
        while len(started) < i + 1:
            time.sleep(0.001)
        time.sleep(0.01)
    # ...then three interactive ones
    for i in range(3):
        t = threading.Thread(target=worker, args=("inter", 4.0))
        t.start()
        threads.append(t)
        while len(started) < 4 + i:
            time.sleep(0.001)
        time.sleep(0.01)
    gate.release(hold)
    for t in threads:
        t.join(5.0)
    # virtual finish times: inter at 0.25/0.5/0.75, batch at 1/2/3 —
    # the 4x-weighted tier is served first despite arriving last, and
    # the tail is the starved-in-proportion batch queue
    assert order == ["inter", "inter", "inter",
                     "batch", "batch", "batch"], order


def test_repository_load_carries_slo(artifacts):
    from incubator_mxnet_tpu.serving import ModelRepository
    repo = ModelRepository(buckets=BUCKETS)
    try:
        desc = repo.load("a", artifacts["a"], slo="interactive",
                         warmup=False)
        assert desc["slo"] == "interactive"
        entry = repo.get("a")
        assert entry.batcher.weight == 4.0
        assert entry.batcher.exec_gate is repo.exec_gate
        # reload keeps the class unless told otherwise
        assert repo.reload("a")["slo"] == "interactive"
    finally:
        repo.drain_all()


# ---------------------------------------------------------------------------
# multi-tenant fleet verbs
# ---------------------------------------------------------------------------

def test_fleet_pick_by_model_spawn_one_remove(artifacts):
    fleet = ReplicaFleet({}, n=1, backend="thread", buckets=BUCKETS,
                         probe_ms=60000.0).spawn()
    try:
        r0 = fleet.replicas[0]
        r0.admin("load", "a", path=artifacts["a"])
        r1 = fleet.spawn_one(models={})
        r1.admin("load", "b", path=artifacts["b"])
        assert r0.has_model("a") and not r0.has_model("b")
        assert [r.rid for r in fleet.routable("a")] == [r0.rid]
        assert [r.rid for r in fleet.routable("b")] == [r1.rid]
        assert fleet.pick(name="a") is r0
        assert fleet.pick(name="b") is r1
        assert fleet.pick(name="a", exclude={r0.rid}) is r0  # fallback
        st = fleet.states()[r0.rid]
        assert st["models"] == ["a"]
        # the probe contract is per-replica: each owes only its own set
        fleet.probe_once()
        assert r0.healthy and r1.healthy
        fleet.remove(r1.rid)
        assert [r.rid for r in fleet.replicas] == [r0.rid]
        assert fleet.pick(name="b") is None
    finally:
        fleet.shutdown()


# ---------------------------------------------------------------------------
# the control loop
# ---------------------------------------------------------------------------

def test_desired_is_level_triggered():
    """Pure decision math: one step per tick, idle collapse to the
    floor, on-demand models stay at zero until traffic."""
    fleet = ReplicaFleet({}, n=1, backend="thread", buckets=BUCKETS,
                         probe_ms=60000.0).spawn()
    try:
        scaler = Autoscaler(fleet, placer=Placer(budget_bytes=0),
                            interval_s=0.05, idle_unload_s=10.0,
                            queue_high=4.0, max_replicas=4)
        scaler.add_policy(ModelPolicy("m", "/nope", min_replicas=0,
                                      footprint_bytes=1))
        sig = lambda **kw: {"m": {"queued": 0, "inflight": 0,
                                  "p99_ms": 0.0, "idle_s": 0.0,
                                  "actual": 1, **kw}}
        # backlog over the high-water mark: one step up
        assert scaler.desired(sig(queued=5))["m"] == 2
        assert scaler.desired(sig(queued=9, actual=2))["m"] == 3
        # hard cap
        assert scaler.desired(sig(queued=99, actual=4))["m"] == 4
        # light load holds; collapsed load steps down by one
        assert scaler.desired(sig(queued=2))["m"] == 1
        assert scaler.desired(sig(queued=0, actual=3))["m"] == 2
        # idle past the unload threshold: straight to the floor
        assert scaler.desired(sig(idle_s=11.0))["m"] == 0
        # scaled to zero stays there (the on-demand path owns wakeup)
        assert scaler.desired(sig(actual=0))["m"] == 0
    finally:
        fleet.shutdown()


def test_scale_from_zero_first_request(artifacts):
    fleet, router, scaler = _stack(artifacts)
    try:
        assert scaler.actual("a") == 0
        t0 = time.monotonic()
        out, _ = router.route("a", _x())
        first_ms = (time.monotonic() - t0) * 1000.0
        assert scaler.actual("a") == 1
        # the AOT path: nothing compiled, anywhere, at any point
        assert sum(sum(r.repository.compile_counts().values())
                   for r in fleet.replicas) == 0
        desc = scaler.describe()
        assert desc["models"]["a"]["scale_from_zero_ms"] is not None
        assert desc["decisions"]["scale_from_zero"] >= 1
        # generous CPU bound; the bench pins the honest 1.5s number
        assert first_ms < 10000.0
        # second request rides the warm copy
        router.route("a", _x())
    finally:
        router.shutdown()


def test_concurrent_scale_from_zero_respects_replica_ceiling(artifacts):
    """ISSUE 13 satellite: two on-demand ``ensure_loaded`` calls racing
    a replica spawn used to each see the pre-spawn fleet size and
    jointly overshoot MXNET_SERVING_SCALE_MAX_REPLICAS by one.  The
    ceiling check now consumes a reservation under the planner's lock:
    the loser waits (typed, retryable) and places onto the replica the
    winner's spawn lands — never a second spawn past the ceiling."""
    fleet, router, scaler = _stack(artifacts, max_replicas=1)
    try:
        # empty the fleet: 0 live replicas, ceiling 1 — both loads
        # below need the same single spawn slot
        fleet.kill(fleet.replicas[0].rid)
        orig = fleet.spawn_one

        def slow_spawn(models=None):
            time.sleep(0.1)       # hold the race window open
            return orig(models=models)

        fleet.spawn_one = slow_spawn
        errs = []

        def load(name):
            try:
                # DEFAULT retry budget on purpose: the wait_spawn path
                # blocks until the in-flight spawn lands, so the loser
                # must succeed without an inflated retry count
                scaler.ensure_loaded(name)
            except Exception as e:  # noqa: BLE001 — collected and asserted below
                errs.append(e)

        ts = [threading.Thread(target=load, args=(n,))
              for n in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30.0)
        live = [r for r in fleet.replicas if r.state != "dead"]
        assert len(live) == 1, (
            f"spawn race overshot the ceiling: {len(live)} live "
            f"replicas with max_replicas=1")
        assert not errs, errs
        # the loser placed onto the winner's replica (budget is
        # unlimited here) — both models serve from the one copy
        assert fleet.routable("a") and fleet.routable("b")
        assert scaler.describe()["decisions"]["spawn"] == 1
    finally:
        fleet.spawn_one = orig
        router.shutdown()


def test_stop_racing_demand_spawn_removes_the_replica(artifacts):
    """stop() landing while an on-demand ``ensure_loaded`` spawn is in
    flight must not leak the replica into a torn-down fleet: the
    demand path carries the same guard as the background loop's
    _apply_one — remove + forget, and the caller gets a typed
    FleetDrainingError (shutdown is not retryable)."""
    from incubator_mxnet_tpu.error import FleetDrainingError

    fleet, router, scaler = _stack(artifacts, max_replicas=2)
    orig = fleet.spawn_one
    try:
        fleet.kill(fleet.replicas[0].rid)   # force the spawn path
        entered = threading.Event()

        def slow_spawn(models=None):
            entered.set()
            time.sleep(0.2)                 # hold the race window open
            return orig(models=models)

        fleet.spawn_one = slow_spawn
        errs = []

        def load():
            try:
                scaler.ensure_loaded("a")
            except Exception as e:  # noqa: BLE001 — asserted below
                errs.append(e)

        t = threading.Thread(target=load)
        t.start()
        assert entered.wait(10.0)
        scaler.stop()                       # races the in-flight spawn
        t.join(30.0)
        assert len(errs) == 1 and isinstance(errs[0], FleetDrainingError), errs
        live = [r for r in fleet.replicas if r.state != "dead"]
        assert not live, f"stop() leaked a live replica: {live}"
        assert not fleet.routable("a")
    finally:
        fleet.spawn_one = orig
        router.shutdown()


def test_idle_unload_then_reload_on_demand(artifacts):
    fleet, router, scaler = _stack(artifacts, idle_unload_s=0.3)
    try:
        router.route("a", _x())
        assert scaler.actual("a") == 1
        time.sleep(0.4)
        _converge(lambda: scaler.actual("a") == 0, scaler,
                  what="idle unload")
        assert scaler.describe()["decisions"]["scale_down"] >= 1
        # the model is still in the catalog and comes back on demand
        code, body = router.health()
        assert "a" in body["models"]
        router.route("a", _x())
        assert scaler.actual("a") == 1
    finally:
        router.shutdown()


def test_scale_up_under_load_and_back(artifacts):
    fleet, router, scaler = _stack(artifacts, max_replicas=2,
                                   idle_unload_s=0.3)
    try:
        router.route("a", _x())
        stop = threading.Event()

        def client():
            x = _x()
            while not stop.is_set():
                try:
                    router.route("a", x, deadline_ms=5000.0)
                except ConnectionError:
                    time.sleep(0.01)

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(8)]
        for t in threads:
            t.start()
        _converge(lambda: scaler.actual("a") >= 2, scaler,
                  what="scale-up under load")
        assert len(fleet.replicas) == 2
        stop.set()
        for t in threads:
            t.join(5.0)
        time.sleep(0.4)
        _converge(lambda: scaler.actual("a") == 0
                  and len(fleet.replicas) == 1, scaler,
                  what="scale back to the floor")
        assert scaler.describe()["decisions"]["shrink"] >= 1
    finally:
        router.shutdown()


def test_budget_eviction_lru_with_tier_protection(artifacts):
    nbytes = model_footprint_bytes(artifacts["a"])
    fleet, router, scaler = _stack(
        artifacts, budget_bytes=nbytes + 64, max_replicas=1,
        slos={"a": "interactive", "b": "batch"})
    try:
        router.route("a", _x())
        assert scaler.actual("a") == 1
        # b arrives: one slot, fleet at ceiling — but a is interactive
        # AND active, so it is protected: b cannot be placed, typed
        with pytest.raises(ModelEvictedError) as ei:
            router.route("b", _x())
        assert isinstance(ei.value, ConnectionError)
        assert scaler.actual("a") == 1
        # once a is idle (desired 0), b's load LRU-evicts it
        scaler.idle_unload_s = 0.1
        time.sleep(0.2)
        router.route("b", _x())
        assert scaler.actual("b") == 1
        assert scaler.actual("a") == 0
        assert scaler.describe()["evictions"].get("a", 0) >= 1
    finally:
        router.shutdown()


def test_one_tick_cannot_overcommit_budget(artifacts):
    """Two models crossing the threshold in ONE tick must not be
    planned into the same free bytes: grow plans reserve their budget
    at plan time, so the second plan sees the first's claim and is
    blocked (typed / counted), never co-loaded past the budget."""
    nbytes = model_footprint_bytes(artifacts["a"])
    fleet = ReplicaFleet({}, n=1, backend="thread", buckets=BUCKETS,
                         probe_ms=60000.0).spawn()
    try:
        placer = Placer(budget_bytes=nbytes + 64)   # fits exactly one
        scaler = Autoscaler(fleet, placer=placer, interval_s=0.05,
                            idle_unload_s=300.0, max_replicas=1)
        # min_replicas=1 makes both desired=1 from a cold start — the
        # same-tick double-grow the reservation exists for
        scaler.add_policy(ModelPolicy("a", artifacts["a"],
                                      min_replicas=1))
        scaler.add_policy(ModelPolicy("b", artifacts["b"],
                                      min_replicas=1))
        _converge(lambda: scaler.actual("a") + scaler.actual("b") >= 1,
                  scaler, what="first placement")
        for _ in range(4):
            scaler.run_once()
        used = placer.used_bytes(fleet.replicas[0].rid)
        assert used <= placer.budget_bytes, \
            f"budget overcommitted: {used} > {placer.budget_bytes}"
        assert scaler.actual("a") + scaler.actual("b") == 1
        assert scaler.describe()["decisions"]["blocked"] >= 1
    finally:
        fleet.shutdown()


def test_scale_fault_drops_decision_not_loop(artifacts):
    """An injected serving.scale fault drops ONE tick's decision; the
    level-triggered loop re-derives and converges (the autoscale CI
    stage pins the seeded version of this)."""
    fleet, router, scaler = _stack(artifacts, idle_unload_s=0.2)
    try:
        router.route("a", _x())
        fault.configure("serving.scale:error")   # every decision
        time.sleep(0.3)
        before = scaler.actual("a")
        for _ in range(4):
            scaler.run_once()
        assert scaler.actual("a") == before      # all dropped, typed
        assert scaler.describe()["decisions"]["faults"] >= 1
        fault.configure("serving.scale:delay:ms=2")  # laggy, not lost
        _converge(lambda: scaler.actual("a") == 0, scaler,
                  what="convergence under scale delays")
    finally:
        fault.reset()
        router.shutdown()


def test_shrink_waits_for_sessions(artifacts):
    """A replica with live sessions is never a shrink victim; once
    its sessions close, it drains and goes (snapshot-migrate safety
    is PR 11's machinery — what this loop owes is the ordering)."""
    fleet = ReplicaFleet({}, n=2, backend="thread", buckets=BUCKETS,
                         probe_ms=60000.0, warmup=False,
                         session_models={
                             "dec": "toy_decoder:dim=8,max_len=16"},
                         ).spawn()
    router = FleetRouter(fleet)
    scaler = Autoscaler(fleet, router=router,
                        placer=Placer(budget_bytes=0),
                        interval_s=0.05, idle_unload_s=300.0,
                        max_replicas=2, min_fleet=1)
    try:
        info = router.session_create("dec")
        owner = info["replica"]
        # both replicas are model-empty; only the session-free one may
        # shrink — and the floor keeps the fleet at one
        _converge(lambda: len(fleet.replicas) == 1, scaler,
                  what="shrink of the empty replica")
        assert fleet.replicas[0].rid == owner, \
            "the session-holding replica must survive the shrink"
        # the surviving replica still steps the session
        router.session_step("dec", info["session_id"],
                            (onp.full(8, 0.1, onp.float32),))
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# observability: additive shapes + gauges
# ---------------------------------------------------------------------------

def test_healthz_and_describe_autoscale_shape(artifacts):
    """The additive JSON-shape pin (satellite): existing keys
    unchanged (test_fleet pins the bare-router shape), the
    ``autoscale`` block appears only with a control plane attached,
    with this exact schema."""
    from incubator_mxnet_tpu import flightrec
    fleet, router, scaler = _stack(artifacts)
    # flight recording off for the exact-shape pins below (its block
    # is additive and pinned by tests/test_flightrec.py)
    flightrec.configure(ring=0)
    try:
        router.route("a", _x())
        code, body = router.health()
        assert code == 200
        assert set(body) == {"status", "uptime_s", "ready", "replicas",
                             "models", "autoscale"}
        assert body["models"] == ["a", "b"]   # catalog incl. scaled-to-0
        auto = body["autoscale"]
        assert set(auto) == {"models", "decisions", "evictions",
                             "replicas", "shrinking",
                             "replica_seconds", "budget_bytes",
                             "interval_s", "idle_unload_s"}
        assert set(auto["models"]["a"]) == {
            "desired", "actual", "slo", "min_replicas",
            "scale_from_zero_ms"}
        assert auto["models"]["a"]["actual"] == 1
        desc = router.describe()
        assert {"replicas", "ready", "models", "sessions",
                "autoscale"} <= set(desc)
        assert desc["autoscale"]["models"]["a"]["actual"] == 1
    finally:
        flightrec.reset()
        router.shutdown()


def test_fleet_metrics_autoscale_and_idle_gauges(artifacts):
    fleet, router, scaler = _stack(artifacts)
    try:
        router.route("a", _x())
        page = router.metrics.render()
        assert 'mxnet_serving_autoscale_desired_replicas{model="a"}' \
            in page
        assert 'mxnet_serving_autoscale_actual_replicas{model="a"} 1' \
            in page
        assert 'mxnet_serving_autoscale_decisions_total' in page
        assert 'mxnet_serving_model_idle_seconds{model="a"}' in page
        assert 'mxnet_serving_fleet_model_requests_total{model="a",' \
            'code="200"} 1' in page
        assert "mxnet_serving_autoscale_replica_seconds_total" in page
        snap = router.metrics.snapshot()
        assert snap["models"]["a"]["requests"] == 1
        assert snap["models"]["a"]["idle_s"] < 60.0
        assert snap["autoscale"]["models"]["a"]["actual"] == 1
        # the idle signal the scaler consumes
        assert router.metrics.model_idle_s("a") < 60.0
        assert router.metrics.model_idle_s("never-routed") >= 0.0
    finally:
        router.shutdown()


def test_serving_metrics_idle_gauges():
    """Satellite: per-model idle-seconds / last-request gauges in the
    single-server ServingMetrics too (standalone /metrics value)."""
    from incubator_mxnet_tpu.serving.metrics import ServingMetrics
    m = ServingMetrics()
    assert m.last_request_uptime_s("m") is None
    m.record_request("m", 200, e2e_ms=1.0)
    idle = m.idle_seconds("m")
    assert 0.0 <= idle < 60.0
    assert m.idle_seconds()["m"] == pytest.approx(idle, abs=5.0)
    last = m.last_request_uptime_s("m")
    assert last is not None and last >= 0.0
    page = m.render()
    assert 'mxnet_serving_model_idle_seconds{model="m"}' in page
    assert ('mxnet_serving_model_last_request_uptime_seconds'
            '{model="m"}') in page
    snap = m.snapshot()
    assert "m.idle_s" in snap
