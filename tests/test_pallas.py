"""Pallas kernel correctness vs jnp references (interpret mode on CPU —
identical kernel code paths as on TPU, per ops/pallas_kernels.py)."""
import os

import jax
import jax.numpy as jnp
import numpy as onp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from incubator_mxnet_tpu.ops import pallas_kernels as pk


def _rand(*shape, dtype=jnp.float32, seed=0):
    return jnp.asarray(onp.random.RandomState(seed).randn(*shape), dtype)


# ---------------- softmax ----------------------------------------------

@pytest.mark.parametrize("shape,axis", [
    ((4, 10), -1), ((3, 5, 7), -1), ((6, 130), -1), ((2, 3, 129), 1),
])
def test_fused_softmax_matches_jnp(shape, axis):
    x = _rand(*shape)
    got = pk.fused_softmax(x, axis)
    want = jax.nn.softmax(x, axis=axis)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=1e-5, atol=1e-6)


def test_fused_softmax_grad():
    x = _rand(5, 33, seed=1)

    def f_pallas(x):
        return (pk.fused_softmax(x, -1) * jnp.arange(33)).sum()

    def f_ref(x):
        return (jax.nn.softmax(x, axis=-1) * jnp.arange(33)).sum()

    onp.testing.assert_allclose(onp.asarray(jax.grad(f_pallas)(x)),
                                onp.asarray(jax.grad(f_ref)(x)),
                                rtol=1e-4, atol=1e-6)


def test_fused_softmax_extreme_values():
    x = jnp.asarray([[1e4, 1e4 + 1, -1e4], [0.0, 0.0, 0.0]], jnp.float32)
    got = pk.fused_softmax(x, -1)
    want = jax.nn.softmax(x, axis=-1)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=1e-5, atol=1e-6)


# ---------------- layer norm -------------------------------------------

@pytest.mark.parametrize("shape", [(4, 16), (2, 3, 20), (5, 128), (3, 257)])
def test_fused_layer_norm_matches_reference(shape):
    x = _rand(*shape, seed=2)
    c = shape[-1]
    gamma = _rand(c, seed=3) * 0.1 + 1.0
    beta = _rand(c, seed=4) * 0.1
    got = pk.fused_layer_norm(x, gamma, beta, 1e-5)

    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    want = (x - mean) * jax.lax.rsqrt(var + 1e-5) * gamma + beta
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=1e-4, atol=1e-5)


def test_fused_layer_norm_grads():
    x = _rand(6, 37, seed=5)
    gamma = _rand(37, seed=6) * 0.2 + 1.0
    beta = _rand(37, seed=7) * 0.2

    def f_pallas(x, g, b):
        return (pk.fused_layer_norm(x, g, b, 1e-5) ** 2).sum()

    def f_ref(x, g, b):
        mean = x.mean(-1, keepdims=True)
        var = ((x - mean) ** 2).mean(-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + 1e-5) * g + b
        return (y ** 2).sum()

    got = jax.grad(f_pallas, argnums=(0, 1, 2))(x, gamma, beta)
    want = jax.grad(f_ref, argnums=(0, 1, 2))(x, gamma, beta)
    for g_, w_ in zip(got, want):
        onp.testing.assert_allclose(onp.asarray(g_), onp.asarray(w_),
                                    rtol=1e-3, atol=1e-4)


# ---------------- flash attention --------------------------------------

def _attn_ref(q, k, v, causal):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = jnp.arange(tk)[None, :] <= jnp.arange(tq)[:, None]
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,d", [(64, 32), (200, 64)])
def test_flash_attention_matches_reference(causal, t, d):
    q = _rand(2, 3, t, d, seed=8) * 0.5
    k = _rand(2, 3, t, d, seed=9) * 0.5
    v = _rand(2, 3, t, d, seed=10)
    got = pk.flash_attention(q, k, v, causal=causal)
    want = _attn_ref(q, k, v, causal)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=1e-4, atol=1e-4)


def test_flash_attention_cross_lengths():
    q = _rand(1, 2, 70, 32, seed=11) * 0.5
    k = _rand(1, 2, 150, 32, seed=12) * 0.5
    v = _rand(1, 2, 150, 32, seed=13)
    got = pk.flash_attention(q, k, v)
    want = _attn_ref(q, k, v, False)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads(causal):
    q = _rand(1, 2, 96, 32, seed=14) * 0.5
    k = _rand(1, 2, 96, 32, seed=15) * 0.5
    v = _rand(1, 2, 96, 32, seed=16)

    def f_pallas(q, k, v):
        return (pk.flash_attention(q, k, v, causal=causal) ** 2).sum()

    def f_ref(q, k, v):
        return (_attn_ref(q, k, v, causal) ** 2).sum()

    got = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for g_, w_ in zip(got, want):
        onp.testing.assert_allclose(onp.asarray(g_), onp.asarray(w_),
                                    rtol=2e-3, atol=2e-4)


def test_flash_attention_under_jit_and_vmap():
    q = _rand(2, 2, 64, 32, seed=17) * 0.5
    k = _rand(2, 2, 64, 32, seed=18) * 0.5
    v = _rand(2, 2, 64, 32, seed=19)
    jitted = jax.jit(lambda q, k, v: pk.flash_attention(q, k, v, causal=True))
    onp.testing.assert_allclose(onp.asarray(jitted(q, k, v)),
                                onp.asarray(_attn_ref(q, k, v, True)),
                                rtol=1e-4, atol=1e-4)


def test_nn_ops_dispatch_to_pallas(monkeypatch):
    """ops.softmax / ops.layer_norm route through the Pallas kernels when
    MXNET_USE_PALLAS=1 and produce reference results."""
    from incubator_mxnet_tpu.ops import nn_ops
    pk.reload_manifest()
    monkeypatch.setenv("MXNET_USE_PALLAS", "1")
    try:
        x = _rand(4, 50, seed=20)
        onp.testing.assert_allclose(
            onp.asarray(nn_ops.softmax(x, axis=-1)),
            onp.asarray(jax.nn.softmax(x, -1)), rtol=1e-5, atol=1e-6)
        g = _rand(50, seed=21) * 0.1 + 1.0
        b = _rand(50, seed=22) * 0.1
        mean = x.mean(-1, keepdims=True)
        var = ((x - mean) ** 2).mean(-1, keepdims=True)
        want = (x - mean) * jax.lax.rsqrt(var + 1e-5) * g + b
        onp.testing.assert_allclose(
            onp.asarray(nn_ops.layer_norm(x, g, b, axis=-1, eps=1e-5)),
            onp.asarray(want), rtol=1e-4, atol=1e-5)
    finally:
        pk.reload_manifest()


def test_transformer_flash_attention_matches_gspmd():
    from incubator_mxnet_tpu.models.transformer import (TransformerConfig,
                                                        TransformerLM)
    cfg = dict(vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
               max_len=32, dtype="float32")
    m_g = TransformerLM(TransformerConfig(**cfg, attention="gspmd"))
    m_f = TransformerLM(TransformerConfig(**cfg, attention="flash"))
    params = m_g.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(onp.random.RandomState(0).randint(0, 64, (2, 17)))
    out_g = m_g.apply(params, tokens)
    out_f = m_f.apply(params, tokens)
    onp.testing.assert_allclose(onp.asarray(out_g), onp.asarray(out_f),
                                rtol=1e-4, atol=1e-4)


def test_fused_softmax_xent_matches_reference():
    """fused_softmax_xent == -log_softmax[label] fwd+bwd, incl. padded
    widths, and the SoftmaxCrossEntropyLoss fast path stays equal to
    the log_softmax+pick formulation."""
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops.pallas_kernels import fused_softmax_xent
    rng = onp.random.RandomState(0)
    for n, c in ((4, 7), (10, 300), (16, 1024)):
        x = jnp.asarray(rng.randn(n, c), jnp.float32)
        lbl = jnp.asarray(rng.randint(0, c, (n,)), jnp.int32)
        loss = fused_softmax_xent(x, lbl)
        ref = -jax.nn.log_softmax(x)[jnp.arange(n), lbl]
        onp.testing.assert_allclose(onp.asarray(loss), onp.asarray(ref),
                                    rtol=1e-5, atol=1e-6)
        g = jax.grad(lambda x: fused_softmax_xent(x, lbl).sum())(x)
        gref = jax.grad(
            lambda x: (-jax.nn.log_softmax(x)[jnp.arange(n), lbl]).sum())(x)
        onp.testing.assert_allclose(onp.asarray(g), onp.asarray(gref),
                                    rtol=1e-4, atol=1e-6)


def test_fused_softmax_xent_label_clip_semantics():
    """Out-of-range labels clamp like the generic pick(mode='clip')
    path — an ignore-marker label of -1 or an off-by-one vocab must not
    poison the loss with the padding value."""
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops.pallas_kernels import fused_softmax_xent
    rng = onp.random.RandomState(3)
    x = jnp.asarray(rng.randn(4, 10), jnp.float32)
    lbl = jnp.asarray([0, -1, 10, 9], jnp.int32)
    loss = onp.asarray(fused_softmax_xent(x, lbl))
    clipped = jnp.clip(lbl, 0, 9)
    ref = onp.asarray(-jax.nn.log_softmax(x)[jnp.arange(4), clipped])
    onp.testing.assert_allclose(loss, ref, rtol=1e-5, atol=1e-6)
    assert (onp.abs(loss) < 1e3).all()  # no padding leak
    g = jax.grad(lambda x: fused_softmax_xent(x, lbl).sum())(x)
    assert onp.isfinite(onp.asarray(g)).all()


def test_softmax_ce_loss_fast_path_parity():
    from incubator_mxnet_tpu import nd, autograd, gluon
    rng = onp.random.RandomState(1)
    pred = nd.array(rng.randn(6, 50).astype("f"))
    label = nd.array(rng.randint(0, 50, (6,)).astype("f"))
    fast = gluon.loss.SoftmaxCrossEntropyLoss()
    slow = gluon.loss.SoftmaxCrossEntropyLoss(axis=-1)
    # 3-D input exercises the generic path; 2-D the fused path
    out_fast = fast(pred, label)
    pred3 = nd.array(rng.randn(2, 3, 50).astype("f"))
    label3 = nd.array(rng.randint(0, 50, (2, 3)).astype("f"))
    out_gen = slow(pred3, label3)
    assert out_gen.shape == (2,)
    # fused == generic on the same 2-D input
    import jax.numpy as jnp
    ref = -jnp.take_along_axis(
        jax.nn.log_softmax(pred.data), label.data.astype(jnp.int32)[:, None],
        axis=1)[:, 0]
    onp.testing.assert_allclose(out_fast.asnumpy(), onp.asarray(ref),
                                rtol=1e-5, atol=1e-6)
    # gradient flows through the fused path
    pred.attach_grad()
    with autograd.record():
        loss = fast(pred, label).mean()
    loss.backward()
    assert float(nd.sum(nd.abs(pred.grad)).asnumpy()) > 0


def test_fused_rms_norm_matches_reference():
    """fused_rms_norm == plain RMSNorm formula, fwd + both gradients,
    incl. padded widths and a 3-D batch."""
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops.pallas_kernels import fused_rms_norm
    rng = onp.random.RandomState(2)
    for shape in ((4, 7), (10, 300), (2, 3, 129)):
        x = jnp.asarray(rng.randn(*shape), jnp.float32)
        gamma = jnp.asarray(rng.rand(shape[-1]) + 0.5, jnp.float32)

        def ref(x, gamma):
            ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
            return x * jax.lax.rsqrt(ms + 1e-6) * gamma

        got = fused_rms_norm(x, gamma, 1e-6)
        onp.testing.assert_allclose(onp.asarray(got), onp.asarray(
            ref(x, gamma)), rtol=1e-5, atol=1e-6)
        gx, gg = jax.grad(lambda x, g: fused_rms_norm(x, g, 1e-6).sum(),
                          argnums=(0, 1))(x, gamma)
        rx, rg = jax.grad(lambda x, g: ref(x, g).sum(),
                          argnums=(0, 1))(x, gamma)
        onp.testing.assert_allclose(onp.asarray(gx), onp.asarray(rx),
                                    rtol=1e-4, atol=1e-5)
        onp.testing.assert_allclose(onp.asarray(gg), onp.asarray(rg),
                                    rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# known-good manifest (VERDICT r3 Next #2): scripts/pallas_smoke.py
# writes it on real hardware; use_pallas() consults it per kernel
# ---------------------------------------------------------------------------

def test_manifest_gates_kernels(tmp_path, monkeypatch):
    import json
    from incubator_mxnet_tpu.ops import pallas_kernels as pk
    # the manifest gates only AUTO mode on the accelerator backend, so
    # write a tpu-platform manifest and fake the backend as tpu
    man = tmp_path / "manifest.json"
    man.write_text(json.dumps({
        "format": "pallas_smoke_v1", "platform": "tpu",
        "kernels": {"fused_softmax": {"ok": True},
                    "flash_attention": {"ok": False}}}))
    monkeypatch.setenv("MXNET_PALLAS_MANIFEST", str(man))
    monkeypatch.delenv("MXNET_USE_PALLAS", raising=False)
    monkeypatch.setattr(pk.jax, "default_backend", lambda: "tpu")
    pk.reload_manifest()
    try:
        assert pk.use_pallas("fused_softmax")
        assert not pk.use_pallas("flash_attention")
        # unknown kernels stay permissive
        assert pk.use_pallas("fused_rms_norm")
        # bare use_pallas: auto + tpu backend -> on
        assert pk.use_pallas()
        # explicit force-on IGNORES the manifest (override contract)
        monkeypatch.setenv("MXNET_USE_PALLAS", "1")
        assert pk.use_pallas("flash_attention")
        # explicit off wins over everything
        monkeypatch.setenv("MXNET_USE_PALLAS", "0")
        assert not pk.use_pallas("fused_softmax")
        # a manifest for ANOTHER platform never gates this one
        monkeypatch.delenv("MXNET_USE_PALLAS")
        man.write_text(json.dumps({
            "platform": "cpu",
            "kernels": {"fused_softmax": {"ok": False}}}))
        pk.reload_manifest()
        assert pk.use_pallas("fused_softmax")
    finally:
        pk.reload_manifest()


def test_flash_attention_falls_back_when_marked_bad(tmp_path, monkeypatch):
    import json
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops import pallas_kernels as pk
    rng = onp.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 2, 16, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 16, 8), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 16, 8), jnp.float32)
    ref = onp.asarray(pk._xla_attention(q, k, v, 8 ** -0.5, True))
    man = tmp_path / "manifest.json"
    man.write_text(json.dumps({
        "platform": "cpu",
        "kernels": {"flash_attention": {"ok": False}}}))
    monkeypatch.setenv("MXNET_PALLAS_MANIFEST", str(man))
    pk.reload_manifest()
    try:
        # interpret mode is on (cpu backend), so the kernel path still
        # runs interpreted; the fallback branch is for real hardware —
        # drive it directly by patching interpret_mode
        monkeypatch.setattr(pk, "interpret_mode", lambda: False)
        out = onp.asarray(pk.flash_attention(q, k, v, causal=True))
        onp.testing.assert_allclose(out, ref, rtol=1e-6)
    finally:
        pk.reload_manifest()


def test_smoke_harness_writes_manifest(tmp_path):
    """End-to-end: the harness runs one kernel in a subprocess and the
    written manifest is consumable by the gating logic."""
    import json
    import subprocess
    import sys as _sys
    out = tmp_path / "m.json"
    proc = subprocess.run(
        [_sys.executable,
         os.path.join(REPO, "scripts", "pallas_smoke.py"),
         "--kernels", "fused_softmax", "--platform", "cpu",
         "--timeout", "120", "--out", str(out)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-400:]
    man = json.loads(out.read_text())
    assert man["platform"] == "cpu"
    assert man["kernels"]["fused_softmax"]["ok"] is True
    assert man["kernels"]["fused_softmax"]["max_err"] < 2e-2
