"""mx.rtc — runtime Pallas kernels (reference python/mxnet/rtc.py
CudaModule/NVRTC; on TPU the user kernel is Pallas and Mosaic is the
runtime compiler).  Runs in interpret mode on the CPU harness."""
import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_pallas_module_saxpy():
    def saxpy(x_ref, y_ref, o_ref, *, alpha):
        o_ref[...] = x_ref[...] * alpha + y_ref[...]

    mod = mx.rtc.PallasModule(saxpy, num_inputs=2, static_args=("alpha",))
    kern = mod.get_kernel("saxpy", alpha=3.0)
    x = nd.ones((8, 128))
    y = nd.ones((8, 128))
    out = kern.launch([x, y], mx.tpu(0))
    onp.testing.assert_allclose(out.asnumpy(), 4.0 * onp.ones((8, 128)),
                                rtol=1e-6)


def test_pallas_module_inplace_output_arg():
    def double(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    mod = mx.rtc.PallasModule(double, num_inputs=1)
    kern = mod.get_kernel("double")
    x = nd.ones((4, 128))
    out = nd.zeros((4, 128))
    ret = kern.launch([x, out], mx.tpu(0))
    assert ret is out
    onp.testing.assert_allclose(out.asnumpy(), 2.0 * onp.ones((4, 128)))


def test_cuda_source_rejected_with_hint():
    import pytest
    with pytest.raises(TypeError, match="Pallas"):
        mx.rtc.CudaModule("__global__ void axpy(float*x){}")


def test_unknown_kernel_and_static_args():
    import pytest

    def k(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    mod = mx.rtc.PallasModule(k)
    with pytest.raises(ValueError, match="no kernel"):
        mod.get_kernel("nope")
    with pytest.raises(ValueError, match="unknown static"):
        mod.get_kernel("k", beta=1.0)
