"""Finite-difference gradient sweep across the op registry.

The reference validates every operator's FGradient against central
differences (test_utils.py:987 check_numeric_gradient, used throughout
tests/python/unittest/test_operator.py).  Here the backward comes from
jax.vjp through the invoke path, so this sweep validates the whole
autograd integration per op family — wrappers, static-kwarg routing,
multi-input cotangents — not just jnp formulas.
"""
import numpy as onp
import pytest

from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.test_utils import check_numeric_gradient

R = onp.random.RandomState(42)


def arr(*shape, positive=False, lo=-1.0, hi=1.0):
    data = R.uniform(lo, hi, shape).astype(onp.float32)
    if positive:
        data = onp.abs(data) + 0.5
    return nd.array(data)


# (name, fn(*inputs)->scalar, inputs builder)
CASES = [
    # elemwise unary
    ("tanh", lambda x: nd.sum(nd.tanh(x)), lambda: [arr(3, 4)]),
    ("sigmoid", lambda x: nd.sum(nd.sigmoid(x)), lambda: [arr(3, 4)]),
    ("exp", lambda x: nd.sum(nd.exp(x)), lambda: [arr(3, 4)]),
    ("log", lambda x: nd.sum(nd.log(x)), lambda: [arr(3, 4, positive=True)]),
    ("sqrt", lambda x: nd.sum(nd.sqrt(x)),
     lambda: [arr(3, 4, positive=True)]),
    ("rsqrt", lambda x: nd.sum(nd.rsqrt(x)),
     lambda: [arr(3, 4, positive=True)]),
    ("square", lambda x: nd.sum(nd.square(x)), lambda: [arr(3, 4)]),
    ("erf", lambda x: nd.sum(nd.erf(x)), lambda: [arr(3, 4)]),
    ("gelu", lambda x: nd.sum(nd.LeakyReLU(x, act_type="gelu")),
     lambda: [arr(3, 4)]),
    ("elu", lambda x: nd.sum(nd.LeakyReLU(x, act_type="elu", slope=0.7)),
     lambda: [arr(3, 4)]),
    ("softsign", lambda x: nd.sum(nd.softsign(x)), lambda: [arr(3, 4)]),
    # binary + broadcast
    ("broadcast_mul",
     lambda a, b: nd.sum(nd.broadcast_mul(a, b)),
     lambda: [arr(3, 4), arr(1, 4)]),
    ("broadcast_div",
     lambda a, b: nd.sum(nd.broadcast_div(a, b)),
     lambda: [arr(3, 4), arr(1, 4, positive=True)]),
    ("broadcast_power",
     lambda a, b: nd.sum(nd.broadcast_power(a, b)),
     lambda: [arr(3, 4, positive=True), arr(1, 4)]),
    ("hypot", lambda a, b: nd.sum(nd.hypot(a, b)),
     lambda: [arr(3, 4, positive=True), arr(3, 4, positive=True)]),
    # reductions
    ("mean", lambda x: nd.mean(x), lambda: [arr(4, 5)]),
    ("nansum", lambda x: nd.nansum(x), lambda: [arr(4, 5)]),
    ("norm", lambda x: nd.norm(x), lambda: [arr(4, 5, positive=True)]),
    ("max", lambda x: nd.max(x), lambda: [arr(4, 5)]),
    ("logsumexp", lambda x: nd.sum(nd.logsumexp(x, axis=1)),
     lambda: [arr(4, 5)]) if hasattr(nd, "logsumexp") else None,
    ("softmax", lambda x: nd.sum(nd.square(nd.softmax(x, axis=-1))),
     lambda: [arr(3, 5)]),
    ("log_softmax", lambda x: nd.sum(nd.log_softmax(x, axis=-1) * 0.3),
     lambda: [arr(3, 5)]),
    # shape / index
    ("transpose", lambda x: nd.sum(nd.square(nd.transpose(x, axes=(1, 0)))),
     lambda: [arr(3, 4)]),
    ("slice", lambda x: nd.sum(nd.square(
        nd.slice(x, begin=(1, 0), end=(3, 2)))), lambda: [arr(4, 3)]),
    ("tile", lambda x: nd.sum(nd.square(nd.tile(x, reps=(2, 2)))),
     lambda: [arr(2, 3)]),
    ("take", lambda x: nd.sum(nd.square(
        nd.take(x, nd.array(onp.array([0, 2], onp.int32))))),
     lambda: [arr(4, 3)]),
    # nn
    ("FullyConnected",
     lambda x, w, b: nd.sum(nd.square(
         nd.FullyConnected(x, w, b, num_hidden=4))),
     lambda: [arr(2, 3), arr(4, 3), arr(4)]),
    ("Convolution",
     lambda x, w, b: nd.mean(nd.square(nd.Convolution(
         x, w, b, kernel=(3, 3), num_filter=2, pad=(1, 1)))),
     lambda: [arr(1, 2, 5, 5), arr(2, 2, 3, 3), arr(2)]),
    ("Pooling_avg",
     lambda x: nd.sum(nd.square(nd.Pooling(
         x, kernel=(2, 2), stride=(2, 2), pool_type="avg"))),
     lambda: [arr(1, 2, 4, 4)]),
    ("LayerNorm",
     lambda x, g, b: nd.sum(nd.square(nd.LayerNorm(x, g, b))),
     lambda: [arr(3, 6), arr(6, positive=True), arr(6)]),
    ("Embedding",
     lambda w: nd.sum(nd.square(nd.Embedding(
         nd.array(onp.array([0, 2, 1], onp.int32)), w, input_dim=4,
         output_dim=3))),
     lambda: [arr(4, 3)]),
    # linalg
    ("dot", lambda a, b: nd.sum(nd.square(nd.dot(a, b))),
     lambda: [arr(3, 4), arr(4, 2)]),
    ("batch_dot", lambda a, b: nd.sum(nd.square(nd.batch_dot(a, b))),
     lambda: [arr(2, 3, 4), arr(2, 4, 2)]),
    ("linalg_gemm2",
     lambda a, b: nd.sum(nd.square(nd.linalg_gemm2(a, b, alpha=1.5))),
     lambda: [arr(3, 4), arr(4, 2)]),
    ("linalg_trmm", lambda a, b: nd.sum(nd.square(nd.linalg_trmm(a, b))),
     lambda: [arr(3, 3), arr(3, 2)]),
    ("linalg_sumlogdiag",
     lambda a: nd.sum(nd.linalg_sumlogdiag(a)),
     lambda: [nd.array(onp.eye(3, dtype=onp.float32) * 2.0
                       + 0.1 * R.rand(3, 3).astype(onp.float32))]),
    # new image / attention ops
    ("BilinearResize2D",
     lambda x: nd.sum(nd.square(nd.BilinearResize2D(x, height=5, width=7))),
     lambda: [arr(1, 2, 3, 4)]),
    ("image_normalize",
     lambda x: nd.mean(nd.square(nd.image_normalize(
         x, mean=(0.4, 0.5, 0.6), std=(0.2, 0.25, 0.3)))),
     lambda: [arr(3, 4, 4)]),
    ("interleaved_selfatt",
     lambda qkv: nd.sum(nd.square(nd.interleaved_matmul_selfatt_qk(
         qkv, heads=2))),
     lambda: [arr(3, 2, 12)]),
    ("quadratic",
     lambda x: nd.sum(nd.quadratic(x, a=1.5, b=-2.0, c=0.3)),
     lambda: [arr(3, 4)]),
    ("sequence_mask",
     lambda x: nd.sum(nd.square(nd.SequenceMask(
         x, nd.array(onp.array([2.0, 3.0], onp.float32)),
         use_sequence_length=True))),
     lambda: [arr(4, 2, 3)]),
]
CASES = [c for c in CASES if c is not None]


@pytest.mark.parametrize("name,fn,builder", CASES,
                         ids=[c[0] for c in CASES])
def test_numeric_gradient(name, fn, builder):
    check_numeric_gradient(fn, builder())
