"""Detection data pipeline (reference python/mxnet/image/detection.py):
bbox-aware augmenters + ImageDetIter feeding MultiBoxTarget."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import image, nd


def _sample(n=5, size=32):
    rng = onp.random.RandomState(0)
    items = []
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), dtype=onp.uint8)
        lab = onp.asarray([[i % 3, 0.2, 0.3, 0.6, 0.7]], onp.float32)
        items.append((img, lab))
    return items


def test_det_flip_moves_boxes():
    aug = image.DetHorizontalFlipAug(p=1.0)
    img = onp.zeros((8, 8, 3), onp.uint8)
    lab = onp.asarray([[0, 0.1, 0.2, 0.4, 0.9]], onp.float32)
    img2, lab2 = aug(img, lab)
    onp.testing.assert_allclose(lab2[0], [0, 0.6, 0.2, 0.9, 0.9],
                                rtol=1e-6)
    # flipping twice restores
    _, lab3 = aug(img2, lab2)
    onp.testing.assert_allclose(lab3, lab, rtol=1e-6)


def test_det_border_pad_square():
    aug = image.DetBorderAug(fill=0)
    img = onp.ones((4, 8, 3), onp.uint8)
    lab = onp.asarray([[1, 0.0, 0.0, 1.0, 1.0]], onp.float32)
    out, lab2 = aug(img, lab)
    assert out.shape[:2] == (8, 8)
    # the full-image box now spans the padded center band vertically
    onp.testing.assert_allclose(lab2[0], [1, 0.0, 0.25, 1.0, 0.75],
                                rtol=1e-6)


def test_det_random_crop_keeps_objects():
    onp.random.seed(0)
    aug = image.DetRandomCropAug(min_object_covered=1.0,
                                 min_crop_size=0.7)
    img = onp.zeros((32, 32, 3), onp.uint8)
    lab = onp.asarray([[0, 0.4, 0.4, 0.6, 0.6]], onp.float32)
    for _ in range(10):
        img2, lab2 = aug(img, lab)
        assert len(lab2) == 1
        assert (lab2[:, 1:] >= -1e-6).all() and (lab2[:, 1:] <= 1 + 1e-6).all()


def test_image_det_iter_batches_and_multibox_target():
    items = _sample(5)
    it = image.ImageDetIter(batch_size=2, data_shape=(3, 16, 16),
                            imglist=items,
                            augmenters=image.CreateDetAugmenter(
                                (3, 16, 16), rand_mirror=True))
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 1                 # 5 items, bs 2 -> wrap 1
    b = batches[0]
    assert b.data[0].shape == (2, 3, 16, 16)
    assert b.label[0].shape[0] == 2 and b.label[0].shape[2] == 5
    # labels feed MultiBoxTarget directly
    anchors = nd.contrib.MultiBoxPrior(nd.zeros((1, 3, 4, 4)),
                                       sizes=(0.5,), ratios=(1.0,))
    out = nd.contrib.MultiBoxTarget(anchors, b.label[0],
                                    nd.zeros((2, 3, anchors.shape[1])))
    assert out[0].shape[0] == 2
