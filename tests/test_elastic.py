"""Elastic sharding-aware training runtime (ISSUE 6).

Covers the three tentpole pieces end to end:

* **checkpoint resharding** — a tree saved on mesh shape A restores on
  B ∈ {smaller, larger, single} element-wise identical, carrying the
  target sharding, CRC-verified per source shard, with the same
  newest-first corruption fallback as the same-shape path (and typed
  ``ReshardError`` for spec-level problems, which must NOT fall back);
* **membership + elasticity** — workers join with a declared dp-rank,
  a worker silent past ``MXNET_KVSTORE_BEAT_INTERVAL`` ×
  ``MXNET_KVSTORE_DEAD_AFTER`` is evicted and sync rounds/barriers
  re-balance to the survivors, an evicted worker gets a typed
  ``WorkerEvictedError`` (never a hang), and a rejoiner bootstraps from
  current weights; the elastic Trainer checkpoints on eviction notice;
* **chaos-proven recovery** — the kill → evict → survivors converge →
  rejoin → bootstrap scenario ends with weights matching an
  uninterrupted run (the convergence-parity bar the PR 2 chaos stage
  set), and runs under the seeded fault spec the CI ``elastic`` stage
  pins (heartbeat loss, lost acks, slow checkpoint reads).
"""
import os
import threading
import time

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import fault, nd
from incubator_mxnet_tpu.checkpoint import AsyncCheckpointManager
from incubator_mxnet_tpu.error import (CheckpointCorruptError,
                                       CheckpointWriteError,
                                       PSTimeoutError, ReshardError,
                                       WorkerEvictedError)
from incubator_mxnet_tpu.kvstore.ps_server import PSServer, PSClient
from incubator_mxnet_tpu.parallel import make_mesh, leading_axis_rule

from jax.sharding import NamedSharding, PartitionSpec as P


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.configure(None)
    yield
    fault.reset()


@pytest.fixture()
def fast_beats(monkeypatch):
    """Tight heartbeat budget: eviction after 0.15s of silence."""
    monkeypatch.setenv("MXNET_KVSTORE_BEAT_INTERVAL", "0.05")
    monkeypatch.setenv("MXNET_KVSTORE_DEAD_AFTER", "3")


@pytest.fixture()
def scenario_beats(monkeypatch):
    """Beat budget for the chaos scenario: 1s of silence.  Wide enough
    that a LIVE worker whose beats are occasionally eaten by the seeded
    p=0.2 heartbeat-loss spec (or delayed by retry backoff on the data
    path) never burns it, while the killed worker still evicts fast."""
    monkeypatch.setenv("MXNET_KVSTORE_BEAT_INTERVAL", "0.05")
    monkeypatch.setenv("MXNET_KVSTORE_DEAD_AFTER", "20")


def _start_server(mode="sync", num_workers=1, state=None):
    srv = PSServer(("127.0.0.1", 0), mode=mode, num_workers=num_workers,
                   state=state)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


# ---------------------------------------------------------------------------
# checkpoint resharding (tentpole a)
# ---------------------------------------------------------------------------

def _dp_tree(dp):
    """A mixed tree sharded over a dp mesh: sharded matrix, replicated
    bf16 vector, 0-d host scalar."""
    mesh = make_mesh(dp=dp)
    w = jnp.arange(64.0).reshape(8, 8)
    ws = jax.device_put(w, NamedSharding(mesh, P("dp", None)))
    return mesh, {"w": ws,
                  "b": jnp.full((3,), 2.5, jnp.bfloat16),
                  "step_count": onp.int64(7)}


@pytest.mark.parametrize("dp_to", [2, 8, 1])
def test_reshard_roundtrip_property(tmp_path, dp_to):
    """Acceptance: save on dp=4, restore on dp∈{2,8,1} element-wise
    identical with the target sharding carried."""
    _, tree = _dp_tree(dp=4)
    ckpt = AsyncCheckpointManager(tmp_path)
    ckpt.save(1, tree, wait=True)
    mesh_b = make_mesh(dp=dp_to)
    back = ckpt.reshard_restore(mesh=mesh_b,
                                rule_fn=leading_axis_rule(mesh_b))
    onp.testing.assert_array_equal(onp.asarray(back["w"]),
                                   onp.arange(64.0).reshape(8, 8))
    want_spec = P("dp", None) if dp_to > 1 else P()
    assert back["w"].sharding.spec == want_spec
    assert back["w"].sharding.mesh.shape["dp"] == dp_to
    if dp_to > 1:
        assert len(back["w"].sharding.device_set) == dp_to
    assert str(back["b"].dtype) == "bfloat16"
    onp.testing.assert_array_equal(
        onp.asarray(back["b"]).astype(onp.float32), onp.full((3,), 2.5))
    assert int(onp.asarray(back["step_count"])) == 7


def test_reshard_verifies_crc_per_source_shard(tmp_path):
    _, tree = _dp_tree(dp=4)
    ckpt = AsyncCheckpointManager(tmp_path)
    ckpt.save(1, tree, wait=True)
    d = os.path.join(str(tmp_path), "step_00000001")
    victim = sorted(f for f in os.listdir(d) if "_s" in f)[2]
    raw = bytearray(open(os.path.join(d, victim), "rb").read())
    raw[-3] ^= 0xFF
    open(os.path.join(d, victim), "wb").write(bytes(raw))
    mesh_b = make_mesh(dp=2)
    with pytest.raises(CheckpointCorruptError, match="CRC mismatch"):
        ckpt.reshard_restore(mesh=mesh_b,
                             rule_fn=leading_axis_rule(mesh_b),
                             step=1)


def test_reshard_falls_back_newest_first(tmp_path):
    """Corruption during reshard-restore walks back to the newest VALID
    step — exactly the same-shape restore contract."""
    mesh_a, _ = _dp_tree(dp=4)
    ckpt = AsyncCheckpointManager(tmp_path)
    for step, fill in ((1, 1.0), (2, 2.0)):
        x = jax.device_put(jnp.full((8, 4), fill),
                           NamedSharding(mesh_a, P("dp", None)))
        ckpt.save(step, {"w": x}, wait=True)
    d2 = os.path.join(str(tmp_path), "step_00000002")
    victim = sorted(f for f in os.listdir(d2) if f.endswith(".npy"))[0]
    open(os.path.join(d2, victim), "wb").write(b"torn")
    mesh_b = make_mesh(dp=2)
    back = ckpt.reshard_restore(mesh=mesh_b,
                                rule_fn=leading_axis_rule(mesh_b))
    onp.testing.assert_array_equal(onp.asarray(back["w"]),
                                   onp.full((8, 4), 1.0))


def test_reshard_spec_errors_are_typed_not_fallback(tmp_path):
    """A request the index cannot satisfy is ReshardError — surfaced,
    never silently satisfied by an older checkpoint."""
    _, tree = _dp_tree(dp=4)
    ckpt = AsyncCheckpointManager(tmp_path)
    ckpt.save(1, tree, wait=True)
    mesh_b = make_mesh(dp=2)
    with pytest.raises(ReshardError, match="no entry"):
        ckpt.reshard_restore(tree_spec={"nope": None}, mesh=mesh_b)
    with pytest.raises(ReshardError, match="shape"):
        ckpt.reshard_restore(
            tree_spec={"w": jax.ShapeDtypeStruct((4, 4), jnp.float32)},
            mesh=mesh_b)
    with pytest.raises(ReshardError, match="mesh"):
        ckpt.reshard_restore(mesh=None)


def test_reshard_read_fault_point_is_wired(tmp_path):
    """checkpoint.read fires on every shard read; an injected read
    error is treated as damage (fallback), a delay just slows it."""
    mesh_a, _ = _dp_tree(dp=4)
    ckpt = AsyncCheckpointManager(tmp_path)
    x = jax.device_put(jnp.arange(32.0).reshape(8, 4),
                       NamedSharding(mesh_a, P("dp", None)))
    ckpt.save(1, {"w": x}, wait=True)
    ckpt.save(2, {"w": x}, wait=True)
    fault.configure("checkpoint.read:error:n=1")
    mesh_b = make_mesh(dp=1)
    back = ckpt.reshard_restore(mesh=mesh_b)   # step 2 "damaged" → 1
    calls, fired = fault.stats()["checkpoint.read"]
    assert fired == 1 and calls > 1
    onp.testing.assert_array_equal(onp.asarray(back["w"]),
                                   onp.arange(32.0).reshape(8, 4))
    fault.configure(None)
    with pytest.raises(CheckpointCorruptError):
        fault.configure("checkpoint.read:error")
        ckpt.reshard_restore(mesh=mesh_b, step=2)


# ---------------------------------------------------------------------------
# checkpoint satellites: typed banked write error, index completeness
# ---------------------------------------------------------------------------

def test_banked_write_failure_is_typed_and_surfaces_at_next_save(
        tmp_path, monkeypatch):
    from incubator_mxnet_tpu import checkpoint as ckpt_mod
    ckpt = AsyncCheckpointManager(tmp_path)
    real_save = ckpt_mod.onp.save
    monkeypatch.setattr(ckpt_mod.onp, "save",
                        lambda *a, **k: (_ for _ in ()).throw(
                            IOError("disk gone")))
    ckpt.save(1, {"w": jnp.ones((2,))})
    ckpt._thread.join()           # failure is banked, not yet raised
    monkeypatch.setattr(ckpt_mod.onp, "save", real_save)
    # the NEXT save must deliver the banked failure, typed
    with pytest.raises(CheckpointWriteError, match="disk gone"):
        ckpt.save(2, {"w": jnp.ones((2,))})
    # and the bank is drained: the manager recovers
    ckpt.save(3, {"w": jnp.ones((2,))}, wait=True)
    assert ckpt.all_steps() == [3]


def test_missing_per_process_index_is_incomplete(tmp_path):
    """Satellite: the per-process index is the completion marker — a
    directory missing any index.<i>.json is incomplete (falls back
    newest-first), never a partial tree."""
    import json
    ckpt = AsyncCheckpointManager(tmp_path)
    ckpt.save(1, {"w": jnp.full((4,), 1.0)}, wait=True)
    ckpt.save(2, {"w": jnp.full((4,), 2.0), "extra": jnp.ones((2,))},
              wait=True)
    # rewrite step 2 as a 2-process checkpoint whose process-1 index
    # never landed (the writer died after index.0.json was committed)
    d2 = os.path.join(str(tmp_path), "step_00000002")
    with open(os.path.join(d2, "index.json")) as f:
        idx = json.load(f)
    idx["nprocs"] = 2
    with open(os.path.join(d2, "index.0.json"), "w") as f:
        json.dump(idx, f)
    os.remove(os.path.join(d2, "index.json"))
    with pytest.raises(CheckpointCorruptError, match="incomplete"):
        ckpt.restore(2)
    back = ckpt.restore()          # newest VALID = step 1, full tree
    onp.testing.assert_array_equal(back["w"], onp.full((4,), 1.0))


def test_truncated_per_process_index_falls_back(tmp_path):
    """A torn index.<i>.json (truncated JSON) is damage, not a smaller
    save: fallback, not a partial tree."""
    import json
    ckpt = AsyncCheckpointManager(tmp_path)
    ckpt.save(1, {"w": jnp.full((4,), 1.0)}, wait=True)
    ckpt.save(2, {"w": jnp.full((4,), 2.0)}, wait=True)
    d2 = os.path.join(str(tmp_path), "step_00000002")
    with open(os.path.join(d2, "index.json")) as f:
        idx = json.load(f)
    idx["nprocs"] = 2
    with open(os.path.join(d2, "index.0.json"), "w") as f:
        json.dump(idx, f)
    # process 1's index exists but was torn mid-write
    with open(os.path.join(d2, "index.1.json"), "w") as f:
        f.write('{"step": 2, "nprocs": 2, "par')
    os.remove(os.path.join(d2, "index.json"))
    with pytest.raises(CheckpointCorruptError):
        ckpt.restore(2)
    onp.testing.assert_array_equal(ckpt.restore()["w"],
                                   onp.full((4,), 1.0))


# ---------------------------------------------------------------------------
# membership: join / beat / evict / re-balance / rejoin (tentpole b)
# ---------------------------------------------------------------------------

def test_eviction_is_deterministic_after_missed_beat_budget(fast_beats):
    """Satellite: a worker whose beats are eaten by the seeded fault
    spec is evicted after MXNET_KVSTORE_DEAD_AFTER missed beats —
    deterministically, surfacing the typed notice on its next call."""
    srv = _start_server("sync", num_workers=2)
    c1 = PSClient("127.0.0.1", srv.port)
    c2 = PSClient("127.0.0.1", srv.port)
    c1.join(0)
    c2.join(1)
    c1.call("init", "w", onp.zeros(3, onp.float32))
    # every one of c2's beats is lost on the wire, seeded.  c1 beats
    # through the raw wire command: the injection point is process-wide
    # and the test wants exactly one worker's beats eaten.
    fault.configure("kvstore.heartbeat:error:p=1.0:seed=42")
    deadline = time.monotonic() + 0.4   # budget is 0.15s
    evicted = False
    while time.monotonic() < deadline:
        c1.call("beat", None, {"sess": c1.session})   # c1 stays live
        with pytest.raises(PSTimeoutError):
            c2.beat()                   # injected loss, burns budget
        time.sleep(0.03)
    fault.configure(None)
    try:
        c2.beat()
    except WorkerEvictedError:
        evicted = True
    assert evicted, "c2 must be evicted after the missed-beat budget"
    assert c1.heartbeat()["live_workers"] == 1
    # sync rounds now need only the survivor
    c1.call("push", "w", onp.ones(3, onp.float32))
    onp.testing.assert_array_equal(c1.call("pull", "w"), onp.ones(3))
    c1.call("stop")


def test_uninitialized_key_fails_fast_and_typed():
    """A push/pull for a key no init() ever stored must fail FAST with
    an actionable message — not a bare ``KeyError: 0`` (the historical
    symptom of a leaked MXT_WORKER_ID making rank-0 init never run),
    and never by burning the full sync round timeout."""
    from incubator_mxnet_tpu.base import MXNetError
    srv = _start_server("sync", num_workers=1)
    c = PSClient("127.0.0.1", srv.port)
    c.call("set_optimizer", None, __import__("pickle").dumps(
        mx.optimizer.create("sgd", learning_rate=0.1)))
    t0 = time.monotonic()
    with pytest.raises(MXNetError, match="never initialized.*init"):
        c.call("push", 0, onp.ones(3, onp.float32))
    with pytest.raises(MXNetError, match="never initialized.*init"):
        c.call("pull", 0)
    # deterministic: both surface immediately, not after the bounded
    # sync wait (MXNET_KVSTORE_TIMEOUT-scale) that made this
    # load-sensitive
    assert time.monotonic() - t0 < 5.0
    # a properly initialized key still round-trips
    c.call("init", 0, onp.zeros(3, onp.float32))
    c.call("push", 0, onp.ones(3, onp.float32))
    onp.testing.assert_array_equal(
        onp.asarray(c.call("pull", 0)), onp.full(3, -0.1, onp.float32))
    c.call("stop")


def test_sync_round_rebalances_when_worker_dies_mid_wait(fast_beats):
    """Survivors blocked in a sync pull are released when the missing
    worker's eviction completes the round — within the heartbeat
    budget, not the full MXNET_KVSTORE_TIMEOUT.  The survivors keep
    beating from a side thread: beats ride a dedicated connection, so a
    blocking pull can never starve a worker's own heartbeat."""
    srv = _start_server("sync", num_workers=3)
    cs = [PSClient("127.0.0.1", srv.port) for _ in range(3)]
    for r, c in enumerate(cs):
        c.join(r)
    stop = threading.Event()

    def beater():
        while not stop.wait(0.03):
            for c in cs[:2]:
                try:
                    c.beat()
                except (ConnectionError, TimeoutError):
                    pass

    bt = threading.Thread(target=beater, daemon=True)
    bt.start()
    try:
        cs[0].call("init", "w", onp.zeros(2, onp.float32))
        cs[0].call("push", "w", onp.ones(2, onp.float32))
        cs[1].call("push", "w", onp.ones(2, onp.float32))
        # cs[2] dies without pushing; survivors' pull must complete
        # once the sweeping wait evicts it and re-balances the round
        t0 = time.monotonic()
        out = cs[0].call("pull", "w")
        assert time.monotonic() - t0 < 5.0
        onp.testing.assert_array_equal(out, 2 * onp.ones(2))
    finally:
        stop.set()
        bt.join(timeout=5)
    cs[0].call("stop")


def test_barrier_rebalances_on_eviction(fast_beats):
    srv = _start_server("sync", num_workers=3)
    cs = [PSClient("127.0.0.1", srv.port) for _ in range(3)]
    for r, c in enumerate(cs):
        c.join(r)
    done = []
    stop = threading.Event()

    def beater():                      # survivors stay live while blocked
        while not stop.wait(0.03):
            for c in cs[:2]:
                try:
                    c.beat()
                except (ConnectionError, TimeoutError):
                    pass

    def arrive(c):
        c.call("barrier")
        done.append(1)

    bt = threading.Thread(target=beater, daemon=True)
    bt.start()
    try:
        ts = [threading.Thread(target=arrive, args=(c,)) for c in cs[:2]]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert len(done) == 2, "barrier must release once the dead " \
                               "third worker is evicted"
    finally:
        stop.set()
        bt.join(timeout=5)
    cs[0].call("stop")


def test_rejoin_bootstraps_from_current_weights(fast_beats):
    srv = _start_server("sync", num_workers=2)
    c1 = PSClient("127.0.0.1", srv.port)
    c2 = PSClient("127.0.0.1", srv.port)
    c1.join(0)
    c2.join(1)
    c1.call("init", "w", onp.zeros(3, onp.float32))
    for c in (c1, c2):
        c.call("push", "w", onp.ones(3, onp.float32))
    for _ in range(10):                 # c2 silent past its budget,
        c1.beat()                       # c1 keeps beating; the sweep
        time.sleep(0.03)                # riding c1's beats evicts c2
    assert c1.heartbeat()["live_workers"] == 1
    c1.call("push", "w", onp.full((3,), 5.0, onp.float32))
    onp.testing.assert_array_equal(c1.call("pull", "w"),
                                   onp.full((3,), 5.0))
    # evicted worker: typed error, then rejoin + bare-pull bootstrap
    with pytest.raises(WorkerEvictedError):
        c2.call("push", "w", onp.ones(3, onp.float32))
    info = c2.join(1)
    assert info["rejoin"] and info["live_workers"] == 2
    onp.testing.assert_array_equal(c2.call("pull", "w"),
                                   onp.full((3,), 5.0))
    c1.call("stop")


def test_heartbeat_raced_with_kill_is_typed_not_hang(fast_beats):
    """Satellite: a probe racing PSServer.kill() mid-probe surfaces the
    typed error inside its one-shot budget — never a hang."""
    srv = _start_server("sync", num_workers=1)
    c = PSClient("127.0.0.1", srv.port, timeout=2.0, max_retries=2)
    assert c.heartbeat(timeout=2.0)["mode"] == "sync"
    killer = threading.Timer(0.05, srv.kill)
    killer.start()
    t0 = time.monotonic()
    with pytest.raises(PSTimeoutError, match="heartbeat"):
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            c.heartbeat(timeout=2.0)    # races the kill; must not hang
    assert time.monotonic() - t0 < 15
    killer.join()


def test_join_window_does_not_shrink_rounds(scenario_beats):
    """A fast first joiner must not complete a 'round' of one with a
    partial fleet's gradient while its peers' joins are in flight:
    membership shrinks rounds only through DEPARTURE, never through a
    worker that has not joined yet."""
    srv = _start_server("sync", num_workers=2)
    c1 = PSClient("127.0.0.1", srv.port)
    c1.join(0)                          # 1 of the declared 2 joined
    c1.call("init", "w", onp.zeros(2, onp.float32))
    c1.call("push", "w", onp.ones(2, onp.float32))
    with srv.state.lock:                # solo push must NOT apply
        assert srv.state.round_done.get("w", 0) == 0
        assert srv.state.merge["w"][1] == 1
    c2 = PSClient("127.0.0.1", srv.port)
    c2.join(1)                          # fleet assembled
    c2.call("push", "w", onp.ones(2, onp.float32))
    onp.testing.assert_array_equal(c1.call("pull", "w"),
                                   2 * onp.ones(2))
    c1.call("stop")


def test_join_mid_round_does_not_stall_survivors(scenario_beats):
    """A worker joining while a round is OPEN must not inflate that
    round's threshold (frozen at its first push): the survivors'
    in-flight round completes without waiting on the newcomer."""
    srv = _start_server("sync", num_workers=2)
    c1 = PSClient("127.0.0.1", srv.port)
    c2 = PSClient("127.0.0.1", srv.port)
    c1.join(0)
    c2.join(1)
    c1.call("init", "w", onp.zeros(2, onp.float32))
    c1.call("push", "w", onp.ones(2, onp.float32))   # round open, need=2
    c3 = PSClient("127.0.0.1", srv.port)
    c3.join(2)                          # mid-round join: need stays 2
    c2.call("push", "w", onp.ones(2, onp.float32))   # completes it
    t0 = time.monotonic()
    out = c1.call("pull", "w")
    assert time.monotonic() - t0 < 2.0, "open round stalled on joiner"
    onp.testing.assert_array_equal(out, 2 * onp.ones(2))
    # the NEXT round counts the newcomer
    with srv.state.lock:
        assert srv.state.required() == 3
    c1.call("stop")


def test_leave_then_rejoin_restores_required_floor(scenario_beats):
    """A graceful leave followed by a (fresh-session) rejoin nets out
    of `departed`, so the startup-floor protection is not permanently
    weakened by maintenance cycles."""
    srv = _start_server("sync", num_workers=2)
    c1 = PSClient("127.0.0.1", srv.port)
    c2 = PSClient("127.0.0.1", srv.port)
    c1.join(0)
    c2.join(1)
    c2.leave()
    with srv.state.lock:
        assert srv.state.departed == 1
        assert srv.state.required() == 1
    c2b = PSClient("127.0.0.1", srv.port)   # replacement process
    c2b.join(1)
    with srv.state.lock:
        assert srv.state.departed == 0
        assert srv.state.required() == 2
    c1.call("stop")


def test_step_dir_with_no_index_is_corrupt_not_empty(tmp_path):
    """A step directory where NO writer committed its index must raise
    on explicit restore — never hand back an empty parameter tree."""
    ckpt = AsyncCheckpointManager(tmp_path)
    d = os.path.join(str(tmp_path), "step_00000005")
    os.makedirs(d)
    onp.save(os.path.join(d, "w.p0_s0.npy"), onp.ones(4))
    with pytest.raises(CheckpointCorruptError, match="no index"):
        ckpt.restore(5)
    with pytest.raises(CheckpointCorruptError, match="no index"):
        ckpt.reshard_restore(mesh=make_mesh(dp=1), step=5)


def test_graceful_leave_rebalances_immediately(fast_beats):
    srv = _start_server("sync", num_workers=2)
    c1 = PSClient("127.0.0.1", srv.port)
    c2 = PSClient("127.0.0.1", srv.port)
    c1.join(0)
    c2.join(1)
    c1.call("init", "w", onp.zeros(2, onp.float32))
    c1.call("push", "w", onp.ones(2, onp.float32))
    c2.leave()                          # no budget burned
    out = c1.call("pull", "w")          # round complete with 1 live
    onp.testing.assert_array_equal(out, onp.ones(2))
    c1.call("stop")


# ---------------------------------------------------------------------------
# elastic Trainer (tentpole b, trainer half)
# ---------------------------------------------------------------------------

def _elastic_trainer(tmp_path, monkeypatch, srv):
    from incubator_mxnet_tpu.gluon import nn, Trainer
    monkeypatch.setenv("MXT_SERVERS", f"127.0.0.1:{srv.port}")
    monkeypatch.setenv("MXT_KV_MODE", "sync")
    net = nn.Dense(2, in_units=3)
    net.initialize()
    net(nd.zeros((1, 3)))
    kv = mx.kv.create("dist_sync")
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 kvstore=kv, elastic=True,
                 checkpoint_dir=str(tmp_path / "ckpt"))
    return net, kv, tr


def _one_step(net, tr):
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import loss as gloss
    x = nd.random.uniform(shape=(4, 3))
    y = nd.random.uniform(shape=(4, 2))
    with autograd.record():
        l = gloss.L2Loss()(net(x), y)
    l.backward()
    tr.step(4)


def test_trainer_evicts_checkpoints_and_rejoins(tmp_path, monkeypatch,
                                                scenario_beats):
    # scenario budget (1s): a cold jit compile holds the GIL long
    # enough to starve a 0.15s budget and evict a healthy worker
    srv = _start_server("sync", num_workers=1)
    net, kv, tr = _elastic_trainer(tmp_path, monkeypatch, srv)
    _one_step(net, tr)
    assert tr.live_workers == 1
    # all beats lost → evicted after the budget; the next step must
    # save an eviction checkpoint and surface the typed error
    fault.configure("kvstore.heartbeat:error:p=1.0:seed=7")
    time.sleep(1.4)
    with pytest.raises(WorkerEvictedError, match="eviction checkpoint"):
        _one_step(net, tr)
    fault.configure(None)
    assert tr._ckpt.all_steps(), "eviction checkpoint must be durable"
    tr.rejoin()
    _one_step(net, tr)                  # trains again after rejoin
    tr.close()
    kv._clients[0].call("stop")


def test_rejoin_bootstrap_is_mode_aware(tmp_path, monkeypatch,
                                        scenario_beats):
    """In gradient-aggregation mode the server holds merged GRADIENTS —
    rejoin must bootstrap from the eviction checkpoint, never by
    pulling those into the weights (which destroys the model)."""
    srv = _start_server("sync", num_workers=1)
    net, kv, tr = _elastic_trainer(tmp_path, monkeypatch, srv)
    _one_step(net, tr)
    before = {n: onp.asarray(v.data) for n, v in tr._param_tree().items()}
    fault.configure("kvstore.heartbeat:error:p=1.0:seed=3")
    time.sleep(1.4)
    with pytest.raises(WorkerEvictedError):
        _one_step(net, tr)
    fault.configure(None)
    tr.rejoin()
    # weights equal the eviction-checkpoint state — not the merged
    # gradient the aggregation-mode server stores under the same keys
    for n, v in tr._param_tree().items():
        onp.testing.assert_array_equal(onp.asarray(v.data), before[n])
    tr.close()
    kv._clients[0].call("stop")


def test_update_on_kvstore_server_holds_weights(tmp_path, monkeypatch,
                                                scenario_beats):
    """update_on_kvstore=True: the server applies the optimizer and
    holds the authoritative weights, so a rejoiner's bootstrap pull
    lands TRUE weights (the drive-level eviction/rejoin contract)."""
    from incubator_mxnet_tpu.gluon import nn, Trainer
    srv = _start_server("sync", num_workers=1)
    monkeypatch.setenv("MXT_SERVERS", f"127.0.0.1:{srv.port}")
    monkeypatch.setenv("MXT_KV_MODE", "sync")
    net = nn.Dense(2, in_units=3)
    net.initialize()
    net(nd.zeros((1, 3)))
    kv = mx.kv.create("dist_sync")
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 kvstore=kv, elastic=True, update_on_kvstore=True,
                 checkpoint_dir=str(tmp_path / "ckpt"))
    for _ in range(3):
        _one_step(net, tr)
    after = {n: onp.asarray(v.data) for n, v in tr._param_tree().items()}
    # server-side weights == local weights (pulled back each step)
    for i, p in enumerate(tr._params):
        onp.testing.assert_allclose(
            onp.asarray(kv._clients[0].call("pull", i)),
            onp.asarray(p.data().data), rtol=1e-6)
    # scribble the local params; rejoin bootstrap restores from server
    for p in tr._params:
        p.set_data(nd.zeros(p.shape))
    tr.rejoin()
    for n, v in tr._param_tree().items():
        onp.testing.assert_allclose(onp.asarray(v.data), after[n],
                                    rtol=1e-6)
    tr.close()
    kv._clients[0].call("stop")


def test_chunked_trainer_drains_eviction_to_chunk_boundary(
        tmp_path, monkeypatch, scenario_beats):
    """Chunked training (ISSUE 13): with ``chunk_steps=K`` a banked
    eviction notice arriving MID-chunk drains the remaining steps of
    the chunk, surfaces exactly ON the boundary (worst-case latency K
    steps, docs/fault_tolerance.md), checkpoints there, and the
    rejoin-and-finish run lands weight parity with an uninterrupted
    run of the same schedule — bare and under the pinned elastic
    chaos spec (this file's CI stage)."""
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.gluon import Trainer, nn
    from incubator_mxnet_tpu.gluon import loss as gloss

    K, total = 3, 6
    rng = onp.random.RandomState(7)
    xs = [rng.rand(4, 3).astype("f") for _ in range(total)]
    ys = [rng.rand(4, 2).astype("f") for _ in range(total)]

    def fresh_net():
        mx.random.seed(0)
        net = nn.Dense(2, in_units=3)
        net.initialize()
        net(nd.zeros((1, 3)))
        return net

    def one_step(net, tr, i):
        with autograd.record():
            l = gloss.L2Loss()(net(nd.array(xs[i])), nd.array(ys[i]))
        l.backward()
        tr.step(4)

    # uninterrupted reference (single worker: the PS sync returns the
    # worker's own summed gradient, so the local path is the same math)
    ref = fresh_net()
    tr_ref = Trainer(ref.collect_params(), "sgd",
                     {"learning_rate": 0.1}, kvstore=None)
    for i in range(total):
        one_step(ref, tr_ref, i)

    srv = _start_server("sync", num_workers=1)
    monkeypatch.setenv("MXT_SERVERS", f"127.0.0.1:{srv.port}")
    monkeypatch.setenv("MXT_KV_MODE", "sync")
    net = fresh_net()
    kv = mx.kv.create("dist_sync")
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 kvstore=kv, elastic=True, chunk_steps=K,
                 checkpoint_dir=str(tmp_path / "ckpt"))
    one_step(net, tr, 0)
    # bank a notice mid-chunk: steps 2 and 3 must still complete
    tr._evicted_reason = "test: notice banked mid-chunk"
    one_step(net, tr, 1)
    one_step(net, tr, 2)
    assert tr._step_count == K          # chunk drained, not interrupted
    with pytest.raises(WorkerEvictedError, match="eviction checkpoint"):
        one_step(net, tr, 3)            # surfaces AT the boundary
    # the eviction checkpoint landed exactly on the chunk boundary
    assert tr._step_count % K == 0
    assert K in tr._ckpt.all_steps()
    # rejoin (restores the boundary checkpoint in grad-agg mode),
    # finish the schedule: parity with the uninterrupted run
    tr.rejoin()
    for i in range(K, total):
        one_step(net, tr, i)
    for (n1, p1), (n2, p2) in zip(ref.collect_params().items(),
                                  net.collect_params().items()):
        onp.testing.assert_allclose(
            p1.data().asnumpy(), p2.data().asnumpy(),
            rtol=1e-6, atol=1e-7, err_msg=n1)
    tr.close()
    kv._clients[0].call("stop")


def test_beat_thread_survives_unexpected_errors(tmp_path, monkeypatch,
                                                scenario_beats):
    """A beat failure that is neither a transport error nor an eviction
    notice (e.g. an injected PermanentFault) must not kill the
    heartbeat thread — a dead beat thread silently evicts a HEALTHY
    worker."""
    srv = _start_server("sync", num_workers=1)
    net, kv, tr = _elastic_trainer(tmp_path, monkeypatch, srv)
    _one_step(net, tr)
    fault.configure("kvstore.heartbeat:error:class=permanent:n=2")
    time.sleep(0.3)                     # beats hit the permanent fault
    fault.configure(None)
    time.sleep(0.2)                     # thread must still be beating
    assert tr._beat_thread.is_alive()
    _one_step(net, tr)                  # and the worker was never evicted
    tr.close()
    kv._clients[0].call("stop")


def test_trainer_step_fault_point_is_wired():
    from incubator_mxnet_tpu.gluon import nn, Trainer
    net = nn.Dense(2, in_units=3)
    net.initialize()
    net(nd.zeros((1, 3)))
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 kvstore=None)
    fault.configure("trainer.step:error:n=1:class=permanent")
    with pytest.raises(fault.PermanentFault):
        _one_step(net, tr)
    fault.configure(None)
    _one_step(net, tr)                  # recovered


def test_trainer_reshard_restore_lands_on_mesh(tmp_path, monkeypatch):
    """Trainer checkpoints restore onto a different mesh shape and the
    values land back in the parameters with the target sharding."""
    from incubator_mxnet_tpu.gluon import nn, Trainer
    net = nn.Dense(4, in_units=8)
    net.initialize()
    net(nd.zeros((1, 8)))
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 kvstore=None, checkpoint_dir=str(tmp_path))
    tr._ckpt.save(0, tr._param_tree(), wait=True)
    before = {n: onp.asarray(v.data)
              for n, v in tr._param_tree().items()}
    for p in tr._params:                # scribble over the live params
        p.set_data(nd.zeros(p.shape))
    mesh = make_mesh(dp=2)
    tree = tr.reshard_restore(mesh, rule_fn=leading_axis_rule(mesh))
    for n, v in tr._param_tree().items():
        onp.testing.assert_array_equal(onp.asarray(v.data), before[n])
    weight = next(k for k in tree if "weight" in k)
    assert tree[weight].sharding.spec == P("dp", None)


# ---------------------------------------------------------------------------
# the chaos scenario (tentpole c): kill → evict → converge → rejoin →
# bootstrap → final-weight parity with an uninterrupted run
# ---------------------------------------------------------------------------

TARGET = onp.array([1.0, -2.0, 3.0, 0.5], onp.float32)
LR = 0.5


def _grad(w):
    """Deterministic global-batch gradient: G(w) = w - TARGET (drives
    w → TARGET under SGD)."""
    return (w - TARGET).astype(onp.float32)


def _baseline(rounds):
    """The uninterrupted run: one worker pushing the full global-batch
    gradient each round against a server-side SGD."""
    import pickle
    srv = _start_server("sync", num_workers=1)
    c = PSClient("127.0.0.1", srv.port)
    c.call("init", "w", onp.zeros(4, onp.float32))
    c.call("set_optimizer", None,
           pickle.dumps(mx.optimizer.SGD(learning_rate=LR)))
    w = onp.zeros(4, onp.float32)
    for _ in range(rounds):
        c.call("push", "w", _grad(w))
        w = onp.array(c.call("pull", "w"))
    c.call("stop")
    return w


def _beat_all(clients):
    for c in clients:
        try:
            c.beat()
        except (ConnectionError, TimeoutError):
            pass       # a lost beat burns budget; the sweep decides


def _hb(client):
    """Vitals probe that tolerates chaos-injected probe loss."""
    while True:
        try:
            return client.heartbeat()
        except (ConnectionError, TimeoutError):
            time.sleep(0.01)


def _run_elastic_scenario(rounds_per_phase=3):
    """kill → evict → survivors converge → rejoin → bootstrap →
    final-weight parity with the uninterrupted baseline."""
    import pickle
    srv = _start_server("sync", num_workers=3)
    cs = [PSClient("127.0.0.1", srv.port) for _ in range(3)]
    for r, c in enumerate(cs):
        c.join(r)
    cs[0].call("init", "w", onp.zeros(4, onp.float32))
    cs[0].call("set_optimizer", None,
               pickle.dumps(mx.optimizer.SGD(learning_rate=LR)))

    def run_rounds(clients, w, n):
        for _ in range(n):
            _beat_all(clients)
            k = len(clients)
            for c in clients:          # data re-balanced over the
                c.call("push", "w", _grad(w) / k)   # live fleet
            w = onp.array(clients[0].call("pull", "w"))
        return w

    # phase 1: full fleet
    w = run_rounds(cs, onp.zeros(4, onp.float32), rounds_per_phase)

    # kill worker 2 mid-run: silent death, no goodbye
    cs[2].close()
    deadline = time.monotonic() + 10.0
    while _hb(cs[0])["live_workers"] != 2:
        _beat_all(cs[:2])
        assert time.monotonic() < deadline, "eviction never happened"
        time.sleep(0.03)

    # phase 2: survivors converge alone
    w = run_rounds(cs[:2], w, rounds_per_phase)

    # phase 3: the worker rejoins (fresh process = fresh session),
    # bootstraps by pulling current weights, fleet is whole again
    c2b = PSClient("127.0.0.1", srv.port)
    c2b.join(2)
    boot = onp.array(c2b.call("pull", "w"))     # bootstrap pull
    onp.testing.assert_allclose(boot, w, rtol=1e-6)
    w = run_rounds([cs[0], cs[1], c2b], w, rounds_per_phase)

    expect = _baseline(3 * rounds_per_phase)
    onp.testing.assert_allclose(w, expect, rtol=1e-6, atol=1e-7)
    # and the run actually went through an eviction + a rejoin
    assert _hb(cs[0])["live_workers"] == 3
    cs[0].call("stop")


def test_chaos_elastic_kill_rejoin_weight_parity(scenario_beats):
    """THE acceptance scenario: worker killed mid-run → evicted within
    the heartbeat budget → survivors keep training (each takes over the
    dead worker's share of the global batch, so the summed gradient is
    fleet-size invariant) → worker rejoins and bootstraps → final
    weights match an uninterrupted run."""
    _run_elastic_scenario()


def test_chaos_scenario_replays_under_seeded_spec(scenario_beats):
    """The CI elastic stage's pinned spec (lost acks + lost beats) must
    not change the scenario's outcome — retries, dedup, and the beat
    budget absorb it."""
    fault.configure("kvstore.recv:error:p=0.05:seed=11,"
                    "kvstore.heartbeat:error:p=0.2:seed=5")
    try:
        _run_elastic_scenario()
    finally:
        fault.configure(None)


# ---------------------------------------------------------------------------
# spec grammar covers the new points
# ---------------------------------------------------------------------------

def test_new_points_parse_and_registry_sync():
    pts = fault.parse_spec("kvstore.heartbeat:error:p=0.2:seed=5,"
                           "checkpoint.read:delay:ms=5,"
                           "trainer.step:error:class=permanent")
    assert set(pts) == {"kvstore.heartbeat", "checkpoint.read",
                        "trainer.step"}
    for p in ("kvstore.heartbeat", "checkpoint.read", "trainer.step"):
        assert p in fault.POINTS
