"""memlint (analysis/memlint.py) — liveness-based HBM planner/analyzer
and enforced end-to-end buffer donation (docs/graph_analysis.md).

Four batteries:

* the estimator itself — buffer liveness math on known graphs,
  donation/alias credit, the ML-DONATE001/ML-PEAK001 must-flag and
  must-pass fixtures, check_memory modes;
* the compile surfaces — fused train step and CachedOp static_alloc
  analyzed (and FAILED when seeded undonated under strict), with the
  CPU aliasing proof: ``unsafe_buffer_pointer`` reuse + donated-input
  deletion + absence of jax's "donated buffers were not usable"
  warning show the donation is real, not just planned;
* bulking dead-temporary reclamation — dropped intermediates never
  leave the compiled program, held ones still settle;
* the table contracts — ``ref_aliases.IDENTITY_ALIASES`` agrees with
  the registry's ``inplace_identity`` metadata in both directions, and
  the export/serving path records + re-applies ``donate_argnums``.
"""
import gc
import warnings

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import error, gluon, nd, profiler
from incubator_mxnet_tpu.analysis import memlint as ml
from incubator_mxnet_tpu.fuse import make_fused_train_step
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.ops import bulking
from incubator_mxnet_tpu.ops.ref_aliases import IDENTITY_ALIASES
from incubator_mxnet_tpu.ops.registry import _OPS


F32 = 4  # bytes


def _step(p, g):
    return p - 0.1 * g


# ---------------------------------------------------------------------------
# the estimator
# ---------------------------------------------------------------------------

def test_peak_counts_live_chain():
    # x (input, pinned) + a + b live together at the add; c is a scalar
    def chain(x):
        a = x * 2.0
        b = a + 1.0
        return b.sum()

    n = 256 * 256 * F32
    rep = ml.analyze_fn(chain, jnp.ones((256, 256)))
    assert rep.peak_bytes >= 2 * n
    assert rep.peak_bytes < 4 * n          # not everything at once
    assert rep.input_bytes == n
    assert rep.n_eqns >= 3
    # the lifetime report names the dominant buffers with birth/last
    top = rep.buffers[0]
    assert top["nbytes"] == n
    assert top["kind"] in ("input", "temp")


def test_donation_reclaims_matched_output():
    rep = ml.analyze_fn(_step, jnp.ones((1024,)), jnp.ones((1024,)),
                        donate_argnums=(0,), require_donation=True)
    assert rep.donated_bytes == 1024 * F32
    assert rep.donated_reclaimed_bytes == 1024 * F32
    assert rep.donation_coverage == 1.0
    assert rep.findings == []
    # the undonated twin holds input AND output alive: higher peak
    rep2 = ml.analyze_fn(_step, jnp.ones((1024,)), jnp.ones((1024,)))
    assert rep2.peak_bytes > rep.peak_bytes


def test_donate001_must_flag_and_must_pass():
    rep = ml.analyze_fn(_step, jnp.ones((1024,)), jnp.ones((1024,)),
                        require_donation=True)
    assert [f.rule for f in rep.findings] == ["ML-DONATE001"]
    assert rep.findings[0].severity == "error"
    assert rep.undonated_bytes == 1024 * F32
    # same match without the donation contract is an advisory
    rep = ml.analyze_fn(_step, jnp.ones((1024,)), jnp.ones((1024,)))
    assert [f.severity for f in rep.findings] == ["advisory"]
    # allow_undonated declares the caller-held arguments
    rep = ml.analyze_fn(_step, jnp.ones((1024,)), jnp.ones((1024,)),
                        allow_undonated=(0, 1), require_donation=True)
    assert rep.findings == []
    # below the byte floor nothing fires
    rep = ml.analyze_fn(_step, jnp.ones((8,)), jnp.ones((8,)),
                        require_donation=True)
    assert rep.findings == []


def test_donated_args_claim_slots_first():
    # p donated and matched; g's advisory must NOT re-claim p's slot —
    # with only one output there is nothing left for g to match
    def one_out(p, g):
        return p - 0.1 * g

    rep = ml.analyze_fn(one_out, jnp.ones((1024,)), jnp.ones((1024,)),
                        donate_argnums=(0,), require_donation=True)
    assert rep.findings == []


def test_alias_credit_for_views():
    rep = ml.analyze_fn(lambda x: x.reshape(32, 32) * 2.0,
                        jnp.ones((1024,)))
    assert rep.alias_credit_bytes == 1024 * F32
    # transpose changes layout: no credit
    rep2 = ml.analyze_fn(lambda x: x.T * 2.0, jnp.ones((64, 16)))
    assert rep2.alias_credit_bytes == 0


def test_subjaxpr_peak_recurses():
    def scanned(x):
        def body(c, _):
            t = jnp.outer(c, c)          # (512, 512) transient inside
            return c + t.sum() * 0.0, ()
        c, _ = jax.lax.scan(body, x, None, length=3)
        return c

    rep = ml.analyze_fn(scanned, jnp.ones((512,)))
    # the inner outer-product transient dominates: 512*512*4 = 1 MiB
    assert rep.peak_bytes >= 512 * 512 * F32


def test_peak001_budget_and_ignore():
    cfg = ml.Config(peak_bytes=1024)
    rep = ml.analyze_fn(lambda x: (x * 2 + 1).sum(), jnp.ones((4096,)),
                        config=cfg)
    assert any(f.rule == "ML-PEAK001" and f.severity == "error"
               for f in rep.findings)
    cfg2 = ml.Config(peak_bytes=1024, ignore={"ML-PEAK001"})
    rep2 = ml.analyze_fn(lambda x: (x * 2 + 1).sum(), jnp.ones((4096,)),
                         config=cfg2)
    assert rep2.findings == []


def test_check_memory_modes_and_scope():
    p, g = jnp.ones((1024,)), jnp.ones((1024,))
    # off by default: inert, returns None
    assert ml.check_memory(_step, (p, g), name="t:off") is None
    with ml.mem_scope("warn"):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            rep = ml.check_memory(_step, (p, g), name="t:warn",
                                  require_donation=True)
        assert rep is not None
        assert any("ML-DONATE001" in str(x.message) for x in w)
    with ml.mem_scope("strict"):
        with pytest.raises(error.MemLintError):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                ml.check_memory(_step, (p, g), name="t:strict",
                                require_donation=True)
        # MemLintError IS a GraphLintError (one gate to catch on)
        assert issubclass(error.MemLintError, error.GraphLintError)
        # a donated call under strict passes and records its site
        rep = ml.check_memory(_step, (p, g), name="t:ok",
                              donate_argnums=(0,), require_donation=True)
        assert rep.donated_reclaimed_bytes == 1024 * F32
    assert ml.mem_mode() is None   # scope restored
    # a crash in the analysis warns, never raises (build must survive)
    with ml.mem_scope("strict"):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = ml.check_memory(lambda x: undefined_name, (p,),  # noqa: F821
                                  name="t:crash")
        assert out is None
        assert any("could not analyze" in str(x.message) for x in w)


def test_stats_provider_in_profiler_dumps():
    with ml.mem_scope("warn"):
        ml.check_memory(_step, (jnp.ones((1024,)), jnp.ones((1024,))),
                        name="t:provider", donate_argnums=(0,))
    st = ml.stats()
    assert st["per_site"]["t:provider"]["donated_bytes_reclaimed"] == 4096
    assert st["donated_bytes_reclaimed"] >= 4096
    assert "memlint" in profiler.dumps()


# ---------------------------------------------------------------------------
# the fused-train-step surface (+ CPU aliasing proof)
# ---------------------------------------------------------------------------

def _net(in_units=32, hidden=64):
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, in_units=in_units), nn.Activation("relu"),
            nn.Dense(3, in_units=hidden))
    net.initialize()
    net(nd.ones((2, in_units)))
    return net


def _xy(in_units=32):
    return nd.ones((2, in_units)), nd.array([0, 1])


def test_fused_step_donated_passes_strict_with_full_coverage():
    ml.reset_stats()
    step = make_fused_train_step(_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1})
    x, y = _xy()
    with ml.mem_scope("strict"):
        step(x, y)
    site = ml.stats()["per_site"]["fused_step:HybridSequential"]
    assert site["donation_coverage"] == 1.0
    assert site["donated_bytes_reclaimed"] > 0
    assert site["findings"] == 0
    assert site["peak_hbm_bytes"] > 0


def test_fused_step_undonated_raises_strict():
    step = make_fused_train_step(_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1},
                                 donate=False)
    x, y = _xy()
    with ml.mem_scope("strict"):
        with pytest.raises(error.MemLintError) as ei:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                step(x, y)
    assert "ML-DONATE001" in str(ei.value)


def test_fused_step_actually_reuses_donated_buffers():
    """CPU aliasing proof: the donated param/opt-state buffers are
    really consumed (deleted) and at least some output buffers land on
    the donated pointers — and jax emits no 'donated buffers were not
    usable' warning."""
    step = make_fused_train_step(_net(), gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1})
    old_arrays = list(step.params.values()) + \
        list(step.opt_state["mom"].values())
    old_ptrs = {a.unsafe_buffer_pointer() for a in old_arrays}
    x, y = _xy()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        step(x, y)
    assert not any("donated" in str(x.message).lower() for x in w), \
        [str(x.message) for x in w]
    # the donated inputs are gone...
    assert all(a.is_deleted() for a in old_arrays)
    # ...and the updated params reuse buffers from the donated pool
    new_ptrs = {a.unsafe_buffer_pointer() for a in step.params.values()}
    assert new_ptrs & old_ptrs, (new_ptrs, old_ptrs)
    # the step still trains (second call, buffers rotate again)
    step(x, y)


# ---------------------------------------------------------------------------
# the CachedOp static_alloc surface (+ CPU aliasing proof)
# ---------------------------------------------------------------------------

def test_cachedop_static_alloc_donates_input_buffer():
    """static_alloc's donation is real: the input chunk's device buffer
    is consumed, and for a shape-preserving block the output lands on
    the input's pointer (XLA aliased it)."""
    net = nn.HybridSequential()
    net.add(nn.Activation("relu"))
    net.initialize()
    net.hybridize(static_alloc=True)
    x = nd.array(onp.random.RandomState(0).randn(64, 64).astype("f"))
    raw = x.data
    ptr = raw.unsafe_buffer_pointer()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = net(x)
        out_val = out.data
    assert not any("donated" in str(m.message).lower() for m in w), \
        [str(m.message) for m in w]
    assert raw.is_deleted()          # the donated input is consumed
    assert out_val.unsafe_buffer_pointer() == ptr   # aliased in place
    onp.testing.assert_array_equal(onp.asarray(out_val) >= 0, True)


@pytest.mark.filterwarnings("ignore:Some donated buffers were not usable")
def test_cachedop_static_alloc_strict_memlint_clean():
    # the (2,32) input has no same-shape output in this net: XLA warns
    # the donation is unusable (wasted, not wrong) — expected here
    ml.reset_stats()
    net = _net()
    net.hybridize(static_alloc=True)
    with ml.mem_scope("strict"):
        net(nd.ones((2, 32)))        # cache-miss build analyzes
    site = ml.stats()["per_site"]["cachedop:HybridSequential"]
    assert site["findings"] == 0
    assert site["peak_hbm_bytes"] > 0


def test_cachedop_plain_records_stats_without_errors():
    ml.reset_stats()
    net = _net()
    net.hybridize()
    with ml.mem_scope("strict"):     # params/inputs caller-held: clean
        net(nd.ones((2, 32)))
    site = ml.stats()["per_site"]["cachedop:HybridSequential"]
    assert site["findings"] == 0


# ---------------------------------------------------------------------------
# bulking dead-temporary reclamation
# ---------------------------------------------------------------------------

def test_bulk_dead_intermediates_dropped_and_counted():
    ml.reset_stats()
    with bulking.bulk_scope(True):
        a = nd.ones((64, 64))
        b = nd.ones((64, 64))
        d = nd.ones((64, 64))
        c = (a + b) * d + a          # two dead intermediates
        out = c.asnumpy()
    onp.testing.assert_array_equal(out, onp.full((64, 64), 3.0, "f"))
    st = ml.stats()
    assert st["bulk_temp_reclaimed_bytes"] == 2 * 64 * 64 * F32
    assert st["bulk_temp_reclaimed_buffers"] == 2


def test_bulk_held_intermediate_still_settles():
    ml.reset_stats()
    with bulking.bulk_scope(True):
        a = nd.ones((32,))
        b = nd.ones((32,))
        t = a + b
        c = t * 2
        cn, tn = c.asnumpy(), t.asnumpy()
    onp.testing.assert_array_equal(tn, onp.full((32,), 2.0, "f"))
    onp.testing.assert_array_equal(cn, onp.full((32,), 4.0, "f"))
    assert ml.stats()["bulk_temp_reclaimed_bytes"] == 0


def test_bulk_view_of_dead_wrapper_keeps_buffer():
    # a view shares the chunk: dropping only the base wrapper must NOT
    # drop the output another NDArray still reads through the chunk
    with bulking.bulk_scope(True):
        a = nd.ones((4, 8))
        b = nd.ones((4, 8))
        t = a + b
        v = t.reshape((8, 4))        # view shares t's chunk
        del t
        gc.collect()
        out = v.asnumpy()
    onp.testing.assert_array_equal(out, onp.full((8, 4), 2.0, "f"))


def test_bulk_drop_dead_kill_switch(monkeypatch):
    ml.reset_stats()
    monkeypatch.setattr(bulking, "_env_drop_dead", False)
    with bulking.bulk_scope(True):
        a = nd.ones((16,))
        c = (a + 1) * 2
        c.asnumpy()
    assert ml.stats()["bulk_temp_reclaimed_bytes"] == 0


def test_bulk_dropped_placeholder_resolve_is_typed():
    # internal-API misuse: resolving a raw dropped placeholder gets a
    # clear sticky error, never a silent wrong value
    with bulking.bulk_scope(True):
        a = nd.ones((8,))
        t = a + 1
        pending = t._chunk.array
        assert type(pending) is bulking.PendingArray
        s = t * 2
        del t
        gc.collect()
        s.asnumpy()                  # flush: t's output dropped
    with pytest.raises(RuntimeError, match="dropped at flush"):
        bulking.resolve(pending)


def test_bulk_mode_parity_with_eager():
    rng = onp.random.RandomState(3)
    xs = [rng.randn(16, 16).astype("f") for _ in range(3)]

    def compute():
        a, b, c = (nd.array(v) for v in xs)
        return (((a * b) + c) * (a - c)).asnumpy()

    eager = compute()
    with bulking.bulk_scope(True):
        bulked = compute()
    onp.testing.assert_allclose(bulked, eager, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# table contracts: ref_aliases vs. registry
# ---------------------------------------------------------------------------

def test_identity_alias_table_matches_registry_both_directions():
    """memlint's op-level aliasing credit trusts IDENTITY_ALIASES; the
    registry's inplace_identity metadata must agree exactly."""
    # every registered name of an op marked inplace_identity is in the
    # table with the same input index
    for name, op in _OPS.items():
        if op.inplace_identity is not None:
            assert IDENTITY_ALIASES.get(name) == op.inplace_identity, \
                f"op {name!r} is registered inplace_identity=" \
                f"{op.inplace_identity} but ref_aliases.IDENTITY_ALIASES " \
                f"has {IDENTITY_ALIASES.get(name)!r}"
    # every table entry names a registered op carrying the metadata
    for name, idx in IDENTITY_ALIASES.items():
        op = _OPS.get(name)
        assert op is not None, f"IDENTITY_ALIASES names unregistered {name!r}"
        assert op.inplace_identity == idx, \
            f"IDENTITY_ALIASES[{name!r}]={idx} but the registry says " \
            f"{op.inplace_identity!r}"


def test_segment_alias_credit_uses_table():
    # always-on, per-flush (the same accumulation basis as the reclaim
    # counter) — no memlint mode and no fresh compile required
    ml.reset_stats()
    with bulking.bulk_scope(True):
        a = nd.ones((64, 64))
        # the registered reshape OP (the NDArray .reshape method is
        # a chunk view, not a segment node)
        b = nd.reshape(a + 1, shape=(4096,))
        b.asnumpy()
    assert ml.stats()["bulk_alias_credit_bytes"] == 64 * 64 * F32
    # cache-hit replay counts again: per flush, like reclaimed bytes
    with bulking.bulk_scope(True):
        a = nd.ones((64, 64))
        nd.reshape(a + 1, shape=(4096,)).asnumpy()
    assert ml.stats()["bulk_alias_credit_bytes"] == 2 * 64 * 64 * F32


# ---------------------------------------------------------------------------
# export / serving path
# ---------------------------------------------------------------------------

def _export(tmp_path, donate=(1,)):
    from incubator_mxnet_tpu import deploy

    def fwd(params, x):
        return x @ params["w"] + params["b"]

    params = {"w": jnp.ones((16, 16)), "b": jnp.zeros((16,))}
    prefix = str(tmp_path / "m")
    meta = deploy.export_model(fwd, (jnp.ones((4, 16)),), prefix,
                               params=params, donate_argnums=donate)
    return prefix, meta


def test_export_records_memlint_summary_and_donation(tmp_path):
    prefix, meta = _export(tmp_path)
    assert meta["donate_argnums"] == [1]
    s = meta["memlint"]
    assert s["peak_hbm_bytes"] > 0
    assert s["donated_bytes_reclaimed"] == 4 * 16 * F32
    assert s["donation_coverage"] == 1.0
    # persisted for the serving layer
    import json
    disk = json.load(open(prefix + ".meta.json"))
    assert disk["memlint"]["peak_hbm_bytes"] == s["peak_hbm_bytes"]


def test_export_rejects_params_slot_donation(tmp_path):
    with pytest.raises(ValueError, match="params"):
        _export(tmp_path, donate=(0,))
    with pytest.raises(ValueError, match="out of range"):
        _export(tmp_path, donate=(3,))


def test_predictor_reapplies_donation(tmp_path):
    from incubator_mxnet_tpu import deploy
    prefix, _ = _export(tmp_path)
    pred = deploy.load_predictor(prefix)
    x = jnp.ones((4, 16))
    out = pred(x)
    assert x.is_deleted()            # the donated request buffer is gone
    # numpy callers are unaffected (asarray copies to device) and the
    # predictor keeps serving — params were never donated
    out2 = pred(onp.ones((4, 16), onp.float32))
    onp.testing.assert_allclose(out, out2)
    # polymorphic batch path carries the same donation
    out3 = pred(onp.ones((7, 16), onp.float32))
    assert out3.shape == (7, 16)


def test_undonated_export_still_serves(tmp_path):
    from incubator_mxnet_tpu import deploy
    prefix, meta = _export(tmp_path, donate=())
    assert meta["donate_argnums"] == []
    pred = deploy.load_predictor(prefix)
    x = jnp.ones((4, 16))
    pred(x)
    assert not x.is_deleted()


def test_repository_surfaces_memory_summary(tmp_path):
    from incubator_mxnet_tpu.serving.metrics import ServingMetrics
    from incubator_mxnet_tpu.serving.model_repository import ModelRepository
    prefix, _ = _export(tmp_path)
    metrics = ServingMetrics()
    repo = ModelRepository(metrics=metrics, warmup=False)
    try:
        desc = repo.load("m", prefix)
        assert desc["memlint"]["peak_hbm_bytes"] > 0
        assert desc["memlint"]["donated_bytes_reclaimed"] > 0
        text = metrics.render()
        assert 'mxnet_serving_model_peak_hbm_bytes{model="m"}' in text
        assert ('mxnet_serving_model_donated_bytes_reclaimed{model="m"}'
                in text)
        snap = metrics.snapshot()
        assert snap["m.peak_hbm_bytes"] > 0
    finally:
        repo.drain_all(timeout=5)
