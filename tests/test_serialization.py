"""Reference checkpoint interop tests (VERDICT r2 task #5).

The fixture in tests/fixtures/ was written byte-for-byte in the
reference's on-disk formats by make_ref_fixture.py using only the
stdlib (layout per reference src/ndarray/ndarray.cc:1679-1924), so
loading it here proves a reference-produced checkpoint loads bit-exact.
"""
import os

import numpy as onp
import pytest

import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, sym, model
from incubator_mxnet_tpu.ndarray.sparse import RowSparseNDArray, CSRNDArray
from incubator_mxnet_tpu.gluon import SymbolBlock

FIX = os.path.join(os.path.dirname(__file__), "fixtures")
PREFIX = os.path.join(FIX, "refmlp")


# ---------------------------------------------------------------------------
# .params TLV reader against the committed reference-format fixture
# ---------------------------------------------------------------------------

def test_load_reference_params_bit_exact():
    loaded = nd.load(PREFIX + "-0000.params")
    expected = onp.load(PREFIX + "-expected.npz")
    assert set(loaded) == {"arg:fc1_weight", "arg:fc1_bias",
                           "arg:fc2_weight", "arg:fc2_bias",
                           "arg:embed_weight"}
    for name in ("fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"):
        got = loaded[f"arg:{name}"].asnumpy()
        onp.testing.assert_array_equal(got, expected[name])
        assert got.dtype == expected[name].dtype
    rs = loaded["arg:embed_weight"]
    assert isinstance(rs, RowSparseNDArray)
    onp.testing.assert_array_equal(onp.asarray(rs._rs_values),
                                   expected["embed_weight_vals"])
    onp.testing.assert_array_equal(onp.asarray(rs._rs_indices),
                                   expected["embed_weight_rows"])


def test_load_checkpoint_reference_files_forward():
    symbol, arg_params, aux_params = model.load_checkpoint(PREFIX, 0)
    assert "fc1_weight" in arg_params and not aux_params
    ex = symbol.simple_bind(data=(2, 8))
    for k, v in arg_params.items():
        if k in ex.arg_dict and k != "data":
            ex.arg_dict[k][:] = v
    out = ex.forward(data=mx.nd.ones((2, 8)))
    probs = out[0].asnumpy() if isinstance(out, list) else out.asnumpy()
    assert probs.shape == (2, 4)
    onp.testing.assert_allclose(probs.sum(axis=-1), 1.0, rtol=1e-5)


def test_symbolblock_imports_reference_checkpoint():
    net = SymbolBlock.imports(PREFIX + "-symbol.json", ["data"],
                              PREFIX + "-0000.params")
    out = net(mx.nd.ones((3, 8)))
    assert out.shape == (3, 4)
    # forward must equal the hand-computed MLP on the fixture weights
    exp = onp.load(PREFIX + "-expected.npz")
    x = onp.ones((3, 8), onp.float32)
    h = onp.maximum(x @ exp["fc1_weight"].T + exp["fc1_bias"], 0)
    logits = h @ exp["fc2_weight"].T + exp["fc2_bias"]
    ref = onp.exp(logits - logits.max(-1, keepdims=True))
    ref /= ref.sum(-1, keepdims=True)
    onp.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# writer round trips through the same wire format
# ---------------------------------------------------------------------------

def test_save_load_roundtrip_dense_dtypes(tmp_path):
    path = str(tmp_path / "t.params")
    data = {
        "f32": nd.array(onp.random.randn(3, 4).astype(onp.float32)),
        "i64": nd.array(onp.arange(6, dtype=onp.int64).reshape(2, 3)),
        "u8": nd.array(onp.arange(8, dtype=onp.uint8)),
        "bf16": nd.NDArray(jnp.asarray([[1.5, -2.25]], jnp.bfloat16)),
    }
    nd.save(path, data)
    back = nd.load(path)
    for k in data:
        a, b = data[k], back[k]
        assert a.data.dtype == b.data.dtype, k
        onp.testing.assert_array_equal(
            onp.asarray(a.data.astype(jnp.float32)),
            onp.asarray(b.data.astype(jnp.float32)))


def test_save_load_roundtrip_list_unnamed(tmp_path):
    path = str(tmp_path / "l.params")
    arrs = [nd.ones((2, 2)), nd.zeros((3,))]
    nd.save(path, arrs)
    back = nd.load(path)
    assert isinstance(back, list) and len(back) == 2
    onp.testing.assert_array_equal(back[0].asnumpy(), onp.ones((2, 2)))


def test_save_load_roundtrip_sparse(tmp_path):
    path = str(tmp_path / "s.params")
    rs = mx.nd.sparse.row_sparse_array(
        (onp.ones((2, 3), onp.float32), onp.array([1, 3])), shape=(5, 3))
    csr = mx.nd.sparse.csr_matrix(
        (onp.array([1.0, 2.0], onp.float32), onp.array([0, 2]),
         onp.array([0, 1, 2])), shape=(2, 3))
    nd.save(path, {"rs": rs, "csr": csr})
    back = nd.load(path)
    assert isinstance(back["rs"], RowSparseNDArray)
    assert isinstance(back["csr"], CSRNDArray)
    onp.testing.assert_array_equal(back["rs"].asnumpy(), rs.asnumpy())
    onp.testing.assert_array_equal(back["csr"].asnumpy(), csr.asnumpy())


def test_save_checkpoint_roundtrip(tmp_path):
    prefix = str(tmp_path / "ck")
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="fc1")
    args = {"fc1_weight": nd.ones((4, 3)), "fc1_bias": nd.zeros((4,))}
    model.save_checkpoint(prefix, 7, fc, args, {})
    s2, a2, _ = model.load_checkpoint(prefix, 7)
    assert set(a2) == set(args)
    onp.testing.assert_array_equal(a2["fc1_weight"].asnumpy(),
                                   args["fc1_weight"].asnumpy())


def test_legacy_mxtpu_container_still_loads(tmp_path):
    # round-1 files must stay readable: craft one in the old format
    import struct
    path = str(tmp_path / "old.params")
    arr = onp.arange(6, dtype=onp.float32).reshape(2, 3)
    with open(path, "wb") as f:
        f.write(b"MXTPU001")
        f.write(struct.pack("<q", 1))
        key = b"w"
        f.write(struct.pack("<q", len(key))); f.write(key)
        dn = b"float32"
        f.write(struct.pack("<q", len(dn))); f.write(dn)
        f.write(struct.pack("<q", 2))
        f.write(struct.pack("<q", 2)); f.write(struct.pack("<q", 3))
        b = arr.tobytes()
        f.write(struct.pack("<q", len(b))); f.write(b)
    back = nd.load(path)
    onp.testing.assert_array_equal(back["w"].asnumpy(), arr)
