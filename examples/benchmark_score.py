"""Model-zoo inference throughput — the TPU counterpart of the
reference's headline perf script
(example/image-classification/benchmark_score.py, whose numbers fill
docs perf.md:165-215 and BASELINE.md).

For each (model, batch_size) it compiles the hybridized forward once
and reports img/s with the platform's honest sync discipline (host
readback inside the timed region — jax.block_until_ready does not wait
on the axon tunnel).

Usage:
    python examples/benchmark_score.py                    # default set
    python examples/benchmark_score.py --models resnet50_v1 vgg16 \
        --batch-sizes 1 32 --image-shape 3,224,224 --dtype bfloat16
"""
import argparse
import time

import numpy as onp


def score(model_name, batch_size, image_shape, dtype, steps, warmup):
    import jax
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, amp
    from incubator_mxnet_tpu.gluon.model_zoo import vision

    accel = jax.devices()[0]
    cpu0 = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu0):  # eager setup off the accelerator
        net = getattr(vision, model_name)()
        net.initialize(ctx=mx.cpu())
        net(nd.random.uniform(shape=(1,) + image_shape))  # shape resolve
        if dtype == "bfloat16":
            amp.convert_block(net, "bfloat16")
        params, apply_fn = net.functional()
        x = jnp.asarray(
            onp.random.rand(batch_size, *image_shape),
            jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    fwd = jax.jit(lambda p, x: apply_fn(p, x, training=False))
    params = jax.tree_util.tree_map(lambda t: jax.device_put(t, accel),
                                    params)
    x = jax.device_put(x, accel)

    out = fwd(params, x)
    float(jnp.asarray(out).ravel()[0])  # compile + sync
    for _ in range(warmup):
        out = fwd(params, x)
    float(jnp.asarray(out).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fwd(params, x)
    float(jnp.asarray(out).ravel()[0])  # sync INSIDE the timed region
    dt = time.perf_counter() - t0
    return batch_size * steps / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", nargs="+", default=[
        "alexnet", "vgg16", "inception_v3", "resnet50_v1", "resnet152_v1",
        "mobilenet1_0", "densenet121", "squeezenet1_1"])
    ap.add_argument("--batch-sizes", nargs="+", type=int,
                    default=[1, 32, 64, 128])
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    args = ap.parse_args()
    shape = tuple(int(d) for d in args.image_shape.split(","))
    import os
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # sitecustomize re-adds the axon plugin programmatically; honor
        # an explicit CPU request (same pattern as train_mnist.py)
        import jax
        jax.config.update("jax_platforms", "cpu")

    print(f"image_shape={shape} dtype={args.dtype}")
    for model in args.models:
        for bs in args.batch_sizes:
            try:
                ips = score(model, bs, shape, args.dtype, args.steps,
                            args.warmup)
                print(f"{model:16s} bs={bs:4d}  {ips:10.1f} img/s",
                      flush=True)
            except Exception as e:  # noqa: BLE001 — keep sweeping
                print(f"{model:16s} bs={bs:4d}  FAILED: {e}", flush=True)
    print("done")


if __name__ == "__main__":
    main()
