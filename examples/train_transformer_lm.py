#!/usr/bin/env python
"""Train the flagship TransformerLM over a device mesh
(dp/tp/sp/pp/ep — SURVEY.md §7 stage 10; no reference equivalent).

  python examples/train_transformer_lm.py --dp 2 --tp 2 --pp 2 [--smoke]

On real hardware the mesh spans TPU chips over ICI; under --smoke it
runs on 8 virtual CPU devices.
"""
import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--attention", default="gspmd",
                    choices=["gspmd", "ring", "flash"])
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        import os
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.parallel.mesh import make_mesh
    from incubator_mxnet_tpu.models.transformer import (TransformerConfig,
                                                        TransformerLM)

    if args.smoke:
        cfg = TransformerConfig(vocab_size=256, d_model=64, n_heads=4,
                                n_layers=2, d_ff=128, max_len=64,
                                dtype="float32", attention=args.attention)
        B, T, steps = 8, 33, 3
    else:
        cfg = TransformerConfig(attention=args.attention)
        B, T, steps = 32, 1025, args.steps

    mesh = make_mesh(dp=args.dp, tp=args.tp, pp=args.pp, sp=args.sp)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    params = model.shard_params(params, mesh)
    # donate: params are pure carry in this loop, so the update writes
    # in place (one param copy in HBM instead of two)
    step, tok_sharding = model.make_train_step(mesh, lr=1e-3, donate=True)
    key = jax.random.PRNGKey(1)
    for i in range(steps):
        key, sub = jax.random.split(key)
        tokens = jax.device_put(
            jax.random.randint(sub, (B, T), 0, cfg.vocab_size),
            tok_sharding)
        params, loss = step(params, tokens)
        print(f"step {i}: loss {float(loss):.4f}", flush=True)
    print("done")


if __name__ == "__main__":
    main()
