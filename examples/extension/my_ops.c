/* Example external-op library for the mxt ext-op C ABI
 * (src/include/mxt/ext_op.h; reference example/extensions/lib_custom_op).
 * Build: gcc -shared -fPIC -I../../src my_ops.c -o libmyops.so
 * Ops: my_relu(x), my_scaled_add(a, b)  [out = a + 2*b]
 */
#include <string.h>
#include "include/mxt/ext_op.h"

int mxt_ext_abi_version(void) { return MXT_EXT_ABI_VERSION; }
int mxt_ext_num_ops(void) { return 2; }

const char* mxt_ext_op_name(int idx) {
  return idx == 0 ? "my_relu" : "my_scaled_add";
}

int mxt_ext_op_num_inputs(int idx) { return idx == 0 ? 1 : 2; }

static int64_t numel(const int64_t* shape, int ndim) {
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}

int mxt_ext_op_infer_shape(int idx, int nin,
                           const int64_t* const* in_shapes,
                           const int* in_ndims,
                           int64_t* out_shape, int* out_ndim) {
  (void)idx; (void)nin;
  *out_ndim = in_ndims[0];
  memcpy(out_shape, in_shapes[0], in_ndims[0] * sizeof(int64_t));
  return 0;
}

int mxt_ext_op_forward(int idx, int nin,
                       const float* const* in_data,
                       const int64_t* const* in_shapes,
                       const int* in_ndims,
                       float* out_data) {
  (void)nin;
  int64_t n = numel(in_shapes[0], in_ndims[0]);
  if (idx == 0) {
    for (int64_t i = 0; i < n; ++i)
      out_data[i] = in_data[0][i] > 0.f ? in_data[0][i] : 0.f;
  } else {
    for (int64_t i = 0; i < n; ++i)
      out_data[i] = in_data[0][i] + 2.f * in_data[1][i];
  }
  return 0;
}
