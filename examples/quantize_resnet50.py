#!/usr/bin/env python
"""Int8 post-training quantization of ResNet-50, end to end (VERDICT r3
Next #5; reference example/quantization/imagenet_gen_qsym_mkldnn.py +
python/mxnet/contrib/quantization.py flow).

Calibrates with BOTH calib modes (minmax + entropy-KL), runs int8
inference, and reports top-1 agreement vs the float model and img/s for
float vs int8 — one JSON line per configuration.

No ImageNet ships in this environment, so data is synthetic by default
(top-1 *agreement with the float model* plays the reference's top-1
delta role: on real data they coincide up to label noise).  Point
--data-rec at an ImageNet recordio to measure true top-1.

Runs on whatever backend jax selects (TPU when the chip answers; CPU
otherwise — platform is recorded in the report line).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50_v1")
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--eval-batches", type=int, default=4)
    p.add_argument("--calib-batches", type=int, default=2)
    p.add_argument("--modes", default="naive,entropy")
    p.add_argument("--fuse-bn", action="store_true",
                   help="fold BatchNorm into convs before calibration "
                        "(fewer layers to calibrate; the standard "
                        "deploy-quantization flow)")
    p.add_argument("--exclude-layers", default="output",
                   help="comma-separated layer names kept float "
                        "(default: the classifier head, matching the "
                        "reference examples' excluded_sym_names)")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")
    args = p.parse_args(argv)

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as onp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.contrib.quantization import quantize_net
    from incubator_mxnet_tpu.gluon.model_zoo import vision

    print("[int8] probing device...", file=sys.stderr, flush=True)
    platform = jax.devices()[0].platform
    print(f"[int8] platform={platform}", file=sys.stderr, flush=True)
    rng = onp.random.RandomState(0)
    shape = (args.batch, 3, args.image_size, args.image_size)
    eval_x = [nd.array(rng.rand(*shape).astype(onp.float32))
              for _ in range(args.eval_batches)]
    calib_x = eval_x[:args.calib_batches]

    def build():
        mx.random.seed(0)
        net = getattr(vision, args.model)()
        net.initialize(ctx=mx.cpu())
        net(nd.zeros((1, 3, args.image_size, args.image_size)))
        if args.fuse_bn:
            from incubator_mxnet_tpu.gluon.contrib import fuse_conv_bn
            fuse_conv_bn(net)
        # whole-graph jit: eager per-op dispatch through the TPU tunnel
        # costs one compile per distinct op/shape — hybridize collapses
        # the model to a single compiled program per input shape
        net.hybridize()
        return net

    def top1(net):
        return [net(x).asnumpy().argmax(1) for x in eval_x]

    def imgs_per_sec(net):
        net(eval_x[0])                      # warm/compile
        t0 = time.perf_counter()
        for x in eval_x:
            out = net(x)
        float(out.asnumpy().ravel()[0])     # host sync
        dt = time.perf_counter() - t0
        return args.batch * len(eval_x) / dt

    float_net = build()
    print("[int8] float model built; evaluating...", file=sys.stderr,
          flush=True)
    ref_pred = top1(float_net)
    float_ips = imgs_per_sec(float_net)
    print(f"[int8] float baseline {float_ips:.1f} img/s", file=sys.stderr,
          flush=True)

    for mode in args.modes.split(","):
        print(f"[int8] calibrating mode={mode}...", file=sys.stderr,
              flush=True)
        qnet = quantize_net(build(), calib_data=calib_x, calib_mode=mode,
                            exclude_layers=tuple(
                                args.exclude_layers.split(",")),
                            num_calib_batches=args.calib_batches)
        if hasattr(qnet, "hybridize"):
            qnet.hybridize()
        print(f"[int8] mode={mode} quantized; evaluating...",
              file=sys.stderr, flush=True)
        q_pred = top1(qnet)
        agree = float(onp.mean([(a == b).mean()
                                for a, b in zip(ref_pred, q_pred)]))
        q_ips = imgs_per_sec(qnet)
        print(json.dumps({
            "model": args.model, "platform": platform,
            "calib_mode": mode, "batch": args.batch,
            "top1_agreement_vs_float": round(agree, 4),
            "float_img_per_sec": round(float_ips, 2),
            "int8_img_per_sec": round(q_ips, 2),
            "speedup": round(q_ips / float_ips, 3),
        }), flush=True)


if __name__ == "__main__":
    main()
