#!/usr/bin/env python
"""LeNet on (synthetic) MNIST — the reference example/image-classification
starter, on the TPU-native stack.

  python examples/train_mnist.py [--epochs 2] [--batch-size 64] [--smoke]

Uses the Gluon API end-to-end: HybridBlock -> hybridize (whole-graph XLA
compile) -> Trainer(kvstore 'device').
"""
import argparse
import time

import numpy as onp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny synthetic run (CI)")
    args = ap.parse_args()
    if args.smoke:
        import os
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, autograd, gluon
    from incubator_mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(32, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Conv2D(64, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2), nn.Flatten(),
            nn.Dense(128, activation="relu"), nn.Dense(10))
    net.initialize(ctx=mx.tpu())
    net.hybridize()

    n = 256 if args.smoke else 8192
    rng = onp.random.RandomState(0)
    images = rng.rand(n, 1, 28, 28).astype(onp.float32)
    labels = rng.randint(0, 10, (n,)).astype(onp.float32)

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr}, kvstore="device")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = gluon.metric.Accuracy()
    epochs = 1 if args.smoke else args.epochs
    bs = args.batch_size
    for epoch in range(epochs):
        metric.reset()
        t0 = time.time()
        for i in range(0, n - bs + 1, bs):
            x = nd.array(images[i:i + bs])
            y = nd.array(labels[i:i + bs])
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(bs)
            metric.update([y], [out])
        name, acc = metric.get()
        print(f"epoch {epoch}: {name}={acc:.3f} "
              f"({n / (time.time() - t0):.0f} samples/s)")
    print("done")


if __name__ == "__main__":
    main()
