#!/usr/bin/env python
"""LeNet on MNIST-style digits — the reference example/image-classification
starter, on the TPU-native stack.

  python examples/train_mnist.py [--epochs 2] [--batch-size 64] [--smoke]
  python examples/train_mnist.py --dataset digits   # REAL data, asserts
                                                    # the accuracy target

Uses the Gluon API end-to-end: HybridBlock -> hybridize (whole-graph XLA
compile) -> DataLoader -> Trainer(kvstore 'device').

``--dataset digits`` is the accuracy-parity config (VERDICT r4 Next #4;
reference analog: tests/python/train/test_conv.py, which trains MNIST
to an asserted 0.98 top-1): this environment has no network egress, so
the real-data point uses the offline-available scikit-learn handwritten
digits (1797 genuine 8x8 samples of the same task family), split
80/20, trained through the full stack and asserted to >=0.97 held-out
top-1 — a convergence proof on real data, not a synthetic loss curve.
"""
import argparse
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_digits_data():
    """Real handwritten digits, deterministic 80/20 split, normalized."""
    from sklearn.datasets import load_digits
    d = load_digits()
    x = (d.images / 16.0).astype(onp.float32)[:, None, :, :]  # NCHW
    y = d.target.astype(onp.float32)
    rng = onp.random.RandomState(42)
    idx = rng.permutation(len(x))
    n_test = len(x) // 5
    test, train = idx[:n_test], idx[n_test:]
    return (x[train], y[train]), (x[test], y[test])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=None,
                    help="default: 2 synthetic, 40 digits")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--dataset", choices=["synthetic", "digits"],
                    default="synthetic")
    ap.add_argument("--target-acc", type=float, default=0.97,
                    help="asserted held-out top-1 for --dataset digits")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny synthetic run (CI)")
    args = ap.parse_args()
    if args.smoke:
        import os
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, autograd, gluon
    from incubator_mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(32, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Conv2D(64, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2), nn.Flatten(),
            nn.Dense(128, activation="relu"), nn.Dense(10))
    net.initialize(ctx=mx.tpu())
    net.hybridize()

    if args.dataset == "digits":
        (images, labels), (timages, tlabels) = load_digits_data()
        n = len(images)
        epochs = args.epochs if args.epochs is not None else 40
    else:
        n = 256 if args.smoke else 8192
        rng = onp.random.RandomState(0)
        images = rng.rand(n, 1, 28, 28).astype(onp.float32)
        labels = rng.randint(0, 10, (n,)).astype(onp.float32)
        timages = tlabels = None
        epochs = 1 if args.smoke else (
            args.epochs if args.epochs is not None else 2)

    bs = args.batch_size
    dataset = gluon.data.ArrayDataset(images, labels)
    loader = gluon.data.DataLoader(dataset, batch_size=bs, shuffle=True,
                                   last_batch="discard")
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr}, kvstore="device")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = gluon.metric.Accuracy()
    for epoch in range(epochs):
        metric.reset()
        t0 = time.time()
        for x, y in loader:
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(bs)
            metric.update([y], [out])
        name, acc = metric.get()
        print(f"epoch {epoch}: {name}={acc:.3f} "
              f"({n / (time.time() - t0):.0f} samples/s)")

    if timages is not None:
        metric.reset()
        for i in range(0, len(timages), bs):
            x = nd.array(timages[i:i + bs])
            y = nd.array(tlabels[i:i + bs])
            metric.update([y], [net(x)])
        _, test_acc = metric.get()
        import jax
        print(f"RESULT digits_test_top1 {test_acc:.4f} "
              f"(target {args.target_acc}) "
              f"platform={jax.devices()[0].platform}")
        assert test_acc >= args.target_acc, (
            f"held-out top-1 {test_acc:.4f} < target {args.target_acc}")
    print("done")


if __name__ == "__main__":
    main()
