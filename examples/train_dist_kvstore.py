#!/usr/bin/env python
"""Multi-process data-parallel training through the kvstore — the
reference example/distributed_training pattern.

Launch (collectives over jax.distributed):
  python tools/launch.py -n 2 --launcher local \
      python examples/train_dist_kvstore.py

Launch (parameter servers):
  python tools/launch.py -n 2 -s 1 --kv-mode sync --launcher local \
      python examples/train_dist_kvstore.py
"""
import os

import numpy as onp


def main():
    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("MXT_EXAMPLE_PLATFORM", "cpu"))
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd

    kv = mx.kv.create(os.environ.get("MXT_EXAMPLE_KVTYPE", "dist_sync"))
    rank, nworkers = kv.rank, kv.num_workers
    rng = onp.random.RandomState(42)        # same data on every worker
    w_true = rng.randn(8, 1).astype(onp.float32)
    X = rng.randn(256, 8).astype(onp.float32)
    y = X @ w_true

    # reference update_on_kvstore pattern: a server-side optimizer
    # applies each aggregated push to the stored weights
    w = nd.zeros((8, 1))
    kv.init("w", w)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1,
                                      rescale_grad=1.0 / nworkers))
    per = len(X) // nworkers
    shard = slice(rank * per, (rank + 1) * per)
    Xs, ys = X[shard], y[shard]
    for step in range(100):
        kv.pull("w", out=w)
        pred = Xs @ w.asnumpy()
        grad = 2.0 / len(Xs) * Xs.T @ (pred - ys)
        kv.push("w", nd.array(grad))
        kv.barrier()
    kv.pull("w", out=w)
    err = float(onp.abs(w.asnumpy() - w_true).mean())
    print(f"worker {rank}/{nworkers}: |w - w_true| = {err:.4f}")
    assert err < 0.05, "distributed SGD failed to converge"
    print("done")


if __name__ == "__main__":
    main()
