#!/usr/bin/env python
"""Multi-process data-parallel training through the kvstore — the
reference example/distributed_training pattern.

Launch (collectives over jax.distributed):
  python tools/launch.py -n 2 --launcher local \
      python examples/train_dist_kvstore.py

Launch (parameter servers):
  python tools/launch.py -n 2 -s 1 --kv-mode sync --launcher local \
      python examples/train_dist_kvstore.py
"""
import os

import numpy as onp


def main():
    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("MXT_EXAMPLE_PLATFORM", "cpu"))
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd

    kv = mx.kv.create(os.environ.get("MXT_EXAMPLE_KVTYPE", "dist_sync"))
    rank, nworkers = kv.rank, kv.num_workers
    rng = onp.random.RandomState(42)        # same data on every worker
    w_true = rng.randn(8, 1).astype(onp.float32)
    X = rng.randn(256, 8).astype(onp.float32)
    y = X @ w_true

    w = nd.zeros((8, 1))
    kv.init("w", w)
    lr = 0.1
    per = len(X) // nworkers
    shard = slice(rank * per, (rank + 1) * per)
    Xs, ys = X[shard], y[shard]
    for step in range(50):
        kv.pull("w", out=w)
        pred = Xs @ w.asnumpy()
        grad = 2.0 / len(Xs) * Xs.T @ (pred - ys)
        kv.push("w", nd.array(grad * lr))
        kv.barrier()
    kv.pull("w", out=w)
    err = float(onp.abs(w.asnumpy()).mean())
    print(f"worker {rank}/{nworkers}: pulled aggregate, |w|={err:.4f}")
    print("done")


if __name__ == "__main__":
    main()
