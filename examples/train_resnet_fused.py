#!/usr/bin/env python
"""Train ResNet-50 on the Pallas fused-bottleneck path (eager Trainer).

Demonstrates the user-facing API for the NHWC fused configuration the
headline benchmark uses (`BENCH_LAYOUT=NHWC BENCH_FUSED=1`):

    net = vision.resnet50_v1(layout="NHWC", fused=True)

During training each BottleneckV1 runs `_fused_bottleneck_v1[_proj]`
(ops/fused_block.py): 1x1 convs emit their BN batch stats from the
matmul epilogue and apply the previous BN's normalize+ReLU in the
prologue; BN moving stats update through the normal gluon contract.
Inference (no autograd scope) uses the plain layer path.

Synthetic data; on CPU the kernels run in Pallas interpret mode, on a
TPU chip they compile under Mosaic (gated by the smoke manifest unless
MXNET_USE_PALLAS=1).

``--chunk-steps K`` (or ``MXNET_TRAIN_CHUNK_STEPS``) switches from the
eager Trainer to the whole-loop-compiled path: the fused train step
(fuse.py) scanned K steps per XLA dispatch (fuse_loop.py), batches fed
through the dataloader's device-side prefetch ring — one dispatch and
one scalar transfer per K steps instead of K (docs/performance.md
"Chunked training loop").

Usage:
  python examples/train_resnet_fused.py [--batch 8] [--image-size 64]
      [--steps 4] [--cpu] [--chunk-steps K]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--classes", type=int, default=100)
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--chunk-steps", type=int, default=0,
                   help="K > 0: fused step + lax.scan whole-loop "
                        "compilation, one XLA dispatch per K steps; "
                        "0 = eager Trainer (default)")
    args = p.parse_args(argv)

    if args.cpu:
        os.environ.setdefault("MXNET_USE_PALLAS", "1")  # interpret mode
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as onp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, nd
    from incubator_mxnet_tpu.gluon.model_zoo import vision

    mx.random.seed(0)
    net = vision.resnet50_v1(classes=args.classes, layout="NHWC",
                             fused=True)
    net.initialize(ctx=mx.cpu())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = onp.random.RandomState(0)
    x = nd.array(rng.rand(args.batch, args.image_size, args.image_size,
                          3).astype("float32"))
    y = nd.array(rng.randint(0, args.classes, args.batch).astype("int32"))

    extra = {}
    if args.chunk_steps > 0:
        from incubator_mxnet_tpu.fuse import make_fused_train_step
        net(x)                      # materialize deferred param shapes
        step = make_fused_train_step(
            net, loss_fn, "sgd",
            {"learning_rate": 0.01, "momentum": 0.9},
            chunk_steps=args.chunk_steps)
        loop = step.chunked_loop()
        batches = [(x, y)] * args.steps
        t0 = time.perf_counter()
        records = loop.run_epoch(batches)
        losses = [float(r["loss"]) for r in records]  # per-chunk means
        dt = time.perf_counter() - t0
        step.write_back()
        extra = {"chunk_steps": args.chunk_steps,
                 "chunks": loop.chunks_run,
                 "tail_steps": loop.tail_steps_run,
                 "loop_compiles": loop.compile_count}
    else:
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.01, "momentum": 0.9})
        losses = []
        t0 = time.perf_counter()
        for step in range(args.steps):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(args.batch)
            losses.append(float(loss.mean().asnumpy()))
        dt = time.perf_counter() - t0

    assert all(onp.isfinite(l) for l in losses), losses
    # memorizing one fixed batch: training must reach a lower loss than
    # it started at SOME step (tiny-batch BN dynamics are oscillatory,
    # so the last step is not a reliable monotonicity probe)
    if len(losses) > 1:
        assert min(losses[1:]) < losses[0], losses
    print(json.dumps({
        "example": "train_resnet_fused",
        "platform": jax.devices()[0].platform,
        "losses": [round(l, 4) for l in losses],
        "img_per_sec": round(args.batch * args.steps / dt, 2),
        **extra,
    }))
    print("done")


if __name__ == "__main__":
    main()
