"""SSD object detection training (BASELINE.json config 4; reference
example/ssd).

Trains the SSD detector on synthetic box data (or your own via the
detection iterator — see io.ImageDetIter) with the multibox target +
detection pipeline: anchors from MultiBoxPrior, targets from
MultiBoxTarget, NMS'd outputs from MultiBoxDetection.

Usage:
    python examples/train_ssd.py --smoke          # tiny CI run
    python examples/train_ssd.py --steps 500 --batch-size 32
"""
import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--image-size", type=int, default=96)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny synthetic run (CI)")
    args = ap.parse_args()
    if args.smoke:
        import os
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
        args.batch_size, args.steps, args.image_size = 2, 25, 32

    import numpy as onp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, autograd, gluon
    from incubator_mxnet_tpu.models.ssd import SSD, SSDLoss

    mx.random.seed(0)
    net = SSD(num_classes=2, sizes=((0.3, 0.4), (0.6, 0.7)),
              ratios=((1, 2),) * 2, base_channels=8)
    net.initialize(ctx=mx.tpu())
    lossfn = SSDLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    # synthetic scene: one box per image, class = which half it sits in
    rng = onp.random.RandomState(0)
    B, S = args.batch_size, args.image_size
    x = nd.random.uniform(shape=(B, 3, S, S))
    boxes = []
    for i in range(B):
        cls = i % 2
        base = 0.1 if cls == 0 else 0.5
        boxes.append([[cls, base, base, base + 0.35, base + 0.35]])
    labels = nd.array(onp.array(boxes, onp.float32))

    first = last = None
    for step in range(args.steps):
        with autograd.record():
            anchors, cls_preds, box_preds = net(x)
            loc_t, loc_m, cls_t = net.targets(anchors, labels, cls_preds)
            loss = lossfn(cls_preds, box_preds, cls_t, loc_t, loc_m)
        loss.backward()
        trainer.step(B)
        v = float(loss.mean().asnumpy())
        first = first if first is not None else v
        last = v
        if step % 10 == 0:
            print(f"step {step:4d}  loss {v:.4f}", flush=True)

    print(f"loss {first:.4f} -> {last:.4f}")
    det = net.detections(cls_preds, box_preds, anchors).asnumpy()
    kept = det[0][det[0][:, 1] > 0.3]
    print(f"detections on image 0: {len(kept)} above 0.3 confidence")
    print("done")


if __name__ == "__main__":
    main()
