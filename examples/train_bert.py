"""BERT-style masked-LM + NSP pretraining (BASELINE.json config 3;
reference counterpart: gluon-nlp BERT-base pretraining scripts).

Runs the two BERT objectives on synthetic token streams with AMP bf16
(the reference runs fp16 AMP here — bf16 is the TPU-native policy).

Usage:
    python examples/train_bert.py --smoke        # tiny CI run
    python examples/train_bert.py --steps 1000 --units 768 --layers 12
"""
import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=1000)
    ap.add_argument("--units", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--amp", action="store_true", help="bf16 AMP")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        import os
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
        args.batch_size, args.seq_len, args.vocab = 4, 16, 60
        args.units, args.layers, args.heads, args.steps = 32, 2, 2, 30

    import numpy as onp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, autograd, gluon
    from incubator_mxnet_tpu.models.bert import BERTModel

    mx.random.seed(0)
    net = BERTModel(vocab_size=args.vocab, num_layers=args.layers,
                    units=args.units, hidden_size=args.units * 4,
                    num_heads=args.heads, max_length=args.seq_len,
                    dropout=0.0 if args.smoke else 0.1)
    net.initialize(ctx=mx.tpu())
    if args.amp:
        from incubator_mxnet_tpu import amp
        amp.convert_block(net, "bfloat16")
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = onp.random.RandomState(0)
    B, T = args.batch_size, args.seq_len
    tokens = rng.randint(3, args.vocab, (B, T)).astype(onp.int32)
    nsp_labels = (rng.rand(B) > 0.5).astype(onp.int32)
    masked = tokens.copy()
    mask_pos = rng.rand(B, T) < 0.15
    masked[mask_pos] = 0  # [MASK] id
    x = nd.array(masked)
    y_mlm = nd.array(tokens.reshape(-1))
    y_nsp = nd.array(nsp_labels)

    first = last = None
    for step in range(args.steps):
        with autograd.record():
            mlm_logits, nsp_logits = net(x)
            loss = (ce(mlm_logits.reshape(B * T, -1), y_mlm).mean()
                    + ce(nsp_logits, y_nsp).mean())
        loss.backward()
        trainer.step(B)
        v = float(loss.asnumpy())
        first = first if first is not None else v
        last = v
        if step % 10 == 0:
            print(f"step {step:4d}  loss {v:.4f}", flush=True)
    print(f"loss {first:.4f} -> {last:.4f}")
    print("done")


if __name__ == "__main__":
    main()
