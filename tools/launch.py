#!/usr/bin/env python
"""Launch a distributed training job (reference tools/launch.py:29-80 CLI,
dmlc_tracker local launcher semantics).

TPU-native redesign: instead of the ps-lite scheduler + DMLC_* rendezvous,
the local launcher
  * spawns ``-s`` parameter-server processes (kvstore/ps_server.py) when
    servers are requested (dist_async / PS-mode dist_sync), and
  * spawns ``-n`` worker processes with the coordination env that
    ``jax.distributed.initialize`` + DistKVStore consume:
    MXT_COORDINATOR, MXT_NUM_WORKERS, MXT_WORKER_ID (DMLC_* aliases are
    exported too so reference-era scripts keep working).

Examples
--------
  # 2 workers, pure-collective dist_sync (jax.distributed over DCN/ICI)
  python tools/launch.py -n 2 --launcher local python train.py

  # 2 workers + 1 async parameter server
  python tools/launch.py -n 2 -s 1 --kv-mode async --launcher local \
      python train.py
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local(args, extra_env=None):
    """Spawn servers + workers on this host; returns worker exit codes."""
    procs = []
    env_base = dict(os.environ)
    env_base.update(extra_env or {})

    server_ports = []
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for i in range(args.num_servers):
        port = _free_port()
        server_ports.append(port)
        env = dict(env_base)
        env["DMLC_ROLE"] = "server"
        env["JAX_PLATFORMS"] = "cpu"
        # servers are CPU processes (reference: server role never owns a
        # GPU); force the cpu backend BEFORE anything imports jax — the
        # server-side optimizer path uses jnp and must not touch the
        # accelerator plugin
        code = (f"import sys; sys.path.insert(0, {repo_root!r}); "
                f"import jax; jax.config.update('jax_platforms', 'cpu'); "
                f"from incubator_mxnet_tpu.kvstore.ps_server import "
                f"serve_forever; "
                f"serve_forever({port}, {args.kv_mode!r}, {args.num_workers})")
        procs.append(("server", subprocess.Popen(
            [sys.executable, "-c", code], env=env)))

    coordinator = f"127.0.0.1:{_free_port()}"
    workers = []
    for i in range(args.num_workers):
        env = dict(env_base)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_WORKER_ID": str(i),
            "DMLC_NUM_SERVER": str(args.num_servers),
            "MXT_COORDINATOR": coordinator,
            "MXT_NUM_WORKERS": str(args.num_workers),
            "MXT_WORKER_ID": str(i),
            "MXT_SERVERS": ",".join(f"127.0.0.1:{p}" for p in server_ports),
            "MXT_KV_MODE": args.kv_mode,
        })
        for kv in args.env_worker + args.env:
            k, _, v = kv.partition(":")
            env[k] = v
        p = subprocess.Popen(args.command, env=env)
        workers.append(p)
        procs.append(("worker", p))

    codes = [p.wait() for p in workers]
    for role, p in procs:
        if role == "server" and p.poll() is None:
            p.send_signal(signal.SIGTERM)
    return codes


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed job (reference launch.py CLI)")
    parser.add_argument("-n", "--num-workers", required=True, type=int)
    parser.add_argument("-s", "--num-servers", type=int, default=0)
    parser.add_argument("-H", "--hostfile", type=str,
                        help="ssh/mpi launcher host file")
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh", "mpi", "sge", "yarn"])
    parser.add_argument("--kv-mode", type=str, default="sync",
                        choices=["sync", "async"],
                        help="parameter-server mode when -s > 0")
    parser.add_argument("--sync-dst-dir", type=str)
    parser.add_argument("--env-server", action="append", default=[])
    parser.add_argument("--env-worker", action="append", default=[])
    parser.add_argument("--env", action="append", default=[])
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")
    if args.launcher != "local":
        raise NotImplementedError(
            f"launcher {args.launcher!r}: this build targets single-host "
            "multi-process (reference dmlc_tracker local); on TPU pods use "
            "the platform scheduler (GKE/xmanager) to start one process "
            "per host with MXT_COORDINATOR/MXT_NUM_WORKERS/MXT_WORKER_ID")
    codes = launch_local(args)
    bad = [c for c in codes if c != 0]
    sys.exit(bad[0] if bad else 0)


if __name__ == "__main__":
    main()
