#!/usr/bin/env python
"""Launch a distributed training job (reference tools/launch.py:29-80 CLI,
dmlc_tracker local launcher semantics).

TPU-native redesign: instead of the ps-lite scheduler + DMLC_* rendezvous,
the local launcher
  * spawns ``-s`` parameter-server processes (kvstore/ps_server.py) when
    servers are requested (dist_async / PS-mode dist_sync), and
  * spawns ``-n`` worker processes with the coordination env that
    ``jax.distributed.initialize`` + DistKVStore consume:
    MXT_COORDINATOR, MXT_NUM_WORKERS, MXT_WORKER_ID (DMLC_* aliases are
    exported too so reference-era scripts keep working).

Examples
--------
  # 2 workers, pure-collective dist_sync (jax.distributed over DCN/ICI)
  python tools/launch.py -n 2 --launcher local python train.py

  # 2 workers + 1 async parameter server
  python tools/launch.py -n 2 -s 1 --kv-mode async --launcher local \
      python train.py
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _server_code(port, kv_mode, num_workers):
    """Bootstrap string for one PS server process.  Servers are CPU
    processes (reference: server role never owns a GPU); the cpu
    backend is forced BEFORE anything imports jax — the server-side
    optimizer path uses jnp and must not touch the accelerator plugin."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return (f"import sys; sys.path.insert(0, {repo_root!r}); "
            f"import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"from incubator_mxnet_tpu.kvstore.ps_server import "
            f"serve_forever; "
            f"serve_forever({port}, {kv_mode!r}, {num_workers})")


def launch_local(args, extra_env=None):
    """Spawn servers + workers on this host; returns worker exit codes."""
    procs = []
    env_base = dict(os.environ)
    env_base.update(extra_env or {})

    server_ports = []
    for i in range(args.num_servers):
        port = _free_port()
        server_ports.append(port)
        env = dict(env_base)
        env["DMLC_ROLE"] = "server"
        env["JAX_PLATFORMS"] = "cpu"
        code = _server_code(port, args.kv_mode, args.num_workers)
        procs.append(("server", subprocess.Popen(
            [sys.executable, "-c", code], env=env)))

    coordinator = f"127.0.0.1:{_free_port()}"
    server_addrs = [f"127.0.0.1:{p}" for p in server_ports]
    workers = []
    for i in range(args.num_workers):
        env = dict(env_base)
        env.update(_worker_env(args, i, coordinator, server_addrs))
        p = subprocess.Popen(args.command, env=env)
        workers.append(p)
        procs.append(("worker", p))

    codes = [p.wait() for p in workers]
    for role, p in procs:
        if role == "server" and p.poll() is None:
            p.send_signal(signal.SIGTERM)
    return codes


def read_hostfile(path):
    """Reference dmlc hostfile format: one ``host`` (optionally
    ``host:slots`` or ``host slots=N``) per line; # comments."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            slots = 1
            if " slots=" in line:
                host, _, s = line.partition(" slots=")
                slots = int(s)
            elif ":" in line:
                host, _, s = line.partition(":")
                slots = int(s)
            else:
                host = line
            hosts.append((host.strip(), slots))
    if not hosts:
        raise ValueError(f"hostfile {path} is empty")
    return hosts


def _worker_env(args, i, coordinator, server_addrs):
    env = {
        "DMLC_ROLE": "worker",
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_WORKER_ID": str(i),
        "DMLC_NUM_SERVER": str(args.num_servers),
        "MXT_COORDINATOR": coordinator,
        "MXT_NUM_WORKERS": str(args.num_workers),
        "MXT_WORKER_ID": str(i),
        "MXT_SERVERS": ",".join(server_addrs),
        "MXT_KV_MODE": args.kv_mode,
    }
    for kv in args.env_worker + args.env:
        k, _, v = kv.partition(":")
        env[k] = v
    return env


def _assign_hosts(hosts, n):
    """Round-robin n workers over (host, slots) respecting slots first."""
    flat = [h for h, slots in hosts for _ in range(slots)]
    if len(flat) < n:  # oversubscribe round-robin like dmlc ssh tracker
        flat = flat + [hosts[i % len(hosts)][0]
                       for i in range(n - len(flat))]
    return flat[:n]


def _sh_quote(s):
    import shlex
    return shlex.quote(s)


def launch_ssh(args, extra_env=None):
    """Reference dmlc_tracker/ssh.py semantics, TPU-native rendezvous:
    one ssh per worker carrying the coordination env inline (``env K=V
    ... cd DIR && exec CMD``); jax.distributed.initialize on each host
    joins the coordinator on the first host.  PS servers (if any) run on
    the first host.  ``--ssh-cmd`` injects the transport — tests use a
    shim that runs the remote shell locally; production uses real ssh
    with agent/keys (StrictHostKeyChecking left to the user's config).
    """
    hosts = read_hostfile(args.hostfile)
    assignment = _assign_hosts(hosts, args.num_workers)
    head = assignment[0]
    # Ports are probed on the LAUNCHER (a heuristic: free here says
    # nothing certain about the head host).  A remote bind failure is
    # loud — serve_forever raises, ssh exits nonzero, and workers error
    # out connecting — and --port pins the coordinator deterministically
    # for schedulers that pre-allocate ports.
    port = args.port or _free_port()
    coordinator = f"{head}:{port}"
    ssh_cmd = args.ssh_cmd.split()
    workdir = args.sync_dst_dir or os.getcwd()

    procs = []
    server_addrs = []
    for i in range(args.num_servers):
        sport = _free_port()
        server_addrs.append(f"{head}:{sport}")
        code = _server_code(sport, args.kv_mode, args.num_workers)
        # Lifecycle: the server runs in the remote shell's background
        # while `cat` holds the ssh channel open; when the launcher
        # closes the server's stdin pipe (or dies), cat sees EOF and the
        # shell kills the server — SIGTERM on the local ssh client alone
        # would leak the remote process.
        server_sh = (f"env DMLC_ROLE=server JAX_PLATFORMS=cpu "
                     f"{_sh_quote(sys.executable)} -c {_sh_quote(code)} "
                     f"& SRV=$!; cat > /dev/null; kill $SRV 2>/dev/null")
        remote = f"cd {_sh_quote(workdir)} && {{ {server_sh}; }}"
        procs.append(("server", subprocess.Popen(
            ssh_cmd + [head, remote], stdin=subprocess.PIPE)))

    workers = []
    for i, host in enumerate(assignment):
        env = _worker_env(args, i, coordinator, server_addrs)
        env_str = " ".join(f"{k}={_sh_quote(v)}" for k, v in env.items())
        cmd = " ".join(_sh_quote(c) for c in args.command)
        remote = f"cd {_sh_quote(workdir)} && env {env_str} {cmd}"
        p = subprocess.Popen(ssh_cmd + [host, remote])
        workers.append(p)
        procs.append(("worker", p))

    codes = [p.wait() for p in workers]
    for role, p in procs:
        if role == "server":
            if p.stdin:
                p.stdin.close()     # EOF -> remote shell kills the server
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.send_signal(signal.SIGTERM)
    return codes


def launch_mpi(args, extra_env=None):
    """Reference dmlc_tracker/mpi.py role: delegate process placement to
    mpirun.  Rank-dependent vars can't ride ``-x`` (same value
    everywhere), so MXT_WORKER_ID is derived per-rank from the MPI env
    (OMPI_COMM_WORLD_RANK / PMI_RANK / SLURM_PROCID) at package import —
    the launcher exports MXT_WORKER_ID_FROM_MPI=1 to request that."""
    if args.num_servers:
        raise NotImplementedError(
            "--launcher mpi runs collective mode only (mpirun places "
            "workers; there is no MPMD server placement here) — use "
            "--launcher ssh or local for parameter-server mode")
    hosts = read_hostfile(args.hostfile) if args.hostfile else None
    head = hosts[0][0] if hosts else "127.0.0.1"
    port = args.port or _free_port()
    env = {
        "MXT_COORDINATOR": f"{head}:{port}",
        "MXT_NUM_WORKERS": str(args.num_workers),
        "MXT_WORKER_ID_FROM_MPI": "1",
        "MXT_KV_MODE": args.kv_mode,
        "DMLC_ROLE": "worker",
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
    }
    for kv in args.env_worker + args.env:
        k, _, v = kv.partition(":")
        env[k] = v
    cmd = args.mpirun_cmd.split() + ["-np", str(args.num_workers)]
    if args.hostfile:
        cmd += ["--hostfile", args.hostfile]
    for k, v in env.items():
        cmd += ["-x", f"{k}={v}"]
    cmd += args.command
    os_env = dict(os.environ)
    os_env.update(env)
    os_env.update(extra_env or {})
    return [subprocess.call(cmd, env=os_env)]


def launch_sge(args, extra_env=None):
    """Reference dmlc_tracker/sge.py role: workers ride a qsub array
    job (``-t 1-N``, ``-sync y`` so the launcher blocks on completion);
    each task derives MXT_WORKER_ID from $SGE_TASK_ID.  The coordinator
    address points at the submitting host (the reference runs its
    tracker on the submit node the same way) and any parameter servers
    run here as local processes.  ``--qsub-cmd`` injects the transport —
    tests use a shim that executes the array tasks locally."""
    import tempfile

    port = args.port or _free_port()
    head = args.sge_head or socket.gethostname()

    procs = []
    server_addrs = []
    for i in range(args.num_servers):
        sport = _free_port()
        server_addrs.append(f"{head}:{sport}")  # PS genuinely run here
        env = dict(os.environ)
        env.update(extra_env or {})
        env["DMLC_ROLE"] = "server"
        env["JAX_PLATFORMS"] = "cpu"
        code = _server_code(sport, args.kv_mode, args.num_workers)
        procs.append(subprocess.Popen([sys.executable, "-c", code], env=env))

    # The jax.distributed coordinator is HOSTED BY WORKER 0 on whatever
    # exec node SGE places task 1 — unknowable at submit time.  Task 1
    # publishes its host through the shared working directory (#$ -cwd;
    # SGE clusters share it over NFS — the same assumption the reference
    # dmlc_tracker/sge.py makes) and the other tasks poll for it.
    coord_file = f".mxt_sge_coord.{os.getpid()}.{port}"
    # template env from the shared helper; worker id and coordinator
    # host are substituted by the array task itself
    env = _worker_env(args, 0, coordinator="__SGE__", server_addrs=server_addrs)
    env.pop("MXT_WORKER_ID"), env.pop("DMLC_WORKER_ID")
    env.pop("MXT_COORDINATOR")
    env.update(extra_env or {})
    lines = ["#!/bin/bash", f"#$ -t 1-{args.num_workers}", "#$ -cwd",
             'export MXT_WORKER_ID=$((SGE_TASK_ID-1))',
             'export DMLC_WORKER_ID=$MXT_WORKER_ID',
             f'if [ "$SGE_TASK_ID" = "1" ]; then',
             f'  echo "$(hostname):{port}" > {coord_file}.tmp'
             f' && mv {coord_file}.tmp {coord_file}',
             'else',
             f'  for i in $(seq 1 120); do'
             f' [ -f {coord_file} ] && break; sleep 1; done',
             f'  [ -f {coord_file} ] || {{ echo "coordinator file never'
             f' appeared" >&2; exit 1; }}',
             'fi',
             f'export MXT_COORDINATOR="$(cat {coord_file})"']
    for k, v in env.items():
        lines.append(f"export {k}={_sh_quote(v)}")
    lines.append("exec " + " ".join(_sh_quote(c) for c in args.command))
    with tempfile.NamedTemporaryFile("w", suffix=".sh", delete=False) as f:
        f.write("\n".join(lines) + "\n")
        script = f.name
    os.chmod(script, 0o755)
    try:
        rc = subprocess.call(args.qsub_cmd.split()
                             + ["-sync", "y", "-t",
                                f"1-{args.num_workers}", script])
    finally:
        os.unlink(script)
        for leftover in (coord_file, coord_file + ".tmp"):
            try:
                os.unlink(leftover)
            except OSError:
                pass
        for p in procs:            # PS lifetime = the job's lifetime
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    return [rc]


def _rendezvous_server():
    """Tracker-analog service on the submit node (the reference runs its
    dmlc tracker there the same way): atomically assigns worker ids and
    publishes worker 0's coordinator address — container placement under
    YARN is unknowable at submit time and there is no shared cwd to
    rendezvous through (unlike SGE)."""
    import socketserver
    import threading

    state = {"coord": None, "next_id": 0}
    lock = threading.Lock()

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            line = self.rfile.readline().decode("utf-8", "replace").strip()
            if line == "ID":
                with lock:
                    wid = state["next_id"]
                    state["next_id"] += 1
                self.wfile.write(f"{wid}\n".encode())
            elif line.startswith("PUT "):
                with lock:
                    state["coord"] = line[4:].strip()
                self.wfile.write(b"OK\n")
            elif line == "GET":
                with lock:
                    coord = state["coord"] or ""
                self.wfile.write((coord + "\n").encode())

    srv = socketserver.ThreadingTCPServer(("0.0.0.0", 0), Handler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


def launch_yarn(args, extra_env=None):
    """Reference dmlc_tracker/yarn.py role, minimally: submit the
    workers as a YARN distributed-shell application (the reference
    ships a Java ApplicationMaster; this build rides Hadoop's stock
    distributedshell AM instead — ``--yarn-jar`` points at it, e.g.
    $HADOOP_HOME/share/hadoop/yarn/hadoop-yarn-applications-
    distributedshell-*.jar).  The tracker analog (worker-id assignment
    + coordinator discovery) and any parameter servers run on the
    submit node, exactly where the reference runs its tracker; each
    container executes a self-contained bootstrap that dials back.
    ``--yarn-cmd`` injects the transport — tests use a shim that runs
    the containers locally."""
    import tempfile

    if not args.yarn_jar:
        raise SystemExit("--launcher yarn requires --yarn-jar (the "
                         "hadoop distributedshell jar)")
    port = args.port or _free_port()
    head = args.yarn_head or socket.gethostname()

    procs = []
    server_addrs = []
    for _ in range(args.num_servers):
        sport = _free_port()
        server_addrs.append(f"{head}:{sport}")  # PS run on the submit node
        env = dict(os.environ)
        env.update(extra_env or {})
        env["DMLC_ROLE"] = "server"
        env["JAX_PLATFORMS"] = "cpu"
        code = _server_code(sport, args.kv_mode, args.num_workers)
        procs.append(subprocess.Popen([sys.executable, "-c", code], env=env))

    srv, rport = _rendezvous_server()
    env = _worker_env(args, 0, coordinator="__YARN__",
                      server_addrs=server_addrs)
    env.pop("MXT_WORKER_ID"), env.pop("DMLC_WORKER_ID")
    env.pop("MXT_COORDINATOR")
    env.update(extra_env or {})

    rdv = (f"import socket;s=socket.create_connection(({head!r},{rport}),"
           "timeout=30);f=s.makefile()")
    lines = [
        "#!/bin/bash",
        f"wid=$(python3 -c \"{rdv};s.sendall(b'ID\\n');"
        "print(f.readline().strip())\")",
        'export MXT_WORKER_ID=$wid',
        'export DMLC_WORKER_ID=$wid',
        'if [ "$wid" = "0" ]; then',
        f"  python3 -c \"{rdv};"
        f"s.sendall(('PUT '+socket.gethostname()+':{port}\\n')"
        ".encode());f.readline()\"",
        f'  export MXT_COORDINATOR="$(hostname):{port}"',
        'else',
        '  for i in $(seq 1 120); do',
        f"    c=$(python3 -c \"{rdv};s.sendall(b'GET\\n');"
        "print(f.readline().strip())\")",
        '    [ -n "$c" ] && break; sleep 1',
        '  done',
        '  [ -n "$c" ] || { echo "coordinator never appeared" >&2;'
        ' exit 1; }',
        '  export MXT_COORDINATOR="$c"',
        'fi',
    ]
    for k, v in env.items():
        lines.append(f"export {k}={_sh_quote(v)}")
    lines.append("exec " + " ".join(_sh_quote(c) for c in args.command))
    with tempfile.NamedTemporaryFile("w", suffix=".sh", delete=False) as f:
        f.write("\n".join(lines) + "\n")
        script = f.name
    os.chmod(script, 0o755)
    try:
        # the distributedshell client blocks until the app completes
        rc = subprocess.call(
            args.yarn_cmd.split()
            + ["jar", args.yarn_jar, "-jar", args.yarn_jar,
               "-shell_script", script,
               "-num_containers", str(args.num_workers)])
    finally:
        os.unlink(script)
        srv.shutdown()
        srv.server_close()
        for p in procs:            # PS lifetime = the job's lifetime
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    return [rc]


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed job (reference launch.py CLI)")
    parser.add_argument("-n", "--num-workers", required=True, type=int)
    parser.add_argument("-s", "--num-servers", type=int, default=0)
    parser.add_argument("-H", "--hostfile", type=str,
                        help="ssh/mpi launcher host file")
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh", "mpi", "sge", "yarn"])
    parser.add_argument("--kv-mode", type=str, default="sync",
                        choices=["sync", "async"],
                        help="parameter-server mode when -s > 0")
    parser.add_argument("--sync-dst-dir", type=str,
                        help="remote working dir for ssh launcher")
    parser.add_argument("--port", type=int, default=0,
                        help="coordinator port (0 = pick a free one)")
    parser.add_argument("--ssh-cmd", type=str, default="ssh",
                        help="ssh transport (tests inject a local shim)")
    parser.add_argument("--mpirun-cmd", type=str, default="mpirun")
    parser.add_argument("--qsub-cmd", type=str, default="qsub",
                        help="sge submit command (tests inject a shim)")
    parser.add_argument("--sge-head", type=str, default=None,
                        help="coordinator host workers dial back to "
                             "(default: this host's name)")
    parser.add_argument("--yarn-cmd", type=str, default="yarn",
                        help="yarn CLI (tests inject a shim)")
    parser.add_argument("--yarn-jar", type=str, default=None,
                        help="hadoop distributedshell jar path")
    parser.add_argument("--yarn-head", type=str, default=None,
                        help="submit-node host workers dial back to "
                             "(default: this host's name)")
    parser.add_argument("--env-server", action="append", default=[])
    parser.add_argument("--env-worker", action="append", default=[])
    parser.add_argument("--env", action="append", default=[])
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")
    if args.launcher == "local":
        codes = launch_local(args)
    elif args.launcher == "ssh":
        if not args.hostfile:
            parser.error("--launcher ssh requires -H hostfile")
        codes = launch_ssh(args)
    elif args.launcher == "mpi":
        codes = launch_mpi(args)
    elif args.launcher == "sge":
        codes = launch_sge(args)
    else:
        codes = launch_yarn(args)
    bad = [c for c in codes if c != 0]
    sys.exit(bad[0] if bad else 0)


if __name__ == "__main__":
    main()
