#!/usr/bin/env python
"""mxlint CLI — framework-aware static analysis driver.

Usage:
    python tools/mxlint.py [paths...]            # default: the package
    python tools/mxlint.py --json                # machine-readable
    python tools/mxlint.py --write-baseline      # accept current findings
    python tools/mxlint.py --baseline ci/mxlint_baseline.json

Exit status: 0 when no unsuppressed findings, 1 on regressions (or a
bad invocation).  Rule catalog / pragma syntax: docs/static_analysis.md.

The analyzer (``incubator_mxnet_tpu/analysis/mxlint.py``) is pure
stdlib; it is loaded straight from its file here so linting never
imports the framework (and therefore never needs jax installed).
"""
import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYZER = os.path.join(REPO, "incubator_mxnet_tpu", "analysis",
                         "mxlint.py")
DEFAULT_BASELINE = os.path.join(REPO, "ci", "mxlint_baseline.json")


def _load_analyzer():
    spec = importlib.util.spec_from_file_location("_mxlint", _ANALYZER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="mxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="*",
                   default=[os.path.join(REPO, "incubator_mxnet_tpu")],
                   help="files/directories to lint (default: the package)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default: {DEFAULT_BASELINE} "
                        "when it exists)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file "
                        "(each entry needs a reason filled in) and exit 0")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as JSON")
    p.add_argument("--docs", default=None,
                   help="env_vars.md path (default: <repo>/docs/env_vars.md)")
    args = p.parse_args(argv)

    mxlint = _load_analyzer()
    findings = mxlint.lint_paths(args.paths, repo_root=REPO,
                                 docs_path=args.docs)

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)

    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        payload = {"findings": [
            dict(rule=f.rule, file=f.file, message=f.message,
                 reason="TODO: justify or fix") for f in findings]}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
        print(f"[mxlint] wrote {len(findings)} finding(s) to {path}; "
              "fill in each 'reason'")
        return 0

    baseline = (mxlint.load_baseline(baseline_path)
                if baseline_path else {})
    regressions, suppressed, stale = mxlint.apply_baseline(findings,
                                                           baseline)

    if args.as_json:
        print(json.dumps({
            "regressions": [f.as_dict() for f in regressions],
            "suppressed": [f.as_dict() for f in suppressed],
            "stale_baseline": [list(k) for k in stale],
        }, indent=2))
    else:
        if regressions:
            print(mxlint.render(regressions))
        for key in stale:
            print(f"[mxlint] note: stale baseline entry {key} — the "
                  "finding is gone, drop it from the baseline")
        print(f"[mxlint] {len(regressions)} finding(s), "
              f"{len(suppressed)} baselined, {len(stale)} stale "
              f"baseline entr{'y' if len(stale) == 1 else 'ies'}")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
