#!/usr/bin/env python
"""mxlint CLI — framework-aware static analysis driver.

Usage:
    python tools/mxlint.py [paths...]            # default: the package
    python tools/mxlint.py --json                # machine-readable
    python tools/mxlint.py --write-baseline      # accept current findings
    python tools/mxlint.py --baseline ci/mxlint_baseline.json

Exit status: 0 when no unsuppressed findings, 1 on regressions (or a
bad invocation).  Rule catalog / pragma syntax: docs/static_analysis.md.

The analyzer (``incubator_mxnet_tpu/analysis/mxlint.py``) is pure
stdlib; it is loaded straight from its file here so linting never
imports the framework (and therefore never needs jax installed).
"""
import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYZER = os.path.join(REPO, "incubator_mxnet_tpu", "analysis",
                         "mxlint.py")
DEFAULT_BASELINE = os.path.join(REPO, "ci", "mxlint_baseline.json")


def _load_analyzer():
    spec = importlib.util.spec_from_file_location("_mxlint", _ANALYZER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="mxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="*",
                   default=[os.path.join(REPO, "incubator_mxnet_tpu")],
                   help="files/directories to lint (default: the package)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default: {DEFAULT_BASELINE} "
                        "when it exists)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file "
                        "(each entry needs a reason filled in) and exit 0")
    p.add_argument("--prune-stale", action="store_true",
                   help="rewrite the baseline file with its stale "
                        "entries (finding no longer present) removed, "
                        "then report as usual")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as JSON")
    p.add_argument("--docs", default=None,
                   help="env_vars.md path (default: <repo>/docs/env_vars.md)")
    args = p.parse_args(argv)

    mxlint = _load_analyzer()
    findings = mxlint.lint_paths(args.paths, repo_root=REPO,
                                 docs_path=args.docs)

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)

    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        payload = {"findings": [
            dict(rule=f.rule, file=f.file, message=f.message,
                 reason="TODO: justify or fix") for f in findings]}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
        print(f"[mxlint] wrote {len(findings)} finding(s) to {path}; "
              "fill in each 'reason'")
        return 0

    baseline = (mxlint.load_baseline(baseline_path)
                if baseline_path else {})
    regressions, suppressed, stale = mxlint.apply_baseline(findings,
                                                           baseline)

    if args.prune_stale and stale and baseline_path:
        # only entries the scanned paths could have re-produced are
        # prunable — a partial run must not delete the rest of the
        # tree's justified entries
        scanned = [os.path.relpath(os.path.abspath(p), REPO)
                   for p in args.paths]

        def in_scope(key):
            f = key[1]
            return any(f == s or f.startswith(s.rstrip(os.sep) + os.sep)
                       for s in scanned)

        pruned = [k for k in stale if in_scope(k)]
        mxlint.prune_stale_baseline(baseline_path, stale,
                                    in_scope=in_scope)
        print(f"[mxlint] pruned {len(pruned)} stale entr"
              f"{'y' if len(pruned) == 1 else 'ies'} from {baseline_path}"
              + (f" ({len(stale) - len(pruned)} out-of-scope kept)"
                 if len(pruned) != len(stale) else ""))
        stale = [k for k in stale if not in_scope(k)]

    if args.as_json:
        print(json.dumps({
            "regressions": [f.as_dict() for f in regressions],
            "suppressed": [f.as_dict() for f in suppressed],
            "stale_baseline": [list(k) for k in stale],
        }, indent=2))
    else:
        if regressions:
            print(mxlint.render(regressions))
        for key in stale:
            print(f"[mxlint] note: stale baseline entry {key} — the "
                  "finding is gone, drop it from the baseline")
        print(f"[mxlint] {len(regressions)} finding(s), "
              f"{len(suppressed)} baselined, {len(stale)} stale "
              f"baseline entr{'y' if len(stale) == 1 else 'ies'}")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
