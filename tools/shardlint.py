#!/usr/bin/env python
"""shardlint CLI — SPMD sharding lint / collective-cost / per-shard HBM
driver.

Usage:
    python tools/shardlint.py --check       # parallel-stack sweep gate
    python tools/shardlint.py --selftest    # every SL-* rule must fire
    python tools/shardlint.py --seed-violation  # MUST exit nonzero (CI)
    python tools/shardlint.py --zoo resnet18_v1 --batch 8   # dp-mesh sweep
    python tools/shardlint.py --json --output shard.json

``--check`` is the CI gate (docs/graph_analysis.md): it analyzes every
surface of the ``parallel/`` stack (mesh rules, pipeline, ulysses,
ring_attention, moe) plus the kvstore compressed all-reduce on the
8-device CPU dryrun mesh and fails on any error-severity finding — the
zero-finding pin the per-module tests also hold.  ``--selftest`` seeds
one violation per rule (SL-SHARD-PEAK001 / SL-RESHARD001 / SL-REPL001 /
SL-SPEC001 / SL-DONATE001, plus a seeded over-budget shard and a
strict-mode raise) and fails unless each surfaces.  ``--seed-violation``
runs a resharding violation under ``MXNET_GRAPH_SHARDLINT=strict``
enforcement and exits with the resulting failure: CI runs it expecting
a NONZERO exit (the stage's negative control).  ``--zoo`` analyzes a
model-zoo forward under data-parallel batch sharding on the dryrun
mesh.

Findings flow through the shared baseline machinery
(``analysis/findings.py``): ``--write-baseline`` accepts the current
findings into ``ci/shardlint_baseline.json`` (each entry needs a
written reason), ``--baseline`` points elsewhere.  Rule catalog and
cost-model assumptions: docs/graph_analysis.md.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
DEFAULT_BASELINE = os.path.join(REPO, "ci", "shardlint_baseline.json")


def selftest():
    """Seed one violation per rule; each must surface."""
    import warnings

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from incubator_mxnet_tpu import error
    from incubator_mxnet_tpu.analysis import shardlint as sl
    from incubator_mxnet_tpu.parallel.mesh import make_mesh

    failures = []
    mesh = make_mesh(dp=4, tp=2)
    x = jnp.zeros((64, 64), jnp.float32)

    def expect(rule, rep, label):
        if any(f.rule == rule and f.severity == "error"
               for f in rep.findings):
            print(f"[selftest] {rule}: {label} flagged OK")
        else:
            failures.append(f"{rule} did not fire on {label} "
                            f"(got {[f.rule for f in rep.findings]})")

    # SL-SPEC001: declared spec names an axis the mesh does not have
    rep = sl.analyze_fn(lambda a: a + 1.0, x, mesh=mesh,
                        in_specs=(P("zz", None),))
    expect("SL-SPEC001", rep, "a spec naming a missing axis")

    # SL-REPL001: a large fully replicated entry buffer
    rep = sl.analyze_fn(lambda a: a + 1.0, x, mesh=mesh,
                        in_specs=(P(None, None),),
                        config=sl.Config(repl_bytes=1024))
    expect("SL-REPL001", rep, "a large replicated weight")
    rep = sl.analyze_fn(lambda a: a + 1.0, x, mesh=mesh,
                        in_specs=(P(None, None),), allow_replicated=(0,),
                        config=sl.Config(repl_bytes=1024))
    if rep.findings:
        failures.append("allow_replicated did not clear SL-REPL001")
    else:
        print("[selftest] SL-REPL001: allow_replicated escape clean OK")

    # SL-RESHARD001: producer declares dp, consumer constrains to tp
    def reshard(a):
        return jax.lax.with_sharding_constraint(
            a * 2.0, NamedSharding(mesh, P(None, "tp")))

    rep = sl.analyze_fn(reshard, x, mesh=mesh, in_specs=(P("dp", None),))
    expect("SL-RESHARD001", rep, "a mid-graph spec disagreement")
    if rep.comm_bytes_per_step <= 0:
        failures.append("the implied reshard was not priced into "
                        "comm_bytes_per_step")
    else:
        print("[selftest] SL-RESHARD001: reshard priced "
              f"({rep.comm_bytes_per_step} bytes) OK")

    # SL-DONATE001: donated dp-sharded input, output resharded to tp
    def donate_mismatch(a):
        return jax.lax.with_sharding_constraint(
            a + 1.0, NamedSharding(mesh, P(None, "tp")))

    rep = sl.analyze_fn(donate_mismatch, x, mesh=mesh,
                        in_specs=(P("dp", None),), donate_argnums=(0,))
    expect("SL-DONATE001", rep, "a donated input resharded before reuse")

    # SL-SHARD-PEAK001: the seeded over-budget shard — dp-sharding one
    # dim divides the peak by 4, but the budget is below even that
    rep = sl.analyze_fn(lambda a: a @ a, x, mesh=mesh,
                        in_specs=(P("dp", None),),
                        config=sl.Config(chip_bytes=100))
    expect("SL-SHARD-PEAK001", rep, "a seeded over-budget shard")
    if not (0 < rep.peak_hbm_bytes_per_shard < rep.peak_hbm_bytes):
        failures.append(
            "sharding did not shrink the per-shard peak "
            f"({rep.peak_hbm_bytes_per_shard} vs whole-graph "
            f"{rep.peak_hbm_bytes})")
    else:
        print("[selftest] per-shard plan: "
              f"{rep.peak_hbm_bytes_per_shard} < whole-graph "
              f"{rep.peak_hbm_bytes} OK")

    # strict mode raises the typed error through the choke point
    with sl.shard_scope("strict"):
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                sl.check_sharding(reshard, (x,), name="selftest:strict",
                                  mesh=mesh, in_specs=(P("dp", None),))
            failures.append("strict mode did not raise ShardLintError")
        except error.ShardLintError:
            print("[selftest] strict-mode: ShardLintError raised OK")

    for f in failures:
        print(f"[selftest] FAIL {f}")
    print("[selftest] " + ("FAILED" if failures
                           else "all seeded violations caught"))
    return 1 if failures else 0


def zoo_sweep(name, batch, image_size):
    """Analyze one zoo model's inference forward under data-parallel
    batch sharding on the dryrun dp mesh."""
    import jax
    from jax.sharding import PartitionSpec as P

    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.analysis import shardlint as sl
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(dp=jax.device_count())
    net = vision.get_model(name, classes=10)
    net.initialize()
    x = nd.random.uniform(shape=(batch, 3, image_size, image_size))
    net(x)   # materialize deferred-shape parameters
    params, apply_fn = net.functional()

    def fwd(p, xin):
        return apply_fn(p, xin, training=False)

    rep = sl.analyze_fn(
        fwd, params, x.data, mesh=mesh,
        in_specs=(None, P("dp", None, None, None)),
        where=f"zoo:{name}", allow_replicated=(0,))
    return rep


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="shardlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--check", action="store_true",
                   help="gate: the parallel-stack sweep on the 8-device "
                        "dryrun mesh must report zero error findings")
    p.add_argument("--selftest", action="store_true",
                   help="seed one violation per rule; each must surface")
    p.add_argument("--seed-violation", action="store_true",
                   help="run a resharding violation under strict mode: "
                        "exits nonzero when enforcement works (CI runs "
                        "this expecting failure)")
    p.add_argument("--zoo", action="append", default=[],
                   help="model_zoo.vision factory name (repeatable)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default: {DEFAULT_BASELINE} "
                        "when it exists)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current sweep findings to the baseline "
                        "file (each entry needs a reason) and exit 0")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--output", default=None,
                   help="write the record to this file")
    args = p.parse_args(argv)

    if not (args.check or args.selftest or args.seed_violation
            or args.zoo or args.write_baseline):
        p.error("nothing to analyze: pass --check, --selftest, "
                "--seed-violation, --write-baseline and/or --zoo")

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import incubator_mxnet_tpu  # noqa: F401  (registers ops)
    from incubator_mxnet_tpu.analysis import findings as fnd
    from incubator_mxnet_tpu.analysis import shardlint as sl

    if args.seed_violation:
        # negative control: enforcement must FAIL this process
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from incubator_mxnet_tpu import error
        from incubator_mxnet_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(dp=4, tp=2)
        x = jnp.zeros((64, 64), jnp.float32)

        def reshard(a):
            return jax.lax.with_sharding_constraint(
                a * 2.0, NamedSharding(mesh, P(None, "tp")))

        with sl.shard_scope("strict"):
            try:
                sl.check_sharding(reshard, (x,), name="seed-violation",
                                  mesh=mesh, in_specs=(P("dp", None),))
            except error.ShardLintError as e:
                print(f"[shardlint] seeded violation caught: {e}",
                      file=sys.stderr)
                return 1
        print("[shardlint] seeded violation NOT caught — enforcement "
              "is broken", file=sys.stderr)
        return 0   # "success" here means the CI control FAILS the stage

    if args.selftest:
        rc = selftest()
        if rc or not (args.check or args.zoo or args.write_baseline):
            return rc

    reports = []
    if args.check or args.write_baseline:
        reports.extend(sl.sweep_parallel())
    for name in args.zoo:
        reports.append((f"zoo:{name}",
                        zoo_sweep(name, args.batch, args.image_size)))

    all_findings = [f for _, rep in reports for f in rep.findings]
    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)

    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        payload = {"findings": [
            dict(rule=f.rule, file=f"{f.where}{f.path}",
                 message=f.message, reason="TODO: justify or fix")
            for f in all_findings]}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
        print(f"[shardlint] wrote {len(all_findings)} finding(s) to "
              f"{path}; fill in each 'reason'")
        return 0

    baseline = (fnd.load_baseline(baseline_path) if baseline_path else {})
    regressions, suppressed, stale = fnd.apply_baseline(all_findings,
                                                        baseline)
    errors = [f for f in regressions if f.severity == "error"]

    record = {
        "metric": "parallel_stack_comm_bytes_per_step",
        "unit": "bytes",
        "value": sum(rep.comm_bytes_per_step for _, rep in reports),
        "surfaces": {name: {
            "peak_hbm_bytes_per_shard": rep.peak_hbm_bytes_per_shard,
            "peak_hbm_bytes": rep.peak_hbm_bytes,
            "comm_bytes_per_step": rep.comm_bytes_per_step,
            "collectives": len(rep.collectives),
            "mesh_axes": rep.mesh_axes,
            "findings": [f.as_dict() for f in rep.findings],
        } for name, rep in reports},
        "error_findings": len(errors),
        "baselined": len(suppressed),
        "check": args.check,
    }
    out = json.dumps(record, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(out + "\n")
    if args.as_json or not args.output:
        print(out)
    if errors:
        from incubator_mxnet_tpu.analysis.graphlint import render
        print(render(errors), file=sys.stderr)
    for key in stale:
        print(f"[shardlint] note: stale baseline entry {key} — the "
              "finding is gone, drop it from the baseline",
              file=sys.stderr)
    if args.check and errors:
        print(f"[shardlint] GATE: {len(errors)} error finding(s) on "
              "the parallel-stack sweep", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
