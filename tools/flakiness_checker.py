#!/usr/bin/env python
"""Flakiness checker — rerun one test many times with fresh seeds
(reference tools/flakiness_checker.py CLI).

Two repetition strategies:
  * tests decorated with ``test_utils.with_seed`` repeat IN-PROCESS via
    MXNET_TEST_COUNT (cheap: one interpreter, N seeded trials);
  * any other pytest node is re-invoked ``--batches`` times in
    subprocesses, each with a fresh MXNET_TEST_SEED (slower but fully
    general).

Usage:
  python tools/flakiness_checker.py test_operators.test_softmax
  python tools/flakiness_checker.py tests/test_gluon.py::test_dense -n 500
  python tools/flakiness_checker.py <nodeid> --seed 42   # replay one seed
"""
from __future__ import annotations

import argparse
import os
import random
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_NUM_TRIALS = 500


def find_test_path(spec):
    """Accept either a pytest nodeid (tests/test_x.py::test_y) or the
    reference's ``module.test_name`` / ``dir/module.test_name`` form."""
    if "::" in spec or spec.endswith(".py"):
        return spec
    mod, _, name = spec.rpartition(".")
    fname = os.path.basename(mod) + ".py"
    for root, _dirs, files in os.walk(os.path.join(REPO, "tests")):
        if fname in files:
            path = os.path.join(root, fname)
            return f"{path}::{name}" if name else path
    raise FileNotFoundError(f"no test file {fname} under tests/")


def run_trials(nodeid, num_trials, batches, seed, verbosity):
    per_batch = max(num_trials // batches, 1)
    failures = 0
    for b in range(batches):
        env = dict(os.environ)
        env["MXNET_TEST_COUNT"] = str(per_batch)
        if seed is not None:
            env["MXNET_TEST_SEED"] = str(seed)
        else:
            env.pop("MXNET_TEST_SEED", None)
            env["PYTHONHASHSEED"] = str(random.randrange(2**31))
        cmd = [sys.executable, "-m", "pytest", nodeid,
               f"--verbosity={verbosity}", "-x"]
        proc = subprocess.run(cmd, cwd=REPO, env=env,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            failures += 1
            # surface the reproduction banner from with_seed
            for line in proc.stdout.splitlines() + proc.stderr.splitlines():
                if "MXNET_TEST_SEED" in line or "FAILED" in line:
                    print(line, flush=True)
        print(f"batch {b + 1}/{batches} ({per_batch} trials): "
              f"{'FAIL' if proc.returncode else 'ok'}", flush=True)
    return failures


def main(argv=None):
    p = argparse.ArgumentParser(
        description="check a test for flakiness (reference "
                    "tools/flakiness_checker.py)")
    p.add_argument("test", help="pytest nodeid or module.test_name")
    p.add_argument("-n", "--num-trials", type=int,
                   default=DEFAULT_NUM_TRIALS)
    p.add_argument("-b", "--batches", type=int, default=10,
                   help="subprocess batches (fresh interpreter per batch)")
    p.add_argument("-s", "--seed", type=int, default=None,
                   help="pin MXNET_TEST_SEED to replay one failure")
    p.add_argument("-v", "--verbosity", type=int, default=1)
    args = p.parse_args(argv)

    nodeid = find_test_path(args.test)
    print(f"checking {nodeid}: {args.num_trials} trials in "
          f"{args.batches} batches", flush=True)
    failures = run_trials(nodeid, args.num_trials, args.batches,
                          args.seed, args.verbosity)
    if failures:
        print(f"FLAKY: {failures}/{args.batches} batches failed")
        return 1
    print("stable: every batch passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
