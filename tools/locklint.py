#!/usr/bin/env python
"""locklint CLI — whole-program lock-discipline analysis driver.

Usage:
    python tools/locklint.py [paths...]          # default: the package
    python tools/locklint.py --json              # machine-readable
    python tools/locklint.py --selftest          # prove every rule fires
    python tools/locklint.py --write-baseline    # accept current findings

Exit status: 0 when no unsuppressed findings (or selftest passes), 1 on
regressions / a selftest miss.  Rule catalog, the named-lock naming
convention and pragma syntax: docs/static_analysis.md.

The analyzer (``incubator_mxnet_tpu/analysis/locklint.py``) is pure
stdlib; it is loaded straight from its file here so linting never
imports the framework (and therefore never needs jax installed).
``--selftest`` seeds one violation per rule into a temp tree and fails
unless the expected rule id fires on it — including the dynamic half:
it loads ``lockwitness.py`` the same way and requires the witness to
catch a two-thread opposite-order acquisition as a lock-order cycle.
"""
import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYZER = os.path.join(REPO, "incubator_mxnet_tpu", "analysis",
                         "locklint.py")
_WITNESS = os.path.join(REPO, "incubator_mxnet_tpu", "analysis",
                        "lockwitness.py")
DEFAULT_BASELINE = os.path.join(REPO, "ci", "locklint_baseline.json")


def _load_by_file(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# --selftest fixtures: one seeded violation per rule
# ---------------------------------------------------------------------------

_FIX_ORDER_A = '''\
from pkg.locks import named_lock
from pkg.beta import grab_b_then_a

L_A = named_lock("self.test.a")

def grab_a_then_b():
    with L_A:
        grab_b_then_a.__name__   # not the call that closes the cycle
        inner()

def inner():
    from pkg.beta import L_B
    with L_B:
        pass
'''

_FIX_ORDER_B = '''\
from pkg.locks import named_lock

L_B = named_lock("self.test.b")

def grab_b_then_a():
    with L_B:
        take_a()

def take_a():
    from pkg.alpha import L_A
    with L_A:
        pass
'''

_FIX_BLOCKING = '''\
import time
from pkg.locks import named_lock

GATE = named_lock("self.test.gate")

def refresh():
    with GATE:
        time.sleep(0.5)
'''

_FIX_GUARDED = '''\
import threading
from pkg.locks import named_lock

class Pool:
    def __init__(self):
        self._lock = named_lock("self.test.pool")
        self.active = 0

    def spawn(self):
        with self._lock:
            self.active += 1
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def _run(self):
        self.active -= 1
'''

_FIX_LOCKS_STUB = '''\
def named_lock(name):
    import threading
    return threading.Lock()
'''


def _selftest():
    import tempfile
    import threading

    locklint = _load_by_file("_locklint_selftest", _ANALYZER)
    failures = []

    with tempfile.TemporaryDirectory(prefix="locklint_selftest_") as td:
        pkg = os.path.join(td, "pkg")
        os.makedirs(pkg)
        fixtures = {
            "__init__.py": "",
            "locks.py": _FIX_LOCKS_STUB,
            "alpha.py": _FIX_ORDER_A,
            "beta.py": _FIX_ORDER_B,
            "blocking.py": _FIX_BLOCKING,
            "guarded.py": _FIX_GUARDED,
        }
        for name, src in fixtures.items():
            with open(os.path.join(pkg, name), "w",
                      encoding="utf-8") as fh:
                fh.write(src)

        findings = locklint.lint_paths([pkg], repo_root=td)
        fired = {f.rule for f in findings}
        for rule, where in (("MX-LOCK002", "pkg/alpha.py+pkg/beta.py"),
                            ("MX-LOCK003", "pkg/blocking.py"),
                            ("MX-GUARD001", "pkg/guarded.py")):
            if rule in fired:
                hit = next(f for f in findings if f.rule == rule)
                print(f"[locklint] selftest: {rule} fired "
                      f"({hit.file}:{hit.line})")
            else:
                failures.append(f"{rule} did not fire on seeded "
                                f"violation in {where}")

    # dynamic half: the witness must turn a two-thread opposite-order
    # acquisition (temporally non-overlapping — no actual deadlock)
    # into a typed, banked lock-order violation
    witness = _load_by_file("_lockwitness_selftest", _WITNESS)
    witness.set_enabled(True)
    witness.clear()
    a = witness.WitnessLock("selftest.a")
    b = witness.WitnessLock("selftest.b")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    th1 = threading.Thread(target=t1)
    th1.start()
    th1.join()
    th2 = threading.Thread(target=t2)
    th2.start()
    th2.join()

    caught = None
    try:
        witness.check()
    except Exception as exc:  # mxlint: allow-broad-except(selftest must catch whatever check() raises to assert the TYPE is LockOrderError)
        caught = exc
    if caught is None:
        failures.append("witness did not bank a violation for the "
                        "two-thread opposite-order acquisition")
    elif type(caught).__name__ != "LockOrderError":
        failures.append("witness raised "
                        f"{type(caught).__name__}, expected LockOrderError")
    else:
        print("[locklint] selftest: witness cycle detection fired "
              f"(LockOrderError: {caught})")
    witness.clear()
    witness.set_enabled(False)

    if failures:
        for msg in failures:
            print(f"[locklint] SELFTEST FAIL: {msg}")
        return 1
    print("[locklint] selftest: all rules fire (MX-LOCK002, MX-LOCK003, "
          "MX-GUARD001, witness cycle detection)")
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="locklint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="*",
                   default=[os.path.join(REPO, "incubator_mxnet_tpu")],
                   help="files/directories to lint (default: the package)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default: {DEFAULT_BASELINE} "
                        "when it exists)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file "
                        "(each entry needs a reason filled in) and exit 0")
    p.add_argument("--prune-stale", action="store_true",
                   help="rewrite the baseline file with its stale "
                        "entries removed, then report as usual")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as JSON")
    p.add_argument("--selftest", action="store_true",
                   help="seed one violation per rule and require the "
                        "rule id to fire; exit nonzero on any miss")
    args = p.parse_args(argv)

    if args.selftest:
        return _selftest()

    locklint = _load_by_file("_locklint", _ANALYZER)
    findings = locklint.lint_paths(args.paths, repo_root=REPO)

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)

    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        payload = {"findings": [
            dict(rule=f.rule, file=f.file, message=f.message,
                 reason="TODO: justify or fix") for f in findings]}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
        print(f"[locklint] wrote {len(findings)} finding(s) to {path}; "
              "fill in each 'reason'")
        return 0

    baseline = (locklint.load_baseline(baseline_path)
                if baseline_path else {})
    regressions, suppressed, stale = locklint.apply_baseline(findings,
                                                             baseline)

    if args.prune_stale and stale and baseline_path:
        scanned = [os.path.relpath(os.path.abspath(p), REPO)
                   for p in args.paths]

        def in_scope(key):
            f = key[1]
            return any(f == s or f.startswith(s.rstrip(os.sep) + os.sep)
                       for s in scanned)

        pruned = [k for k in stale if in_scope(k)]
        locklint.prune_stale_baseline(baseline_path, stale,
                                      in_scope=in_scope)
        print(f"[locklint] pruned {len(pruned)} stale entr"
              f"{'y' if len(pruned) == 1 else 'ies'} from {baseline_path}")
        stale = [k for k in stale if not in_scope(k)]

    if args.as_json:
        print(json.dumps({
            "regressions": [f.as_dict() for f in regressions],
            "suppressed": [f.as_dict() for f in suppressed],
            "stale_baseline": [list(k) for k in stale],
        }, indent=2))
    else:
        if regressions:
            print(locklint.render(regressions))
        for key in stale:
            print(f"[locklint] note: stale baseline entry {key} — the "
                  "finding is gone, drop it from the baseline")
        print(f"[locklint] {len(regressions)} finding(s), "
              f"{len(suppressed)} baselined, {len(stale)} stale "
              f"baseline entr{'y' if len(stale) == 1 else 'ies'}")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
