#!/usr/bin/env python
"""memlint CLI — liveness-based HBM planner/analyzer driver.

Usage:
    python tools/memlint.py --zoo resnet18_v1 --batch 4   # infer+train sweep
    python tools/memlint.py --zoo resnet18_v1 --check     # CI gate
    python tools/memlint.py --selftest      # seeded violations must surface
    python tools/memlint.py --seed-violation  # MUST exit nonzero (CI control)
    python tools/memlint.py --json --output mem.json

Per ``--zoo`` model the sweep analyzes the INFERENCE forward (the
CachedOp/export surface) and the fused TRAIN step (forward + backward +
optimizer, ``donate_argnums=(0, 1, 2)``), runs one real train step with
``MXNET_GRAPH_MEMLINT`` active so the ``memlint`` profiler provider
records the site, and emits a BENCH-style JSON record with the
per-model peak-HBM estimate, donated-bytes-reclaimed and donation
coverage.

``--check`` is the CI gate (docs/graph_analysis.md): it fails unless
every model's train step donates 100% of its parameter/optimizer-state
buffers (donation coverage 1.0), reclaims a nonzero byte count, reports
zero error-severity findings, and the profiler gauge is nonzero.
``--selftest`` seeds one violation per memlint rule (an UNDONATED train
step must raise under strict mode, an over-budget graph must flag
ML-PEAK001) and fails unless each surfaces — proving the gate would
catch the real thing.  ``--seed-violation`` builds the zoo train step
with donation OFF under strict mode and exits with the resulting
failure: CI runs it expecting a NONZERO exit (the stage's negative
control).
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _tiny_net():
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon import nn
    net = nn.HybridSequential()
    # weights big enough to clear memlint's donate_min_bytes floor
    net.add(nn.Dense(64, in_units=32), nn.Activation("relu"),
            nn.Dense(3, in_units=64))
    net.initialize()
    net(nd.ones((2, 32)))
    return net


def selftest():
    """Seed one violation per rule; each must surface."""
    import warnings

    import jax.numpy as jnp

    from incubator_mxnet_tpu import error, gluon, nd
    from incubator_mxnet_tpu.analysis import memlint as ml
    from incubator_mxnet_tpu.fuse import make_fused_train_step

    failures = []

    # ML-DONATE001 (error severity): an undonated params-in/params-out
    # step at a donating surface
    def step(p, g):
        return p - 0.1 * g

    rep = ml.analyze_fn(step, jnp.ones((2048,)), jnp.ones((2048,)),
                        require_donation=True)
    if any(f.rule == "ML-DONATE001" and f.severity == "error"
           for f in rep.findings):
        print("[selftest] ML-DONATE001: undonated step flagged OK")
    else:
        failures.append("ML-DONATE001 not raised on an undonated step")
    rep_ok = ml.analyze_fn(step, jnp.ones((2048,)), jnp.ones((2048,)),
                           donate_argnums=(0,), require_donation=True)
    if rep_ok.findings:
        failures.append(f"donated step still flagged: {rep_ok.findings}")
    elif rep_ok.donated_reclaimed_bytes != 8192:
        failures.append("donated step reclaimed "
                        f"{rep_ok.donated_reclaimed_bytes}, wanted 8192")
    else:
        print("[selftest] ML-DONATE001: donated step clean OK")

    # ML-PEAK001: budget gate
    rep = ml.analyze_fn(lambda x: (x * 2 + 1).sum(), jnp.ones((4096,)),
                        config=ml.Config(peak_bytes=1024))
    if any(f.rule == "ML-PEAK001" for f in rep.findings):
        print("[selftest] ML-PEAK001: over-budget graph flagged OK")
    else:
        failures.append("ML-PEAK001 not raised over budget")

    # strict mode at the real fused-step surface: an undonated build
    # must raise MemLintError on its first step
    net = _tiny_net()
    fstep = make_fused_train_step(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, donate=False)
    x, y = nd.ones((2, 32)), nd.array([0, 1])
    with ml.mem_scope("strict"):
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                fstep(x, y)
            failures.append("strict fused_step: MemLintError not raised "
                            "for donate=False")
        except error.MemLintError:
            print("[selftest] strict-mode: undonated fused step raised OK")
    # and the donated build passes strict with full coverage
    net2 = _tiny_net()
    fstep2 = make_fused_train_step(
        net2, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1})
    with ml.mem_scope("strict"):
        fstep2(x, y)
    site = ml.stats()["per_site"].get("fused_step:HybridSequential", {})
    if site.get("donation_coverage") != 1.0:
        failures.append(f"donated fused step coverage {site}")
    else:
        print("[selftest] strict-mode: donated fused step clean, "
              "coverage 1.0 OK")

    for f in failures:
        print(f"[selftest] FAIL {f}")
    print("[selftest] " + ("FAILED" if failures
                           else "all seeded violations caught"))
    return 1 if failures else 0


def sweep_model(name, batch, image_size, train_steps=1):
    """Analyze one zoo model: inference forward + fused train step
    (run for real under MXNET_GRAPH_MEMLINT so the profiler provider
    records the site)."""
    from incubator_mxnet_tpu import gluon, nd
    from incubator_mxnet_tpu.analysis import memlint as ml
    from incubator_mxnet_tpu.fuse import make_fused_train_step
    from incubator_mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(name, classes=10)
    net.initialize()
    x = nd.random.uniform(shape=(batch, 3, image_size, image_size))
    y = nd.array([i % 10 for i in range(batch)])
    net(x)   # materialize deferred-shape parameters

    infer = ml.analyze_block(net, x, training=False,
                             where=f"zoo:{name}:infer")

    step = make_fused_train_step(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.1,
                                         "momentum": 0.9})
    with ml.mem_scope("warn"):
        for _ in range(train_steps):
            step(x, y)
    train = ml.stats()["per_site"].get(f"fused_step:{type(net).__name__}")
    if train is None:
        raise RuntimeError("fused-step site was not recorded — the "
                           "memlint choke point did not fire")
    errors = [f for f in infer.findings if f.severity == "error"]
    return {
        "infer": {
            "peak_hbm_bytes": infer.peak_bytes,
            "input_bytes": infer.input_bytes,
            "output_bytes": infer.output_bytes,
            "alias_credit_bytes": infer.alias_credit_bytes,
        },
        "train": dict(train),
        # the fused-step site runs require_donation=True, so its
        # recorded findings are error severity by construction
        "error_findings": len(errors) + int(train.get("findings", 0)),
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="memlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--zoo", action="append", default=[],
                   help="model_zoo.vision factory name (repeatable)")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--check", action="store_true",
                   help="gate: every train step must donate 100%% of "
                        "param/opt-state buffers with zero error "
                        "findings and a nonzero profiler gauge")
    p.add_argument("--selftest", action="store_true",
                   help="seed one violation per rule; each must surface")
    p.add_argument("--seed-violation", action="store_true",
                   help="build the train step UNDONATED under strict "
                        "mode: exits nonzero when enforcement works "
                        "(CI runs this expecting failure)")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--output", default=None,
                   help="write the BENCH-style record to this file")
    args = p.parse_args(argv)

    if not (args.zoo or args.selftest or args.seed_violation):
        p.error("nothing to analyze: pass --zoo, --selftest and/or "
                "--seed-violation")

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import incubator_mxnet_tpu  # noqa: F401  (registers ops)
    from incubator_mxnet_tpu.analysis import memlint as ml

    if args.seed_violation:
        # negative control: enforcement must FAIL this process
        from incubator_mxnet_tpu import error, gluon, nd
        from incubator_mxnet_tpu.fuse import make_fused_train_step
        net = _tiny_net()
        step = make_fused_train_step(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1}, donate=False)
        with ml.mem_scope("strict"):
            try:
                step(nd.ones((2, 32)), nd.array([0, 1]))
            except error.MemLintError as e:
                print(f"[memlint] seeded violation caught: {e}",
                      file=sys.stderr)
                return 1
        print("[memlint] seeded violation NOT caught — enforcement is "
              "broken", file=sys.stderr)
        return 0   # "success" here means the CI control FAILS the stage

    if args.selftest:
        rc = selftest()
        if rc or not args.zoo:
            return rc

    models = {}
    problems = []
    for name in args.zoo:
        models[name] = sweep_model(name, args.batch, args.image_size)
        t = models[name]["train"]
        if models[name]["error_findings"]:
            problems.append(f"{name}: error-severity findings")
        if t.get("donation_coverage") != 1.0:
            problems.append(f"{name}: train donation coverage "
                            f"{t.get('donation_coverage')} != 1.0")
        if not t.get("donated_bytes_reclaimed"):
            problems.append(f"{name}: donated_bytes_reclaimed is zero")

    gauge = ml.stats()["donated_bytes_reclaimed"]
    record = {
        "metric": "zoo_peak_hbm_bytes",
        "unit": "bytes",
        "value": max((m["train"].get("peak_hbm_bytes", 0)
                      for m in models.values()), default=0),
        "models": models,
        "profiler_donated_bytes_reclaimed": gauge,
        "check": args.check,
        "problems": problems,
    }
    if args.check and not gauge:
        problems.append("profiler memlint gauge donated_bytes_reclaimed "
                        "is zero")

    out = json.dumps(record, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(out + "\n")
    if args.as_json or not args.output:
        print(out)
    for prob in problems:
        print(f"[memlint] GATE: {prob}", file=sys.stderr)
    if args.check and problems:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
