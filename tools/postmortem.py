#!/usr/bin/env python
"""postmortem: reconstruct one incident from N processes' black boxes.

Every process keeps an always-on flight-recorder ring
(``incubator_mxnet_tpu/flightrec.py``): control-plane events — replica
state transitions, quarantines, scaling decisions, evictions,
membership changes, compile storms, fault injections — dumped on typed
boundary errors, on ``SIGUSR2``, or served live at ``GET /v1/flight``.
This tool merges any number of those dumps (files or URLs), plus
optional request-trace dumps (``GET /v1/trace`` Chrome trace-event
JSON, auto-detected), into ONE causal timeline ordered by the shared
wall-clock anchors, then answers "what happened":

* default      — the merged timeline, one line per event/span;
* ``--incident X`` — narrow to the relevant window: ``X`` is a trace
  id (keep that trace's window), any field value such as a replica id
  (keep the window around events mentioning it), or an explicit
  ``t0..t1`` wall-seconds range;
* ``--report`` — a structured diagnosis: the terminal (last error)
  event, the last N events per category leading up to it, correlated
  fault injections, and compile storms in the window;
* ``--gate a,b,c`` — CI assertion: the named events must appear as an
  ordered subsequence of the merged timeline (exit 1 otherwise) — "the
  dump must contain the injected fault and the quarantine that
  followed", made checkable.

Stdlib-only and jax-free (usable on a laptop against a dead fleet's
dump directory).  Clock skew between hosts shows up as offset, never
as reordering within a process — same contract as traceview.

Usage::

    python tools/postmortem.py dumps/*.flight.json
    python tools/postmortem.py router-123.flight.json \
        http://replica0:P0/v1/flight http://replica1:P1/v1/flight \
        --incident r0 --report
    python tools/postmortem.py dumps/* --gate \
        fault.serving.replica_exec,router.hop_failed,replica.quarantined
"""
from __future__ import annotations

import argparse
import json
import sys


def load(source):
    """One dump — file path or http(s) URL — as a parsed payload."""
    if source.startswith(("http://", "https://")):
        import urllib.request
        with urllib.request.urlopen(source, timeout=30) as resp:
            return json.loads(resp.read())
    with open(source) as f:
        return json.load(f)


def normalize(payload, source):
    """One payload → a list of uniform records::

        {ts, proc, kind, category, name, severity, fields, trace_id,
         dur_us}

    ``ts`` is wall microseconds (both dump kinds export via their
    process's single wall anchor, so records from different processes
    interleave correctly).  Flight dumps carry ``"flight": 1``; trace
    dumps carry ``"traceEvents"``; anything else is rejected loudly —
    a silently-skipped dump would read as "nothing happened there".
    """
    records = []
    if isinstance(payload, dict) and payload.get("flight"):
        proc = f"{payload.get('proc', '?')}-{payload.get('pid', '?')}"
        for e in payload.get("events", []):
            records.append({
                "ts": int(e.get("ts_us", 0)),
                "proc": proc,
                "kind": "flight",
                "category": e.get("category", "?"),
                "name": e.get("name", "?"),
                "severity": e.get("severity", "info"),
                "fields": e.get("fields") or {},
                "trace_id": e.get("trace_id"),
                "dur_us": None,
            })
        return records
    if isinstance(payload, dict) and "traceEvents" in payload:
        for e in payload["traceEvents"]:
            args = e.get("args") or {}
            outcome = args.get("outcome", "ok")
            records.append({
                "ts": int(e.get("ts", 0)),
                "proc": str(args.get("service", "?")),
                "kind": "span" if e.get("ph") == "X" else "span_event",
                "category": "trace",
                "name": e.get("name", "?"),
                "severity": ("info" if outcome in ("ok", None)
                             else "error"),
                "fields": {k: v for k, v in args.items()
                           if k not in ("trace_id", "span_id",
                                        "parent_id", "service")
                           and v is not None},
                "trace_id": args.get("trace_id"),
                "dur_us": e.get("dur") if e.get("ph") == "X" else None,
            })
        return records
    raise ValueError(
        f"{source}: neither a flight dump ('flight': 1) nor a trace "
        "dump ('traceEvents') — refusing to silently skip it")


def merge(sources):
    records = []
    for src in sources:
        records.extend(normalize(load(src), src))
    records.sort(key=lambda r: (r["ts"], r["proc"]))
    return records


# ---------------------------------------------------------------------------
# incident narrowing
# ---------------------------------------------------------------------------

def _mentions(r, needle):
    if r["trace_id"] == needle or r["name"] == needle:
        return True
    return any(str(v) == needle for v in r["fields"].values())


def narrow(records, incident, pad_s=0.5):
    """Keep the records relevant to ``incident``:

    * ``t0..t1``  — explicit wall-seconds window;
    * a trace id / replica id / any field value — the window spanned
      by the records that mention it, padded by ``pad_s`` either side
      (context from OTHER processes inside the window is kept — that
      is the point of a cross-process reconstruction).
    """
    if ".." in incident:
        lo_s, _, hi_s = incident.partition("..")
        try:
            lo, hi = float(lo_s) * 1e6, float(hi_s) * 1e6
        except ValueError:
            raise SystemExit(
                f"--incident {incident!r}: t0..t1 must be wall "
                "seconds (floats)")
        return [r for r in records if lo <= r["ts"] <= hi]
    hits = [r for r in records if _mentions(r, incident)]
    if not hits:
        return []
    lo = min(r["ts"] for r in hits) - int(pad_s * 1e6)
    hi = max(r["ts"] for r in hits) + int(pad_s * 1e6)
    return [r for r in records if lo <= r["ts"] <= hi]


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

_FIELD_SKIP = {"outcome"}


def _fmt_fields(fields):
    keep = {k: v for k, v in fields.items()
            if k not in _FIELD_SKIP and v is not None}
    if not keep:
        return ""
    return " " + " ".join(f"{k}={v}" for k, v in sorted(keep.items()))


def render(records, out=sys.stdout):
    if not records:
        print("no records", file=out)
        return
    t0 = records[0]["ts"]
    procs = sorted({r["proc"] for r in records})
    print(f"{len(records)} record(s) across {len(procs)} process(es): "
          f"{', '.join(procs)}", file=out)
    for r in records:
        off_ms = (r["ts"] - t0) / 1000.0
        dur = (f" ({r['dur_us'] / 1000.0:.3f}ms)"
               if r["dur_us"] else "")
        sev = {"info": " ", "warn": "!", "error": "E"}[r["severity"]]
        tid = f" ~{r['trace_id'][:8]}" if r["trace_id"] else ""
        print(f"  +{off_ms:10.3f}ms {sev} [{r['proc']:>14s}] "
              f"{r['category']:>10s}  {r['name']}{dur}"
              f"{_fmt_fields(r['fields'])}{tid}", file=out)


# ---------------------------------------------------------------------------
# --report: structured diagnosis
# ---------------------------------------------------------------------------

def diagnose(records, last_n=5):
    """The postmortem narrative as data: terminal event, the lead-up
    per category, correlated fault injections, compile storms."""
    if not records:
        return {"terminal": None, "lead_up": {}, "faults": [],
                "compile_storms": [], "errors": 0}
    errors = [r for r in records if r["severity"] == "error"]
    terminal = errors[-1] if errors else records[-1]
    before = [r for r in records if r["ts"] <= terminal["ts"]]
    lead_up = {}
    for r in before:
        lead_up.setdefault(r["category"], []).append(r)
    lead_up = {cat: rs[-last_n:] for cat, rs in sorted(lead_up.items())}
    return {
        "terminal": terminal,
        "lead_up": lead_up,
        "faults": [r for r in records
                   if r["category"] == "fault"
                   or r["name"].startswith("fault.")],
        "compile_storms": [r for r in records
                           if r["name"] == "compile.storm"],
        "errors": len(errors),
    }


def _line(r):
    return (f"{r['ts'] / 1e6:.6f}s [{r['proc']}] {r['category']}:"
            f"{r['name']}{_fmt_fields(r['fields'])}")


def print_report(diag, out=sys.stdout):
    t = diag["terminal"]
    print("== postmortem report ==", file=out)
    if t is None:
        print("no records — nothing to diagnose", file=out)
        return
    print(f"terminal event ({diag['errors']} error(s) total):",
          file=out)
    print(f"  {_line(t)}  [{t['severity']}]", file=out)
    print(f"\nlead-up (last events per category before the terminal "
          f"event):", file=out)
    for cat, rs in diag["lead_up"].items():
        print(f"  [{cat}]", file=out)
        for r in rs:
            mark = {"info": "", "warn": "  !", "error": "  !!"}[
                r["severity"]]
            print(f"    {_line(r)}{mark}", file=out)
    if diag["faults"]:
        print(f"\ncorrelated fault injections "
              f"({len(diag['faults'])}):", file=out)
        for r in diag["faults"][-10:]:
            print(f"  {_line(r)}", file=out)
    if diag["compile_storms"]:
        print(f"\ncompile storms in the window "
              f"({len(diag['compile_storms'])}):", file=out)
        for r in diag["compile_storms"][-10:]:
            print(f"  {_line(r)}", file=out)


# ---------------------------------------------------------------------------
# --gate: CI assertion
# ---------------------------------------------------------------------------

def gate(records, names):
    """The named events must appear as an ordered subsequence of the
    merged timeline.  Returns (ok, detail)."""
    want = list(names)
    i = 0
    matched = []
    for r in records:
        if i < len(want) and r["name"] == want[i]:
            matched.append((want[i], r["ts"], r["proc"]))
            i += 1
    if i == len(want):
        return True, matched
    present = {r["name"] for r in records}
    missing = want[i]
    hint = ("present somewhere but out of order"
            if missing in present else "absent from every dump")
    return False, (f"gate failed at step {i + 1}/{len(want)}: "
                   f"{missing!r} {hint}; matched so far: "
                   f"{[m[0] for m in matched]}")


def main(argv=None):
    p = argparse.ArgumentParser(
        description="merge + reconstruct mxnet-tpu flight dumps")
    p.add_argument("sources", nargs="+",
                   help="flight/trace dumps: files or /v1/flight "
                        "(/v1/trace) URLs")
    p.add_argument("--incident", default=None, metavar="X",
                   help="narrow to a trace id, a replica/field value, "
                        "or an explicit t0..t1 wall-seconds window")
    p.add_argument("--pad", type=float, default=0.5,
                   help="context window padding (s) around an "
                        "incident match")
    p.add_argument("--report", action="store_true",
                   help="structured diagnosis instead of the raw "
                        "timeline")
    p.add_argument("--last", type=int, default=5, metavar="N",
                   help="--report: lead-up events kept per category")
    p.add_argument("--gate", default=None, metavar="EV1,EV2,...",
                   help="exit 1 unless the named events appear in "
                        "this order in the merged timeline")
    p.add_argument("--json", default=None, metavar="OUT",
                   help="also write the merged records (and the "
                        "report, with --report) as JSON")
    args = p.parse_args(argv)

    records = merge(args.sources)
    if args.incident:
        records = narrow(records, args.incident, pad_s=args.pad)
        if not records:
            print(f"incident {args.incident!r} matched nothing in "
                  f"{len(args.sources)} dump(s)", file=sys.stderr)
            return 1

    payload = {"records": records}
    if args.report:
        diag = diagnose(records, last_n=args.last)
        print_report(diag)
        payload["report"] = diag
    else:
        render(records)

    rc = 0
    if args.gate:
        names = [n for n in args.gate.split(",") if n]
        ok, detail = gate(records, names)
        if ok:
            print(f"gate ok: {' -> '.join(n for n, _t, _p in detail)}")
        else:
            print(f"GATE FAIL: {detail}", file=sys.stderr)
            rc = 1
        payload["gate"] = {"names": names, "ok": ok}

    if args.json:
        with open(args.json, "w") as f:
            json.dump(payload, f)
    return rc


if __name__ == "__main__":
    sys.exit(main())
