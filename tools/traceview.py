#!/usr/bin/env python
"""traceview: merge per-process trace dumps into one request timeline.

Each serving process (router, every replica) exposes its own span ring
as Chrome trace-event JSON at ``GET /v1/trace`` (``incubator_mxnet_tpu/
trace.py``).  One request's spans are therefore scattered across
several processes; this tool merges any number of dumps — files or
``http://`` URLs — keys them by trace id, and renders one indented
timeline per trace: offsets, durations, typed outcomes, and instant
events (fault injections, hedge launches, cache hits) in tree order.

Stdlib-only and jax-free (usable on a laptop against a remote fleet's
dumps).  The merged view works because every process exports span
times on a shared wall-anchored timeline (one anchor per process);
clock skew between hosts shows up as offset, never as reordering
within a process.

Usage::

    python tools/traceview.py router.json replica0.json replica1.json
    python tools/traceview.py http://127.0.0.1:8080/v1/trace \
        --trace 3f2a...  --coverage
    python tools/traceview.py dumps/*.json --json merged.json
    python tools/traceview.py --stats profile.json   # provider stats
                                                     # from
                                                     # profiler.dumps(
                                                     #   format="json")

``--coverage`` prints, per trace, the fraction of the root span's wall
time covered by the union of its descendant spans — the "no dark
latency" number the trace CI gate enforces (a request whose spans
account for < 95% of its wall time has an uninstrumented stage).
"""
from __future__ import annotations

import argparse
import json
import sys


def load_events(source):
    """One dump — a file path or an http(s) URL — → its traceEvents."""
    if source.startswith(("http://", "https://")):
        import urllib.request
        with urllib.request.urlopen(source, timeout=30) as resp:
            payload = json.loads(resp.read())
    else:
        with open(source) as f:
            payload = json.load(f)
    if isinstance(payload, dict):
        return list(payload.get("traceEvents", []))
    return list(payload)   # a bare event list is accepted too


def merge(sources):
    events = []
    for src in sources:
        events.extend(load_events(src))
    events.sort(key=lambda e: e.get("ts", 0))
    return events


def by_trace(events):
    """{trace_id: [events]} — events without a trace_id are dropped
    (other profiler output may share a dump file)."""
    out = {}
    for e in events:
        tid = (e.get("args") or {}).get("trace_id")
        if tid:
            out.setdefault(tid, []).append(e)
    return out


def _spans_and_instants(events):
    spans = [e for e in events if e.get("ph") == "X"]
    instants = {}
    for e in events:
        if e.get("ph") == "i":
            sid = (e.get("args") or {}).get("span_id")
            instants.setdefault(sid, []).append(e)
    return spans, instants


def _roots_and_children(spans):
    ids = {(s["args"].get("span_id")) for s in spans}
    children = {}
    roots = []
    for s in spans:
        parent = s["args"].get("parent_id")
        if parent in ids:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    for sibs in children.values():
        sibs.sort(key=lambda e: e["ts"])
    roots.sort(key=lambda e: e["ts"])
    return roots, children


_ARG_SKIP = {"trace_id", "span_id", "parent_id", "service", "outcome"}


def _fmt_args(args):
    keep = {k: v for k, v in args.items()
            if k not in _ARG_SKIP and v is not None}
    if not keep:
        return ""
    return " " + " ".join(f"{k}={v}" for k, v in sorted(keep.items()))


def render(trace_id, events, out=sys.stdout):
    """One indented timeline for one trace, all processes merged."""
    spans, instants = _spans_and_instants(events)
    if not spans:
        print(f"trace {trace_id}: no spans", file=out)
        return
    roots, children = _roots_and_children(spans)
    t0 = min(s["ts"] for s in spans)
    print(f"trace {trace_id} "
          f"({len(spans)} span(s), "
          f"{len({s['args'].get('service') for s in spans})} "
          f"process(es))", file=out)

    def walk(s, depth):
        off_ms = (s["ts"] - t0) / 1000.0
        dur_ms = s.get("dur", 0) / 1000.0
        outcome = s["args"].get("outcome", "ok")
        svc = s["args"].get("service", "?")
        mark = "" if outcome == "ok" else f"  !! {outcome}"
        print(f"  {'  ' * depth}+{off_ms:9.3f}ms "
              f"{dur_ms:9.3f}ms  {s['name']}  [{svc}]"
              f"{_fmt_args(s['args'])}{mark}", file=out)
        for ev in instants.get(s["args"].get("span_id"), []):
            ev_off = (ev["ts"] - t0) / 1000.0
            print(f"  {'  ' * (depth + 1)}@{ev_off:9.3f}ms "
                  f"           * {ev['name']}"
                  f"{_fmt_args(ev.get('args') or {})}", file=out)
        for c in children.get(s["args"].get("span_id"), []):
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)


def coverage(events):
    """Fraction of the (longest) root span's duration covered by the
    union of its descendant spans — "no dark latency" when close to
    1.  Descendants are clipped to the root's interval; gaps between
    them are exactly the unattributed time."""
    spans, _ = _spans_and_instants(events)
    if not spans:
        return 0.0
    roots, children = _roots_and_children(spans)
    root = max(roots, key=lambda s: s.get("dur", 0))
    r0, r1 = root["ts"], root["ts"] + root.get("dur", 0)
    if r1 <= r0:
        return 0.0
    intervals = []

    def collect(span_id):
        for c in children.get(span_id, []):
            a = max(r0, c["ts"])
            b = min(r1, c["ts"] + c.get("dur", 0))
            if b > a:
                intervals.append((a, b))
            collect(c["args"].get("span_id"))

    collect(root["args"].get("span_id"))
    intervals.sort()
    covered = 0
    cur_a = cur_b = None
    for a, b in intervals:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                covered += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        covered += cur_b - cur_a
    return covered / (r1 - r0)


def show_stats(path, out=sys.stdout):
    """Pretty-print the provider sections of a machine-readable
    ``profiler.dumps(format="json")`` dump (the trace provider first
    — this tool's natural companion)."""
    with open(path) as f:
        payload = json.load(f)
    providers = payload.get("providers", payload)
    order = sorted(providers,
                   key=lambda name: (name != "trace", name))
    for name in order:
        print(f"[{name}]", file=out)
        stats = providers[name]
        if isinstance(stats, dict):
            for k, v in sorted(stats.items()):
                print(f"  {k} = {v}", file=out)
        else:
            print(f"  {stats}", file=out)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="merge + render mxnet-tpu trace dumps")
    p.add_argument("sources", nargs="*",
                   help="trace dumps: files or /v1/trace URLs")
    p.add_argument("--trace", default=None, metavar="ID",
                   help="render only this trace id")
    p.add_argument("--json", default=None, metavar="OUT",
                   help="also write the merged Chrome trace JSON")
    p.add_argument("--coverage", action="store_true",
                   help="print per-trace root-span coverage fraction")
    p.add_argument("--min-coverage", type=float, default=None,
                   metavar="F", help="exit 1 if any rendered trace "
                   "covers less than F of its root span (CI gate)")
    p.add_argument("--stats", default=None, metavar="FILE",
                   help="pretty-print a profiler.dumps(format='json') "
                        "file instead of rendering traces")
    args = p.parse_args(argv)

    if args.stats:
        show_stats(args.stats)
        return 0
    if not args.sources:
        p.error("need at least one dump file/URL (or --stats)")
    events = merge(args.sources)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
    traces = by_trace(events)
    if args.trace:
        traces = {tid: evs for tid, evs in traces.items()
                  if tid == args.trace}
        if not traces:
            print(f"trace {args.trace!r} not found "
                  f"({len(by_trace(events))} trace(s) in the dumps)",
                  file=sys.stderr)
            return 1
    failed = False
    for tid in sorted(traces):
        render(tid, traces[tid])
        if args.coverage or args.min_coverage is not None:
            cov = coverage(traces[tid])
            print(f"  coverage: {cov:.1%} of root span accounted")
            if args.min_coverage is not None \
                    and cov < args.min_coverage:
                print(f"  FAIL: below --min-coverage "
                      f"{args.min_coverage:.0%}", file=sys.stderr)
                failed = True
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
