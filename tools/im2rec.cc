// im2rec: pack an image list into a RecordIO shard (TPU-native framework's
// counterpart of the reference tools/im2rec.cc — same .lst and .rec
// formats, libjpeg instead of OpenCV for the optional resize re-encode).
//
// Usage: im2rec <prefix.lst> <image_root> <output.rec> [resize=0] [quality=95]
//   .lst line: <index>\t<label...>\t<relative/path>
// With resize>0 the shorter side is scaled to `resize` and the image is
// re-encoded as JPEG quality `quality`; otherwise bytes pass through.
#include <cstddef>
#include <cstdio>

#include <jpeglib.h>

#include <algorithm>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <iostream>
#include <sstream>
#include <string>
#include <vector>

static const uint32_t kMagic = 0xced7230a;

struct JpegErr {
  jpeg_error_mgr pub;
  std::jmp_buf jmp;
};
static void ErrExit(j_common_ptr c) {
  std::longjmp(reinterpret_cast<JpegErr*>(c->err)->jmp, 1);
}

static bool Decode(const std::vector<unsigned char>& in,
                   std::vector<unsigned char>* out, int* h, int* w) {
  jpeg_decompress_struct ci;
  JpegErr je;
  ci.err = jpeg_std_error(&je.pub);
  je.pub.error_exit = ErrExit;
  if (setjmp(je.jmp)) {
    jpeg_destroy_decompress(&ci);
    return false;
  }
  jpeg_create_decompress(&ci);
  jpeg_mem_src(&ci, const_cast<unsigned char*>(in.data()),
               static_cast<unsigned long>(in.size()));
  jpeg_read_header(&ci, TRUE);
  ci.out_color_space = JCS_RGB;
  jpeg_start_decompress(&ci);
  *w = ci.output_width;
  *h = ci.output_height;
  out->resize(static_cast<size_t>(*w) * *h * 3);
  size_t stride = static_cast<size_t>(*w) * 3;
  while (ci.output_scanline < ci.output_height) {
    unsigned char* row = out->data() + ci.output_scanline * stride;
    jpeg_read_scanlines(&ci, &row, 1);
  }
  jpeg_finish_decompress(&ci);
  jpeg_destroy_decompress(&ci);
  return true;
}

static void Encode(const std::vector<unsigned char>& rgb, int h, int w,
                   int quality, std::vector<unsigned char>* out) {
  jpeg_compress_struct ci;
  jpeg_error_mgr jerr;
  ci.err = jpeg_std_error(&jerr);
  jpeg_create_compress(&ci);
  unsigned char* mem = nullptr;
  unsigned long mem_size = 0;
  jpeg_mem_dest(&ci, &mem, &mem_size);
  ci.image_width = w;
  ci.image_height = h;
  ci.input_components = 3;
  ci.in_color_space = JCS_RGB;
  jpeg_set_defaults(&ci);
  jpeg_set_quality(&ci, quality, TRUE);
  jpeg_start_compress(&ci, TRUE);
  size_t stride = static_cast<size_t>(w) * 3;
  while (ci.next_scanline < ci.image_height) {
    const unsigned char* row = rgb.data() + ci.next_scanline * stride;
    unsigned char* rows[1] = {const_cast<unsigned char*>(row)};
    jpeg_write_scanlines(&ci, rows, 1);
  }
  jpeg_finish_compress(&ci);
  out->assign(mem, mem + mem_size);
  jpeg_destroy_compress(&ci);
  free(mem);
}

static void Resize(const std::vector<unsigned char>& src, int sh, int sw,
                   std::vector<unsigned char>* dst, int dh, int dw) {
  dst->resize(static_cast<size_t>(dh) * dw * 3);
  float ys = dh > 1 ? static_cast<float>(sh - 1) / (dh - 1) : 0.f;
  float xs = dw > 1 ? static_cast<float>(sw - 1) / (dw - 1) : 0.f;
  for (int y = 0; y < dh; ++y) {
    float fy = y * ys;
    int y0 = static_cast<int>(fy), y1 = std::min(y0 + 1, sh - 1);
    float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = x * xs;
      int x0 = static_cast<int>(fx), x1 = std::min(x0 + 1, sw - 1);
      float wx = fx - x0;
      for (int c = 0; c < 3; ++c) {
        float v = src[(y0 * sw + x0) * 3 + c] * (1 - wy) * (1 - wx) +
                  src[(y0 * sw + x1) * 3 + c] * (1 - wy) * wx +
                  src[(y1 * sw + x0) * 3 + c] * wy * (1 - wx) +
                  src[(y1 * sw + x1) * 3 + c] * wy * wx;
        (*dst)[(y * dw + x) * 3 + c] = static_cast<unsigned char>(v + 0.5f);
      }
    }
  }
}

static void WriteRecord(std::FILE* fp, const std::vector<unsigned char>& rec) {
  // Single-chunk write; payloads containing the magic are split like
  // dmlc recordio so readers can resync.
  std::vector<size_t> splits;
  for (size_t i = 0; i + 4 <= rec.size(); i += 4) {
    uint32_t word;
    std::memcpy(&word, rec.data() + i, 4);
    if (word == kMagic) splits.push_back(i);
  }
  auto emit = [&](uint32_t cflag, const unsigned char* buf, size_t n) {
    uint32_t header[2] = {kMagic,
                          (cflag << 29u) | (static_cast<uint32_t>(n) &
                                            ((1u << 29u) - 1u))};
    static const char zeros[4] = {0, 0, 0, 0};
    size_t pad = (4 - (n & 3)) & 3;
    if (std::fwrite(header, 4, 2, fp) != 2 ||
        (n && std::fwrite(buf, 1, n, fp) != n) ||
        (pad && std::fwrite(zeros, 1, pad, fp) != pad)) {
      std::cerr << "FATAL: short write to output shard (disk full?)\n";
      std::exit(2);
    }
  };
  if (splits.empty()) {
    emit(0, rec.data(), rec.size());
    return;
  }
  size_t begin = 0;
  for (size_t k = 0; k <= splits.size(); ++k) {
    size_t end = k < splits.size() ? splits[k] : rec.size();
    uint32_t cflag = k == 0 ? 1u : (k == splits.size() ? 3u : 2u);
    emit(cflag, rec.data() + begin, end - begin);
    begin = end + (k < splits.size() ? 4 : 0);
  }
}

int main(int argc, char** argv) {
  if (argc < 4) {
    std::cerr << "usage: im2rec <list.lst> <image_root> <out.rec> "
                 "[resize=0] [quality=95]\n";
    return 1;
  }
  std::string lst = argv[1], root = argv[2], out = argv[3];
  int resize = argc > 4 ? std::atoi(argv[4]) : 0;
  int quality = argc > 5 ? std::atoi(argv[5]) : 95;

  std::ifstream fin(lst);
  if (!fin) {
    std::cerr << "cannot open " << lst << "\n";
    return 1;
  }
  std::FILE* frec = std::fopen(out.c_str(), "wb");
  if (!frec) {
    std::cerr << "cannot open " << out << "\n";
    return 1;
  }
  std::string line;
  size_t count = 0, failed = 0;
  while (std::getline(fin, line)) {
    if (line.empty()) continue;
    std::vector<std::string> fields;
    std::stringstream ss(line);
    std::string tok;
    while (std::getline(ss, tok, '\t')) fields.push_back(tok);
    if (fields.size() < 3) continue;
    uint64_t idx = std::strtoull(fields[0].c_str(), nullptr, 10);
    std::vector<float> labels;
    for (size_t i = 1; i + 1 < fields.size(); ++i)
      labels.push_back(std::strtof(fields[i].c_str(), nullptr));
    std::string path = root.empty() ? fields.back() : root + "/" + fields.back();

    std::ifstream fimg(path, std::ios::binary);
    if (!fimg) {
      std::cerr << "skip (missing): " << path << "\n";
      ++failed;
      continue;
    }
    std::vector<unsigned char> bytes(
        (std::istreambuf_iterator<char>(fimg)), std::istreambuf_iterator<char>());

    if (resize > 0) {
      std::vector<unsigned char> rgb, sized, enc;
      int h, w;
      if (!Decode(bytes, &rgb, &h, &w)) {
        std::cerr << "skip (decode failed): " << path << "\n";
        ++failed;
        continue;
      }
      int nh, nw;
      if (h < w) {
        nh = resize;
        nw = static_cast<int>(std::lround(static_cast<double>(w) * nh / h));
      } else {
        nw = resize;
        nh = static_cast<int>(std::lround(static_cast<double>(h) * nw / w));
      }
      Resize(rgb, h, w, &sized, nh, nw);
      Encode(sized, nh, nw, quality, &bytes);
    }

    // IRHeader (python/mxnet/recordio.py pack): flag counts extra labels
    uint32_t flag = labels.size() > 1 ? static_cast<uint32_t>(labels.size()) : 0;
    float label0 = labels.empty() ? 0.f : labels[0];
    std::vector<unsigned char> rec(24 + (flag ? 4 * labels.size() : 0) +
                                   bytes.size());
    std::memcpy(rec.data(), &flag, 4);
    std::memcpy(rec.data() + 4, &label0, 4);
    std::memcpy(rec.data() + 8, &idx, 8);
    uint64_t id2 = 0;
    std::memcpy(rec.data() + 16, &id2, 8);
    size_t off = 24;
    if (flag) {
      std::memcpy(rec.data() + off, labels.data(), 4 * labels.size());
      off += 4 * labels.size();
    }
    std::memcpy(rec.data() + off, bytes.data(), bytes.size());
    WriteRecord(frec, rec);
    ++count;
    if (count % 1000 == 0) std::cerr << "packed " << count << " images\n";
  }
  std::fclose(frec);
  std::cerr << "done: " << count << " packed, " << failed << " skipped → "
            << out << "\n";
  return 0;
}
