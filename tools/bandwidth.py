#!/usr/bin/env python
"""Collective-bandwidth probe — the reference `tools/bandwidth/measure.py`
recast for the TPU mesh.

The reference measures KVStore push+pull time over a model's gradient
arrays and reports the algorithmic allreduce bandwidth
``size * 2(n-1)/n / t`` per GPU (measure.py:137-140).  That number is
half of this repo's north-star metric ("KVStore-equivalent allreduce
bandwidth over ICI", BASELINE.md).  TPU-first redesign: the collectives
are XLA ops (psum / all_gather / reduce_scatter / ppermute) jitted under
shard_map over a `jax.sharding.Mesh`, so what we time IS the compiled
collective the training step uses — there is no separate KVStore wire.

Modes:
  --sweep           message-size sweep per collective (default)
  --network resnet50  the reference mode: allreduce the model's actual
                      gradient shapes (fused flat buffer, KVStore-style)
  --json PATH       write results as JSON artifact

On a single-chip session the cross-device path can't be exercised for
real, so the tool defaults to a virtual device mesh
(--devices N -> xla_force_host_platform_device_count); numbers there
validate the machinery and give a host-collective floor.  On a pod
slice the same command reports real ICI bandwidth.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="collective bandwidth probe")
    p.add_argument("--devices", type=int, default=0,
                   help="force an N-device virtual CPU mesh (0 = use "
                        "whatever jax.devices() offers)")
    p.add_argument("--collectives", type=str,
                   default="psum,all_gather,reduce_scatter,ppermute")
    p.add_argument("--min-mb", type=float, default=0.25)
    p.add_argument("--max-mb", type=float, default=64.0)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--network", type=str, default=None,
                   help="measure the gradient-allreduce of this model "
                        "zoo network instead of a size sweep")
    p.add_argument("--dtype", type=str, default="float32")
    p.add_argument("--json", type=str, default=None)
    return p.parse_args(argv)


def _mk_collective(kind, mesh, axis="x"):
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    if kind == "psum":
        fn, in_spec, out_spec = (
            lambda x: jax.lax.psum(x, axis), P(axis), P(axis))
    elif kind == "all_gather":
        fn, in_spec, out_spec = (
            lambda x: jax.lax.all_gather(x, axis, tiled=True),
            P(axis), P(axis))
    elif kind == "reduce_scatter":
        fn, in_spec, out_spec = (
            lambda x: jax.lax.psum_scatter(x, axis, tiled=True),
            P(axis), P(axis))
    elif kind == "ppermute":
        perm = [(i, (i + 1) % n) for i in range(n)]
        fn, in_spec, out_spec = (
            lambda x: jax.lax.ppermute(x, axis, perm), P(axis), P(axis))
    else:
        raise ValueError(kind)
    sm = shard_map(fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
                   check_rep=False)
    jitted = jax.jit(sm,
                     in_shardings=NamedSharding(mesh, in_spec),
                     out_shardings=NamedSharding(mesh, out_spec))
    return jitted


# Algorithmic bytes moved per device, as a fraction of the buffer size
# (ring-algorithm accounting, same convention as reference
# measure.py:139 and nccl-tests).
ALGO_FACTOR = {
    "psum": lambda n: 2.0 * (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
}


def _time_collective(jitted, x, iters, warmup):
    """Per-device execution is in-order, so dispatching N same-input
    calls and host-reading the LAST output times all N (chaining
    out=jitted(out) would be wrong here: all_gather/reduce_scatter
    change the shape every call).  The sync is a host readback, not
    block_until_ready, which returns early on the axon platform."""
    import jax.numpy as jnp
    for _ in range(warmup):
        out = jitted(x)
    float(jnp.ravel(out)[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jitted(x)
    float(jnp.ravel(out)[0])
    dt = (time.perf_counter() - t0) / iters
    return dt


def run_sweep(args, mesh):
    import jax.numpy as jnp
    import numpy as onp

    n = mesh.shape["x"]
    dtype = jnp.dtype(args.dtype)
    rows = []
    mb = args.min_mb
    sizes = []
    while mb <= args.max_mb + 1e-9:
        sizes.append(mb)
        mb *= 4
    for kind in args.collectives.split(","):
        jitted = _mk_collective(kind, mesh)  # jit cache shared across sizes
        for mb in sizes:
            nelem = int(mb * 1e6 / dtype.itemsize)
            # divisible by n^2: sharding splits by n, and the per-shard
            # reduce_scatter splits by n again
            nelem = max(nelem // (n * n) * n * n, n * n)
            x = jnp.asarray(onp.ones((nelem,), onp.float32), dtype)
            dt = _time_collective(jitted, x, args.iters, args.warmup)
            nbytes = nelem * dtype.itemsize
            bw = nbytes * ALGO_FACTOR[kind](n) / dt / 1e9
            rows.append({"collective": kind, "mb": round(mb, 3),
                         "time_us": round(dt * 1e6, 1),
                         "algo_gb_s": round(bw, 6)})
            print(f"{kind:15s} {mb:9.2f} MB  {dt * 1e6:10.1f} us  "
                  f"{bw:8.3f} GB/s", flush=True)
    return rows


def run_network(args, mesh):
    """Reference measure.py mode: allreduce the model's real gradient
    set, both per-array (KVStore push/pull granularity) and fused."""
    import jax
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon.model_zoo import vision

    net = getattr(vision, args.network)()
    net.initialize(ctx=mx.cpu())
    net(nd.random.uniform(shape=(1, 3, 224, 224)))
    shapes = [tuple(p.shape) for p in net.collect_params().values()
              if p.grad_req != "null"]
    total = sum(int(jnp.prod(jnp.array(s))) for s in shapes)
    dtype = jnp.dtype(args.dtype)
    size_mb = total * dtype.itemsize / 1e6
    n = mesh.shape["x"]
    print(f"{args.network}: {len(shapes)} arrays, {size_mb:.1f} MB total",
          flush=True)

    jitted = _mk_collective("psum", mesh)
    # fused: one flat buffer, the fuse.py/multi-tensor path
    nelem = max(total // n * n, n)
    x = jnp.ones((nelem,), dtype)
    dt_fused = _time_collective(jitted, x, args.iters, args.warmup)
    bw_fused = nelem * dtype.itemsize * ALGO_FACTOR["psum"](n) / dt_fused / 1e9

    # per-array: one collective per parameter, KVStore granularity.
    # Warm EVERY distinct shape so no compile lands in the timed region,
    # and sync via host readback (block_until_ready returns early on
    # the axon platform).
    bufs = [jnp.ones((max(int(jnp.prod(jnp.array(s))) // n * n, n),), dtype)
            for s in shapes]
    for b in bufs:
        out = jitted(b)
    float(jnp.ravel(out)[0])
    t0 = time.perf_counter()
    for _ in range(args.iters):
        outs = [jitted(b) for b in bufs]
    float(jnp.ravel(outs[-1])[0])
    dt_per = (time.perf_counter() - t0) / args.iters
    bw_per = total * dtype.itemsize * ALGO_FACTOR["psum"](n) / dt_per / 1e9

    rows = [{"collective": "psum_fused", "mb": round(size_mb, 1),
             "time_us": round(dt_fused * 1e6, 1),
             "algo_gb_s": round(bw_fused, 6)},
            {"collective": "psum_per_array", "mb": round(size_mb, 1),
             "time_us": round(dt_per * 1e6, 1),
             "algo_gb_s": round(bw_per, 6),
             "arrays": len(shapes)}]
    for r in rows:
        print(f"{r['collective']:15s} {r['mb']:9.1f} MB  "
              f"{r['time_us']:10.1f} us  {r['algo_gb_s']:8.3f} GB/s",
              flush=True)
    return rows


def main(argv=None):
    args = parse_args(argv)
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={args.devices}")
    import jax
    if args.devices:
        # env var is not enough on hosts whose sitecustomize programs the
        # platform list (axon); the config update wins
        jax.config.update("jax_platforms", "cpu")
    from jax.sharding import Mesh
    import numpy as onp

    devs = jax.devices()
    mesh = Mesh(onp.array(devs), ("x",))
    print(f"mesh: {len(devs)}x {devs[0].platform}", flush=True)

    rows = run_network(args, mesh) if args.network else run_sweep(args, mesh)
    out = {"platform": devs[0].platform, "n_devices": len(devs),
           "dtype": args.dtype, "results": rows}
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
        print(f"wrote {args.json}", flush=True)
    return out


if __name__ == "__main__":
    main()
