#!/usr/bin/env python
"""graphlint CLI — IR-level static analysis of traced graphs.

Usage:
    python tools/graphlint.py --zoo resnet18_v1 --batch 8   # a model zoo net
    python tools/graphlint.py --ops-smoke                   # curated op sweep
    python tools/graphlint.py --op FullyConnected \
        --spec 8x256:float32 --spec 64x256:float32          # one op
    python tools/graphlint.py --selftest     # seeded violations per rule
    python tools/graphlint.py --json --ignore GL-TILE001 ...

Exit status: 0 when no error-severity findings beyond the baseline
(advisories are reported but never gate), 1 otherwise.  Rule catalog:
docs/graph_analysis.md.  Unlike mxlint this tool traces — it imports
the framework (and jax) and runs on the CPU backend.

``--zoo`` lints the block's forward in BOTH inference and training
mode (training exercises the BatchNorm stats path and dropout masks).
``--ops-smoke`` sweeps a curated set of central operators at canonical
shapes in f32 and bf16 — the compiled surface almost every model
shares.  ``--selftest`` seeds one violation per rule (plus a shape-leak
recompile storm and a strict-mode ``check_traced``) and requires each
expected rule id / typed error to surface — proving the CI stage would
catch the real thing.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "ci", "graphlint_baseline.json")

# (op, specs as (shape, dtype), static kwargs) — central ops most graphs
# share; bf16 entries prove the low-precision paths accumulate wide
_OPS_SMOKE = [
    ("FullyConnected", [((8, 256), "float32"), ((64, 256), "float32"),
                        ((64,), "float32")], {}),
    ("FullyConnected", [((8, 256), "bfloat16"), ((64, 256), "bfloat16"),
                        ((64,), "bfloat16")], {}),
    ("Convolution", [((2, 8, 16, 16), "float32"), ((16, 8, 3, 3),
                     "float32")], {"kernel": (3, 3), "num_filter": 16,
                                   "pad": (1, 1)}),
    ("BatchNorm", [((8, 16, 8, 8), "bfloat16")] + [((16,), "float32")] * 4,
     {"training": True}),
    ("BatchNorm", [((8, 16, 8, 8), "float32")] + [((16,), "float32")] * 4,
     {}),
    ("Pooling", [((4, 16, 16, 16), "bfloat16")],
     {"kernel": (2, 2), "pool_type": "avg"}),
    ("Pooling", [((4, 16, 16, 16), "bfloat16")],
     {"global_pool": True, "pool_type": "avg"}),
    ("LayerNorm", [((16, 128), "bfloat16"), ((128,), "float32"),
                   ((128,), "float32")], {}),
    ("softmax", [((32, 128), "bfloat16")], {}),
    ("softmax_xent", [((32, 128), "float32"), ((32,), "float32")], {}),
    ("sum", [((64, 1024), "bfloat16")], {"axis": 1}),
    ("mean", [((64, 1024), "bfloat16")], {"axis": 1}),
]


def selftest():
    """Seed one violation per rule and require the expected rule id —
    plus the sentinel's storm error and strict-mode check_traced."""
    import warnings

    import numpy as onp
    import jax
    import jax.numpy as jnp
    from jax import lax

    from incubator_mxnet_tpu import error
    from incubator_mxnet_tpu.analysis import graphlint as gl
    from incubator_mxnet_tpu.analysis import recompile as rc

    failures = []

    def expect(tag, rules, fn, *args, **kw):
        got = {f.rule for f in gl.lint_fn(fn, *args, **kw)}
        if not set(rules) <= got:
            failures.append(f"{tag}: wanted {rules}, got {sorted(got)}")
        else:
            print(f"[selftest] {tag}: {sorted(rules)} OK")

    with jax.experimental.enable_x64():
        expect("f64-upcast", ["GL-DTYPE001"],
               lambda x: (x.astype(jnp.float64) * 2.0).sum(),
               jnp.ones((4,), jnp.float32))
    baked = onp.ones((600, 600), onp.float32)
    expect("baked-const", ["GL-CONST001"], lambda x: x @ baked,
           jnp.ones((2, 600)))
    expect("host-callback", ["GL-HOST001"],
           lambda x: jax.pure_callback(
               lambda a: onp.asarray(a) * 2,
               jax.ShapeDtypeStruct(x.shape, x.dtype), x),
           jnp.ones((4,)))
    expect("dead-code", ["GL-DEAD001"],
           lambda x: (jnp.sin(x), (x * 2).sum())[1], jnp.ones((4,)))
    expect("promotion", ["GL-DTYPE002"],
           lambda x, w: x * w, jnp.ones((8,), jnp.bfloat16),
           jnp.ones((8,), jnp.float32))
    expect("bf16-accum", ["GL-PREC001"],
           lambda x: lax.reduce_window(x, 0.0, lax.add, (1024,), (1,),
                                       "VALID"),
           jnp.ones((2048,), jnp.bfloat16))
    expect("tile-layout", ["GL-TILE001"],
           lambda x: x.reshape(65536, 4) * 2, jnp.ones((4 * 65536,)))
    expect("donate-advisory", ["GL-DONATE001"],
           lambda p, g: p - 0.1 * g, jnp.ones((1024,)),
           jnp.ones((1024,)), check_donation=True)

    # shape-leak recompile storm -> typed error with the diagnosis
    rc.reset()
    try:
        with rc.sentinel_scope("raise", 3):
            for n in range(1, 10):
                rc.record_compile(
                    "selftest:leak", (("arr", (n, 8), "float32"),))
        failures.append("recompile-storm: RecompileStormError not raised")
    except error.RecompileStormError as e:
        if "varying leading/batch" not in str(e):
            failures.append(f"recompile-storm: diagnosis missing: {e}")
        else:
            print("[selftest] recompile-storm: RecompileStormError OK")
    finally:
        rc.reset()

    # strict check_traced -> GraphLintError (and warn mode only warns)
    prev = gl.set_lint_mode("strict")
    try:
        gl.check_traced(lambda x: (jnp.sin(x), x.sum())[1],
                        (jnp.ones((4,)),), name="selftest:strict")
        failures.append("strict-mode: GraphLintError not raised")
    except error.GraphLintError:
        print("[selftest] strict-mode: GraphLintError OK")
    finally:
        gl.set_lint_mode(prev)
    gl.set_lint_mode("warn")
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            gl.check_traced(lambda x: (jnp.sin(x), x.sum())[1],
                            (jnp.ones((4,)),), name="selftest:warn")
        if not any("GL-DEAD001" in str(x.message) for x in w):
            failures.append("warn-mode: no GL-DEAD001 warning emitted")
        else:
            print("[selftest] warn-mode: warning OK")
    finally:
        gl.set_lint_mode(prev)

    for f in failures:
        print(f"[selftest] FAIL {f}")
    print("[selftest] " + ("FAILED" if failures
                           else "all seeded violations caught"))
    return 1 if failures else 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="graphlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--zoo", action="append", default=[],
                   help="model_zoo.vision factory name (repeatable)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--op", default=None, help="registered op name")
    p.add_argument("--spec", action="append", default=[],
                   help="input spec for --op as NxM...:dtype (repeatable, "
                        "in positional order)")
    p.add_argument("--kw", action="append", default=[],
                   help="static kwarg for --op as name=value (python "
                        "literal), repeatable")
    p.add_argument("--ops-smoke", action="store_true",
                   help="lint the curated central-operator sweep")
    p.add_argument("--selftest", action="store_true",
                   help="seed one violation per rule; each must surface")
    p.add_argument("--ignore", action="append", default=[],
                   help="rule id to silence (repeatable)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default: {DEFAULT_BASELINE} "
                        "when it exists; same contract as mxlint)")
    p.add_argument("--prune-stale", action="store_true",
                   help="rewrite the baseline file with its stale "
                        "entries removed, then report as usual")
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)

    if not (args.zoo or args.op or args.ops_smoke or args.selftest):
        p.error("nothing to lint: pass --zoo, --op, --ops-smoke "
                "and/or --selftest")

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import incubator_mxnet_tpu as mx   # noqa: F401  (registers ops)
    from incubator_mxnet_tpu.analysis import findings as flib
    from incubator_mxnet_tpu.analysis import graphlint as gl

    if args.selftest:
        rc = selftest()
        if rc or not (args.zoo or args.op or args.ops_smoke):
            return rc

    config = gl.Config(ignore=args.ignore)
    findings = []
    wheres = []   # entry labels this run analyzed (prune-stale scope)

    for name in args.zoo:
        from incubator_mxnet_tpu import nd
        from incubator_mxnet_tpu.gluon.model_zoo import vision
        net = vision.get_model(name, classes=10)
        net.initialize()
        x = nd.random.uniform(
            shape=(args.batch, 3, args.image_size, args.image_size))
        net(x)   # materialize deferred-shape parameters
        for training in (False, True):
            mode = "train" if training else "infer"
            wheres.append(f"zoo:{name}:{mode}")
            findings += gl.lint_block(net, x, training=training,
                                      where=f"zoo:{name}:{mode}",
                                      config=config)

    def parse_spec(s):
        dims, _, dtype = s.partition(":")
        shape = tuple(int(d) for d in dims.split("x") if d)
        return (shape, dtype or "float32")

    if args.op:
        import ast
        kwargs = {}
        for kv in args.kw:
            k, _, v = kv.partition("=")
            try:
                kwargs[k] = ast.literal_eval(v)
            except (ValueError, SyntaxError):
                kwargs[k] = v
        from incubator_mxnet_tpu.ops.registry import get_op
        # canonical name: findings are labeled op:<op.name>, so an
        # alias spelling (--op Reshape) must scope the same entries
        wheres.append(f"op:{get_op(args.op).name}")
        findings += gl.lint_op(args.op,
                               *[parse_spec(s) for s in args.spec],
                               config=config, **kwargs)

    if args.ops_smoke:
        from incubator_mxnet_tpu.ops.registry import get_op
        for op, specs, kwargs in _OPS_SMOKE:
            wheres.append(f"op:{get_op(op).name}")
            findings += gl.lint_op(op, *specs, config=config, **kwargs)

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)
    baseline = (flib.load_baseline(baseline_path) if baseline_path
                else {})
    errors = [f for f in findings if f.severity == "error"]
    advisories = [f for f in findings if f.severity != "error"]
    regressions, suppressed, stale = flib.apply_baseline(errors, baseline)

    if args.prune_stale and stale and baseline_path:
        # only entries whose analyzed surface ran this invocation are
        # prunable — a --zoo/--op subset must not delete the rest of
        # the surfaces' justified entries.  Baseline "file" is the
        # finding's where+path (path always begins with "/"), so the
        # "/" boundary keeps op:relu from claiming op:relu6's entries
        def in_scope(key):
            return any(key[1] == w or key[1].startswith(w + "/")
                       for w in wheres)

        pruned = [k for k in stale if in_scope(k)]
        flib.prune_stale_baseline(baseline_path, stale,
                                  in_scope=in_scope)
        print(f"[graphlint] pruned {len(pruned)} stale entr"
              f"{'y' if len(pruned) == 1 else 'ies'} from {baseline_path}"
              + (f" ({len(stale) - len(pruned)} out-of-scope kept)"
                 if len(pruned) != len(stale) else ""))
        stale = [k for k in stale if not in_scope(k)]

    if args.as_json:
        print(json.dumps({
            "regressions": [f.as_dict() for f in regressions],
            "suppressed": [f.as_dict() for f in suppressed],
            "advisories": [f.as_dict() for f in advisories],
            "stale_baseline": [list(k) for k in stale],
        }, indent=2))
    else:
        if regressions:
            print(gl.render(regressions))
        if advisories:
            print(gl.render(advisories))
        for key in stale:
            print(f"[graphlint] note: stale baseline entry {key} — the "
                  "finding is gone, drop it from the baseline")
        print(f"[graphlint] {len(regressions)} finding(s), "
              f"{len(advisories)} advisor{'y' if len(advisories) == 1 else 'ies'}, "
              f"{len(suppressed)} baselined, {len(stale)} stale")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
