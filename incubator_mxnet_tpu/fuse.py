"""Whole-step compilation: forward + backward + optimizer in ONE XLA program.

The TPU analog of the reference's op-bulking + static_alloc CachedOp
(graph_executor.cc:1422 InitOpSegs; cached_op.h static paths): instead of
pushing hundreds of small ops per step, the entire train step — loss,
gradients, optimizer update, BatchNorm moving-stat updates — compiles to
a single donated-buffer XLA executable.  This is the framework's
performance path for benchmarks and large-scale training; the eager
Trainer remains the flexible path.

Optimizer math is shared with ``optimizer/optimizer.py`` by construction:
the fused updates below implement the same formulas (SGD+momentum, NAG,
Adam, AdamW) as pure pytree transforms.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import executor_cache as _xc
from .ndarray import NDArray

__all__ = ["FusedTrainStep", "make_fused_train_step", "sgd_init", "adam_init"]


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def sgd_init(params):
    return {"mom": _tree_map(jnp.zeros_like, params)}


def adam_init(params):
    return {"m": _tree_map(jnp.zeros_like, params),
            "v": _tree_map(jnp.zeros_like, params),
            "t": jnp.zeros((), jnp.int32)}


def _sgd_update(grads, state, params, lr, momentum, wd):
    new_mom = _tree_map(
        lambda p, g, m: momentum * m - lr * (g + wd * p),
        params, grads, state["mom"])
    new_params = _tree_map(lambda p, m2: (p + m2).astype(p.dtype),
                           params, new_mom)
    return new_params, {"mom": new_mom}


def _nag_update(grads, state, params, lr, momentum, wd):
    """Nesterov momentum, same formula as optimizer.py NAG.update."""
    new_mom = _tree_map(lambda p, g, m: momentum * m + g + wd * p,
                        params, grads, state["mom"])
    new_params = _tree_map(
        lambda p, g, m2: (p - lr * (g + wd * p + momentum * m2)).astype(p.dtype),
        params, grads, new_mom)
    return new_params, {"mom": new_mom}


def _adam_update(grads, state, params, lr, b1, b2, eps, wd):
    t = state["t"] + 1
    tf = t.astype(jnp.float32)
    corr = jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
    new_m = _tree_map(lambda g, m, p: b1 * m + (1 - b1) * (g + wd * p),
                      grads, state["m"], params)
    new_v = _tree_map(lambda g, v, p: b2 * v + (1 - b2) * jnp.square(g + wd * p),
                      grads, state["v"], params)
    new_params = _tree_map(
        lambda p, m2, v2: (p - lr * corr * m2 /
                           (jnp.sqrt(v2) + eps)).astype(p.dtype),
        params, new_m, new_v)
    return new_params, {"m": new_m, "v": new_v, "t": t}


def _adamw_update(grads, state, params, lr, b1, b2, eps, wd):
    """Decoupled weight decay, same formula as optimizer.py AdamW.update."""
    t = state["t"] + 1
    tf = t.astype(jnp.float32)
    corr = jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
    new_m = _tree_map(lambda g, m: b1 * m + (1 - b1) * g, grads, state["m"])
    new_v = _tree_map(lambda g, v: b2 * v + (1 - b2) * jnp.square(g),
                      grads, state["v"])
    new_params = _tree_map(
        lambda p, m2, v2: (p - lr * corr * m2 / (jnp.sqrt(v2) + eps)
                           - lr * wd * p).astype(p.dtype),
        params, new_m, new_v)
    return new_params, {"m": new_m, "v": new_v, "t": t}


class FusedTrainStep:
    """Compiled train step over a gluon block.

    Usage::

        step = make_fused_train_step(net, loss_fn, "sgd",
                                     {"learning_rate": 0.1, "momentum": 0.9})
        for batch in data:
            loss = step(x, y)     # one XLA program; params live on device
        step.write_back()          # sync updated params into the Block
    """

    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, batch_spec=None, donate=True, remat=None):
        self.block = block
        self.loss_block = loss_fn
        opt_params = dict(optimizer_params or {})
        self.lr = opt_params.get("learning_rate", 0.01)
        self.momentum = opt_params.get("momentum", 0.0)
        self.wd = opt_params.get("wd", 0.0)
        self.optimizer = optimizer
        params_all, apply_fn = block.functional()
        self._apply = apply_fn
        # split trainable vs aux (grad_req null → moving stats etc.)
        named = list(block.collect_params().items())
        self._trainable_names = [n for n, p in named if p.grad_req != "null"]
        self._aux_names = [n for n, p in named if p.grad_req == "null"]
        # copy the initial values: the step donates its param buffers, and
        # donating the Block's live arrays would delete them out from
        # under any eval pass on the block itself
        self.params = {n: jnp.array(params_all[n])
                       for n in self._trainable_names}
        self.aux = {n: jnp.array(params_all[n]) for n in self._aux_names}
        if optimizer in ("sgd", "nag"):
            self.opt_state = sgd_init(self.params)
        elif optimizer in ("adam", "adamw"):
            self.opt_state = adam_init(self.params)
        else:
            raise ValueError(
                f"fused step supports sgd/nag/adam/adamw; got {optimizer!r} "
                f"(use the eager Trainer for others)")
        if remat not in (None, "dots", "nothing"):
            raise ValueError(
                f"remat must be None, 'dots' or 'nothing'; got {remat!r}")
        self._key = jax.random.PRNGKey(0)
        self._remat = remat
        self._lint_done = False
        self._memlint_done = False
        self._step_fn = self._build(mesh, batch_spec, donate)
        self._last = None

    def _build(self, mesh, batch_spec, donate):
        loss_block = self.loss_block
        apply = self._apply
        lr, momentum, wd = self.lr, self.momentum, self.wd
        optimizer = self.optimizer

        def loss_of(params, aux, x, y, key):
            out, updates = apply({**params, **aux}, x, training=True,
                                 key=key, with_updates=True)
            if isinstance(out, tuple):
                out = out[0]
            loss = loss_block(NDArray(out), NDArray(y))
            return jnp.mean(loss.data), updates

        if self._remat:
            # rematerialization (SURVEY §"HBM bandwidth"): trade recompute
            # for activation traffic.  'dots' keeps matmul outputs and
            # recomputes the elementwise/norm tail in the backward pass;
            # 'nothing' recomputes the whole forward.
            policies = {
                "dots": jax.checkpoint_policies.checkpoint_dots,
                "nothing": jax.checkpoint_policies.nothing_saveable,
            }
            loss_of = jax.checkpoint(loss_of, policy=policies[self._remat])

        def step(params, aux, opt_state, x, y, key):
            (loss, updates), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, aux, x, y, key)
            if optimizer == "sgd":
                new_params, new_state = _sgd_update(grads, opt_state, params,
                                                    lr, momentum, wd)
            elif optimizer == "nag":
                new_params, new_state = _nag_update(grads, opt_state, params,
                                                    lr, momentum, wd)
            elif optimizer == "adamw":
                new_params, new_state = _adamw_update(
                    grads, opt_state, params, lr, 0.9, 0.999, 1e-8, wd)
            else:
                new_params, new_state = _adam_update(
                    grads, opt_state, params, lr, 0.9, 0.999, 1e-8, wd)
            new_aux = {**aux, **{k: v for k, v in updates.items() if k in aux}}
            return new_params, new_aux, new_state, loss

        donate_argnums = (0, 1, 2) if donate else ()
        # the unified choke point owns sentinel instrumentation + jit
        # (the executor keeps the raw uninstrumented step as .fn for
        # the build-time analyses — its lint trace must not count as a
        # sentinel compile):
        # a fused step should compile ONCE per batch shape — churn here
        # (varying batch, a dtype flip) is the single most expensive
        # recompile in the framework
        in_shardings = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            bspec = NamedSharding(mesh, batch_spec or P("dp"))
            in_shardings = (None, None, None, bspec, bspec, None)
        self._executor = _xc.Executor(
            step, f"fused_step:{type(self.block).__name__}",
            donate_argnums=donate_argnums, in_shardings=in_shardings)
        return self._executor.jfn

    def __call__(self, x, y):
        xv = x.data if isinstance(x, NDArray) else x
        yv = y.data if isinstance(y, NDArray) else y
        self._key, sub = jax.random.split(self._key)
        if not (self._lint_done and self._memlint_done):
            # build-time analyses of the whole train step through the
            # unified choke point (MXNET_GRAPH_LINT/MXNET_GRAPH_MEMLINT).
            # GL-DEAD001 is ignored by documented scope limit: AD
            # transposition leaves dead primal eqns in every
            # value_and_grad trace.  An undonated step (donate=False)
            # earns its GL-DONATE001 advisory and is an error-severity
            # ML-DONATE001 — the fused step CONTRACTS to donate
            # params/aux/optimizer state.  Each latch only sets once
            # its mode is on, so enabling either mode after step 1
            # still analyzes.
            from .analysis import graphlint as _graphlint
            do_lint = not self._lint_done and _xc.lint_active()
            do_mem = not self._memlint_done and _xc.memlint_active()
            self._lint_done = self._lint_done or do_lint
            self._memlint_done = self._memlint_done or do_mem
            if do_lint or do_mem:
                self._executor.analyze(
                    (self.params, self.aux, self.opt_state, xv, yv, sub),
                    graphlint=dict(
                        check_donation=True,
                        config=_graphlint.Config(ignore={"GL-DEAD001"}),
                    ) if do_lint else None,
                    memlint=dict(require_donation=True)
                    if do_mem else None)
        self.params, self.aux, self.opt_state, loss = self._step_fn(
            self.params, self.aux, self.opt_state, xv, yv, sub)
        self._last = loss
        return loss

    def write_back(self):
        """Copy updated params back into the Block's Parameters."""
        all_params = dict(self.block.collect_params().items())
        for name, val in {**self.params, **self.aux}.items():
            all_params[name]._check_and_get()._set_data(val)


def make_fused_train_step(block, loss_fn, optimizer="sgd",
                          optimizer_params=None, **kwargs):
    return FusedTrainStep(block, loss_fn, optimizer, optimizer_params,
                          **kwargs)
