"""Whole-step compilation: forward + backward + optimizer in ONE XLA program.

The TPU analog of the reference's op-bulking + static_alloc CachedOp
(graph_executor.cc:1422 InitOpSegs; cached_op.h static paths): instead of
pushing hundreds of small ops per step, the entire train step — loss,
gradients, optimizer update, BatchNorm moving-stat updates — compiles to
a single donated-buffer XLA executable.  This is the framework's
performance path for benchmarks and large-scale training; the eager
Trainer remains the flexible path.

Optimizer math is shared with ``optimizer/optimizer.py`` by construction:
the fused updates below implement the same formulas (SGD+momentum, NAG,
Adam, AdamW) as pure pytree transforms.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import executor_cache as _xc
from .base import resolve_chunk_steps
from .ndarray import NDArray

__all__ = ["FusedTrainStep", "make_fused_train_step", "sgd_init", "adam_init"]


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def sgd_init(params):
    return {"mom": _tree_map(jnp.zeros_like, params)}


def adam_init(params):
    return {"m": _tree_map(jnp.zeros_like, params),
            "v": _tree_map(jnp.zeros_like, params),
            "t": jnp.zeros((), jnp.int32)}


def _sgd_update(grads, state, params, lr, momentum, wd):
    new_mom = _tree_map(
        lambda p, g, m: momentum * m - lr * (g + wd * p),
        params, grads, state["mom"])
    new_params = _tree_map(lambda p, m2: (p + m2).astype(p.dtype),
                           params, new_mom)
    return new_params, {"mom": new_mom}


def _nag_update(grads, state, params, lr, momentum, wd):
    """Nesterov momentum, same formula as optimizer.py NAG.update."""
    new_mom = _tree_map(lambda p, g, m: momentum * m + g + wd * p,
                        params, grads, state["mom"])
    new_params = _tree_map(
        lambda p, g, m2: (p - lr * (g + wd * p + momentum * m2)).astype(p.dtype),
        params, grads, new_mom)
    return new_params, {"mom": new_mom}


def _adam_update(grads, state, params, lr, b1, b2, eps, wd):
    t = state["t"] + 1
    tf = t.astype(jnp.float32)
    corr = jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
    new_m = _tree_map(lambda g, m, p: b1 * m + (1 - b1) * (g + wd * p),
                      grads, state["m"], params)
    new_v = _tree_map(lambda g, v, p: b2 * v + (1 - b2) * jnp.square(g + wd * p),
                      grads, state["v"], params)
    new_params = _tree_map(
        lambda p, m2, v2: (p - lr * corr * m2 /
                           (jnp.sqrt(v2) + eps)).astype(p.dtype),
        params, new_m, new_v)
    return new_params, {"m": new_m, "v": new_v, "t": t}


def _adamw_update(grads, state, params, lr, b1, b2, eps, wd):
    """Decoupled weight decay, same formula as optimizer.py AdamW.update."""
    t = state["t"] + 1
    tf = t.astype(jnp.float32)
    corr = jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
    new_m = _tree_map(lambda g, m: b1 * m + (1 - b1) * g, grads, state["m"])
    new_v = _tree_map(lambda g, v: b2 * v + (1 - b2) * jnp.square(g),
                      grads, state["v"])
    new_params = _tree_map(
        lambda p, m2, v2: (p - lr * corr * m2 / (jnp.sqrt(v2) + eps)
                           - lr * wd * p).astype(p.dtype),
        params, new_m, new_v)
    return new_params, {"m": new_m, "v": new_v, "t": t}


class FusedTrainStep:
    """Compiled train step over a gluon block.

    Usage::

        step = make_fused_train_step(net, loss_fn, "sgd",
                                     {"learning_rate": 0.1, "momentum": 0.9})
        for batch in data:
            loss = step(x, y)     # one XLA program; params live on device
        step.write_back()          # sync updated params into the Block
    """

    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, batch_spec=None, donate=True, remat=None,
                 chunk_steps=None):
        self.block = block
        self.loss_block = loss_fn
        opt_params = dict(optimizer_params or {})
        self.lr = opt_params.get("learning_rate", 0.01)
        self.momentum = opt_params.get("momentum", 0.0)
        self.wd = opt_params.get("wd", 0.0)
        self.optimizer = optimizer
        params_all, apply_fn = block.functional()
        self._apply = apply_fn
        # split trainable vs aux (grad_req null → moving stats etc.)
        named = list(block.collect_params().items())
        self._trainable_names = [n for n, p in named if p.grad_req != "null"]
        self._aux_names = [n for n, p in named if p.grad_req == "null"]
        # copy the initial values: the step donates its param buffers, and
        # donating the Block's live arrays would delete them out from
        # under any eval pass on the block itself
        self.params = {n: jnp.array(params_all[n])
                       for n in self._trainable_names}
        self.aux = {n: jnp.array(params_all[n]) for n in self._aux_names}
        if optimizer in ("sgd", "nag"):
            self.opt_state = sgd_init(self.params)
        elif optimizer in ("adam", "adamw"):
            self.opt_state = adam_init(self.params)
        else:
            raise ValueError(
                f"fused step supports sgd/nag/adam/adamw; got {optimizer!r} "
                f"(use the eager Trainer for others)")
        if remat not in (None, "dots", "nothing"):
            raise ValueError(
                f"remat must be None, 'dots' or 'nothing'; got {remat!r}")
        # chunk budget for the whole-loop compilation path (fuse_loop):
        # K == 1 stays on this per-step program, K > 1 lets a
        # ChunkedTrainLoop scan K steps per dispatch
        self.chunk_steps = resolve_chunk_steps(chunk_steps)
        self._key = jax.random.PRNGKey(0)
        if mesh is None:
            # commit the whole train state to its device up front: jit
            # outputs are committed arrays, so an uncommitted first
            # call would compile one executable for step 1 and a
            # second — the real steady-state one — for step 2+.  One
            # program per batch shape, from the first dispatch (the
            # mesh path leaves placement to the pjit shardings)
            dev = jax.devices()[0]
            self.params, self.aux, self.opt_state, self._key = \
                jax.device_put(
                    (self.params, self.aux, self.opt_state, self._key),
                    dev)
        self._remat = remat
        # kept for the chunked loop (fuse_loop): the scanned program
        # re-applies the same batch sharding to its (K, batch, ...)
        # blocks, with the scan axis unsharded
        self._mesh = mesh
        self._batch_spec = batch_spec
        self._lint_done = False
        self._memlint_done = False
        self._shardlint_done = False
        self._step_fn = self._build(mesh, batch_spec, donate)
        self._last = None

    def _build(self, mesh, batch_spec, donate):
        loss_block = self.loss_block
        apply = self._apply
        lr, momentum, wd = self.lr, self.momentum, self.wd
        optimizer = self.optimizer

        def loss_of(params, aux, x, y, key):
            out, updates = apply({**params, **aux}, x, training=True,
                                 key=key, with_updates=True)
            if isinstance(out, tuple):
                out = out[0]
            loss = loss_block(NDArray(out), NDArray(y))
            return jnp.mean(loss.data), updates

        if self._remat:
            # rematerialization (SURVEY §"HBM bandwidth"): trade recompute
            # for activation traffic.  'dots' keeps matmul outputs and
            # recomputes the elementwise/norm tail in the backward pass;
            # 'nothing' recomputes the whole forward.
            policies = {
                "dots": jax.checkpoint_policies.checkpoint_dots,
                "nothing": jax.checkpoint_policies.nothing_saveable,
            }
            loss_of = jax.checkpoint(loss_of, policy=policies[self._remat])

        def step(params, aux, opt_state, x, y, key):
            (loss, updates), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, aux, x, y, key)
            if optimizer == "sgd":
                new_params, new_state = _sgd_update(grads, opt_state, params,
                                                    lr, momentum, wd)
            elif optimizer == "nag":
                new_params, new_state = _nag_update(grads, opt_state, params,
                                                    lr, momentum, wd)
            elif optimizer == "adamw":
                new_params, new_state = _adamw_update(
                    grads, opt_state, params, lr, 0.9, 0.999, 1e-8, wd)
            else:
                new_params, new_state = _adam_update(
                    grads, opt_state, params, lr, 0.9, 0.999, 1e-8, wd)
            new_aux = {**aux, **{k: v for k, v in updates.items() if k in aux}}
            return new_params, new_aux, new_state, loss

        donate_argnums = (0, 1, 2) if donate else ()
        # the unified choke point owns sentinel instrumentation + jit
        # (the executor keeps the raw uninstrumented step as .fn for
        # the build-time analyses — its lint trace must not count as a
        # sentinel compile):
        # a fused step should compile ONCE per batch shape — churn here
        # (varying batch, a dtype flip) is the single most expensive
        # recompile in the framework
        in_shardings = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            bspec = NamedSharding(mesh, batch_spec or P("dp"))
            in_shardings = (None, None, None, bspec, bspec, None)
        self._executor = _xc.Executor(
            step, f"fused_step:{type(self.block).__name__}",
            donate_argnums=donate_argnums, in_shardings=in_shardings)
        return self._executor.jfn

    def __call__(self, x, y):
        xv = x.data if isinstance(x, NDArray) else x
        yv = y.data if isinstance(y, NDArray) else y
        self._key, sub = jax.random.split(self._key)
        if not (self._lint_done and self._memlint_done):
            # build-time analyses of the whole train step through the
            # unified choke point (MXNET_GRAPH_LINT/MXNET_GRAPH_MEMLINT).
            # An undonated step (donate=False) earns its GL-DONATE001
            # advisory and is an error-severity ML-DONATE001 — the
            # fused step CONTRACTS to donate params/aux/optimizer
            # state.  Latch/exemption discipline lives in
            # latch_train_analyses (shared with ChunkedTrainLoop).
            self._lint_done, self._memlint_done = \
                _xc.latch_train_analyses(
                    self._executor,
                    (self.params, self.aux, self.opt_state, xv, yv, sub),
                    self._lint_done, self._memlint_done)
        if not self._shardlint_done and _xc.shardlint_active():
            # one-shot shardlint over the same step: the batch args
            # carry the declared dp spec when a mesh was given; the
            # train state is legitimately replicated (dp), so only
            # the collective bill and per-shard peak are of interest
            from jax.sharding import PartitionSpec as P
            bspec = (self._batch_spec or P("dp")) \
                if self._mesh is not None else None
            self._executor.analyze(
                (self.params, self.aux, self.opt_state, xv, yv, sub),
                shardlint=dict(
                    mesh=self._mesh,
                    in_specs=(None, None, None, bspec, bspec, None),
                    allow_replicated=(0, 1, 2, 5)))
            self._shardlint_done = True
        self.params, self.aux, self.opt_state, loss = self._step_fn(
            self.params, self.aux, self.opt_state, xv, yv, sub)
        self._last = loss
        return loss

    @property
    def step_fn(self):
        """The raw (uninstrumented) pure step function
        ``(params, aux, opt_state, x, y, key) -> (params, aux,
        opt_state, loss)`` — the body a :class:`~.fuse_loop.
        ChunkedTrainLoop` scans over."""
        return self._executor.fn

    def chunked_loop(self, chunk_steps=None):
        """A :class:`~.fuse_loop.ChunkedTrainLoop` over this step
        (state stays shared: the loop reads and writes this step's
        params/aux/opt_state/key, so tail batches and ``write_back``
        keep working unchanged)."""
        from .fuse_loop import ChunkedTrainLoop
        return ChunkedTrainLoop(self, chunk_steps=chunk_steps)

    def write_back(self):
        """Copy updated params back into the Block's Parameters."""
        all_params = dict(self.block.collect_params().items())
        for name, val in {**self.params, **self.aux}.items():
            all_params[name]._check_and_get()._set_data(val)


def make_fused_train_step(block, loss_fn, optimizer="sgd",
                          optimizer_params=None, **kwargs):
    return FusedTrainStep(block, loss_fn, optimizer, optimizer_params,
                          **kwargs)
