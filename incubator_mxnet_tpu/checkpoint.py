"""Sharded asynchronous checkpointing (SURVEY §5.4: keep the reference
``.params`` formats for interop — ndarray/params_io.py — and ADD a
sharded async checkpoint for large-scale training; the reference's
preemption story is checkpoint-restart, event_handler.py:336).

TPU-first design: ``save()`` dispatches an async on-device COPY of
each leaf and returns — the copy is a fresh buffer, so the training
loop's donated param buffers (fuse.py donates by default) cannot
invalidate the snapshot — then a background thread pulls the copies to
host and writes them.  Sharded arrays are written one file per unique
addressable shard (replica 0 only), keyed by process index, with an
index of global shape/dtype/shard slices, so on a multi-host mesh each
process writes only the HBM it owns (no gather through one host).
Single-process checkpoints are staged under a ``.tmp`` name and
atomically renamed; multi-process writes land per-file with the
per-process index written last as the completion marker (cross-process
commit barriers belong to the launcher).
"""
from __future__ import annotations

import json
import logging
import os
import re
import shutil
import threading
import zlib

import numpy as onp

from . import fault
from .error import (CheckpointCorruptError, CheckpointWriteError,
                    ReshardError)

__all__ = ["AsyncCheckpointManager"]

_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_RE = re.compile(r"^step_(\d+)\.tmp$")

_log = logging.getLogger("incubator_mxnet_tpu.checkpoint")


def _crc_of(host) -> int:
    """CRC32 of an array's payload bytes — the integrity identity each
    shard records in the index and re-proves at load."""
    return zlib.crc32(onp.ascontiguousarray(host).tobytes()) & 0xFFFFFFFF


def _safe(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", name)


def _leaf_array(v):
    # unwrap the framework NDArray only — numpy scalars/arrays also have
    # a .data attribute (a memoryview), which must NOT be taken
    if hasattr(v, "asnumpy") and hasattr(v, "data"):
        v = v.data
    import jax
    if isinstance(v, jax.Array):
        # async on-device copy: a NEW buffer, immune to later donation
        # of the original by the train step (fuse.py donate_argnums)
        import jax.numpy as jnp
        return jnp.copy(v)
    # host leaves are copied too: the snapshot must not see in-place
    # mutations made after save() returns; plain scalars become 0-d
    return onp.array(v)


class AsyncCheckpointManager:
    """Async, shard-aware checkpoint directory manager.

    Usage::

        ckpt = AsyncCheckpointManager(dir, keep=3)
        ckpt.save(step, {"w": w, "m": m})   # returns immediately
        ...
        ckpt.wait()                          # barrier (e.g. before exit)
        params = ckpt.restore()              # latest, name -> numpy
    """

    def __init__(self, directory, keep=5):
        self.directory = str(directory)
        self.keep = int(keep)
        os.makedirs(self.directory, exist_ok=True)
        self._thread = None
        self._error = None
        self._cleanup_stale_tmp()

    _TMP_STALE_S = 15 * 60

    def _cleanup_stale_tmp(self):
        """Remove ``step_N.tmp`` staging dirs left by a crashed save.

        A live writer touches its staging dir continuously, so only
        dirs whose newest mtime is older than ``_TMP_STALE_S`` are
        removed — another manager's in-flight save into the same
        directory must not be torn out from under it."""
        import time
        now = time.time()  # mxlint: allow-wall-clock(staleness is judged against file mtimes, which are wall-clock)
        for entry in os.listdir(self.directory):
            if not _TMP_RE.match(entry):
                continue
            p = os.path.join(self.directory, entry)
            try:
                newest = max([os.path.getmtime(p)]
                             + [os.path.getmtime(os.path.join(p, f))
                                for f in os.listdir(p)])
            except OSError:
                continue   # racing with its writer or already gone
            if now - newest > self._TMP_STALE_S:
                _log.warning("checkpoint: removing stale staging dir %s "
                             "(crashed save, idle %.0fs)", entry,
                             now - newest)
                shutil.rmtree(p, ignore_errors=True)

    # ------------------------------------------------------------- save
    def save(self, step, tree, wait=False):
        """Snapshot ``tree`` (dict name -> NDArray/jax.Array/numpy) at
        ``step``.  References are captured synchronously (jax.Arrays are
        immutable, so later parameter updates cannot corrupt the
        snapshot); device→host transfer + IO happen on the writer
        thread."""
        self.wait()  # one in-flight checkpoint at a time, oldest first
        flat = {str(k): _leaf_array(v) for k, v in tree.items()}
        from . import flightrec
        flightrec.record(flightrec.CHECKPOINT, "checkpoint.save",
                         step=int(step), leaves=len(flat))
        self._thread = threading.Thread(
            target=self._write, args=(int(step), flat), daemon=True)
        self._thread.start()
        if wait:
            self.wait()

    def _write(self, step, flat):
        import jax
        proc = jax.process_index()
        single = jax.process_count() == 1
        final = os.path.join(self.directory, f"step_{step:08d}")
        tmp = final + ".tmp" if single else final
        try:
            if single and os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp, exist_ok=True)
            index = {}
            for name, arr in flat.items():
                fname = _safe(name)
                shards = getattr(arr, "addressable_shards", None)
                sharded = shards is not None and (
                    len(shards) > 1
                    or not getattr(arr, "is_fully_addressable", True))
                if sharded:
                    entries = []
                    for k, sh in enumerate(shards):
                        if getattr(sh, "replica_id", 0) != 0:
                            continue  # one copy per unique slice
                        fn = f"{fname}.p{proc}_s{k}.npy"
                        host = onp.asarray(sh.data)
                        fault.inject("checkpoint.write", detail=fn)
                        onp.save(os.path.join(tmp, fn), host)
                        entries.append({
                            "file": fn,
                            "crc32": _crc_of(host),
                            "index": [[sl.start or 0,
                                       sl.stop if sl.stop is not None
                                       else dim]
                                      for sl, dim in zip(sh.index,
                                                         arr.shape)],
                        })
                    index[name] = {"shape": list(arr.shape),
                                   "dtype": str(onp.dtype(arr.dtype)),
                                   "shards": entries}
                else:
                    fn = f"{fname}.npy" if single else f"{fname}.p{proc}.npy"
                    if single or proc == 0:  # replicated: one copy
                        host = onp.asarray(arr)
                        fault.inject("checkpoint.write", detail=fn)
                        onp.save(os.path.join(tmp, fn), host)
                        index[name] = {"shape": list(host.shape),
                                       "dtype": str(host.dtype
                                                    if host.dtype.kind != "V"
                                                    else onp.dtype(arr.dtype)),
                                       "file": fn,
                                       "crc32": _crc_of(host)}
            # the per-process index is written LAST: its presence marks
            # this process's contribution complete.  nprocs lets restore
            # prove EVERY process committed — a directory missing any
            # index.<i>.json is incomplete, not a smaller fleet's save.
            idx_name = "index.json" if single else f"index.{proc}.json"
            with open(os.path.join(tmp, idx_name), "w") as f:
                json.dump({"step": step, "nprocs": jax.process_count(),
                           "params": index}, f)
            if single:
                if os.path.exists(final):
                    shutil.rmtree(final)
                os.rename(tmp, final)  # atomic publish
        except BaseException as e:  # mxlint: allow-broad-except(banked sticky and rethrown at the next wait or save)
            self._error = e
            if single:
                shutil.rmtree(tmp, ignore_errors=True)
            return
        try:
            # pruning failures must not mark the (already durable)
            # checkpoint as failed
            self._prune()
        except OSError:
            pass

    def _prune(self):
        steps = sorted(self.all_steps())
        for s in steps[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(os.path.join(self.directory, f"step_{s:08d}"),
                          ignore_errors=True)

    # ------------------------------------------------------- inspection
    def wait(self):
        """Block until the in-flight checkpoint (if any) is durable;
        re-raises a writer-thread failure as a typed
        :class:`~incubator_mxnet_tpu.error.CheckpointWriteError`.
        ``save()`` calls this first, so a banked failure also surfaces
        at the NEXT save — a silently-failing checkpoint loop cannot
        run for hours believing it has durable state."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            from . import flightrec
            flightrec.record(flightrec.CHECKPOINT,
                             "checkpoint.write_failed",
                             severity="error",
                             error=type(err).__name__)
            raise CheckpointWriteError(
                f"async checkpoint write failed: {type(err).__name__}: "
                f"{err}") from err

    def all_steps(self):
        out = []
        for entry in os.listdir(self.directory):
            m = _STEP_RE.match(entry)
            d = os.path.join(self.directory, entry)
            if m and (os.path.exists(os.path.join(d, "index.json"))
                      or os.path.exists(os.path.join(d, "index.0.json"))):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    # ---------------------------------------------------------- restore
    def restore(self, step=None):
        """Reassemble a checkpoint into {name: numpy array} (global
        arrays; re-shard with jax.device_put(..., sharding) to resume
        on a mesh — or use :meth:`reshard_restore` to land directly on
        a target mesh).

        Every shard listed with a ``crc32`` is re-verified against its
        loaded bytes; a mismatch, truncated file, or missing shard
        raises :class:`~incubator_mxnet_tpu.error.CheckpointCorruptError`
        — a damaged checkpoint never loads silently.  With ``step=None``
        the NEWEST complete-and-valid checkpoint wins: corrupt steps
        are logged and skipped (crash-restart must not die on the very
        damage it is recovering from); an explicit ``step`` is strict."""
        if step is not None:
            tree = self._restore_step(step)
            from . import flightrec
            flightrec.record(flightrec.CHECKPOINT,
                             "checkpoint.restored", step=int(step),
                             fell_back=False)
            return tree
        return self._newest_first(self._restore_step)

    def reshard_restore(self, tree_spec=None, mesh=None, rule_fn=None,
                        step=None):
        """Restore a checkpoint directly onto a (possibly different)
        mesh: each global array is assembled from whichever shard files
        cover its slices — regardless of the mesh shape that SAVED it —
        and placed with the :class:`~jax.sharding.NamedSharding` that
        ``rule_fn`` chooses (``parallel.mesh.shard_params``-style
        placement).  Returns ``{name: jax.Array}`` carrying the target
        sharding.

        ``tree_spec`` selects and validates: ``None`` restores every
        name in the index; a dict ``{name: template}`` (arrays or
        ``jax.ShapeDtypeStruct``; ``None`` values skip validation)
        restores exactly those names, raising
        :class:`~incubator_mxnet_tpu.error.ReshardError` on a name the
        index does not carry or a shape/dtype conflict.  ``rule_fn(name,
        shape_dtype_struct) -> PartitionSpec`` (default: replicate).

        Integrity follows :meth:`restore` exactly: per-source-shard CRC
        verification on read, ``step=None`` walks newest-first past
        corrupt checkpoints, an explicit ``step`` is strict.  Spec-level
        problems (``ReshardError``) are NOT treated as corruption — an
        impossible request must surface, not silently fall back."""
        if mesh is None:
            raise ReshardError("reshard_restore requires a target mesh")

        def loader(s):
            tree = self._reshard_step(s, tree_spec, mesh, rule_fn)
            from . import flightrec
            flightrec.record(flightrec.CHECKPOINT, "checkpoint.reshard",
                             step=s, mesh=list(mesh.shape.values()))
            return tree

        if step is not None:
            return loader(step)
        return self._newest_first(loader)

    def _newest_first(self, loader):
        """Run ``loader(step)`` newest-first, skipping damaged steps."""
        from . import flightrec
        steps = self.all_steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        last_err = None
        for s in reversed(steps):
            try:
                tree = loader(s)
                flightrec.record(flightrec.CHECKPOINT,
                                 "checkpoint.restored", step=s,
                                 fell_back=last_err is not None)
                return tree
            except CheckpointCorruptError as e:
                flightrec.record(flightrec.CHECKPOINT,
                                 "checkpoint.fallback", severity="warn",
                                 step=s, error=str(e)[:200])
                _log.warning("checkpoint step %d is damaged (%s); "
                             "falling back to the previous one", s, e)
                last_err = e
        flightrec.record(flightrec.CHECKPOINT,
                         "checkpoint.unrecoverable", severity="error",
                         steps=len(steps))
        raise CheckpointCorruptError(
            f"no valid checkpoint in {self.directory}: all of steps "
            f"{steps} failed verification") from last_err

    def _step_dir(self, step):
        d = os.path.join(self.directory, f"step_{int(step):08d}")
        if not os.path.isdir(d):
            # absence is not corruption: resume logic starts fresh on
            # FileNotFoundError but must crash loudly on real damage
            raise FileNotFoundError(
                f"no checkpoint for step {step} in {self.directory}")
        return d

    def _restore_step(self, step):
        d = self._step_dir(step)
        try:
            return self._load_dir(d, step)
        except CheckpointCorruptError:
            raise
        except (OSError, ValueError, EOFError, KeyError) as e:
            # onp.load on a truncated .npy raises ValueError/EOFError;
            # a torn index raises KeyError/JSONDecodeError (⊂ ValueError)
            raise CheckpointCorruptError(
                f"checkpoint step {step} failed to load: "
                f"{type(e).__name__}: {e}") from e

    def _merged_index(self, d, step):
        """Read and merge the step's index(es) into {name: meta}.

        Multi-process layout: the per-process index is the completion
        marker, and each records ``nprocs`` — any missing
        ``index.<i>.json`` means a writer process died before
        committing, which is corruption (fall back newest-first), not a
        smaller save."""
        if os.path.exists(os.path.join(d, "index.json")):
            with open(os.path.join(d, "index.json")) as f:
                return json.load(f)["params"]
        merged, seen_procs, nprocs = {}, set(), 0
        for entry in sorted(os.listdir(d)):
            m = re.match(r"^index\.(\d+)\.json$", entry)
            if not m:
                continue
            seen_procs.add(int(m.group(1)))
            with open(os.path.join(d, entry)) as f:
                data = json.load(f)
            nprocs = max(nprocs, int(data.get("nprocs", 0)))
            for name, meta in data["params"].items():
                if name in merged and "shards" in meta:
                    merged[name]["shards"] += meta["shards"]
                else:
                    merged[name] = meta
        if not seen_procs:
            # the step directory exists but NO completion marker landed
            # (every writer died pre-commit): an explicit restore(step)
            # must raise, not hand back an empty parameter tree
            raise CheckpointCorruptError(
                f"checkpoint step {step} has no index at all — no "
                "writer process committed (the per-process index is "
                "the completion marker)")
        missing = set(range(nprocs)) - seen_procs
        if missing:
            raise CheckpointCorruptError(
                f"checkpoint step {step} is incomplete: per-process "
                f"index missing for process(es) {sorted(missing)} of "
                f"{nprocs} (the index is the completion marker — a "
                "writer process died before committing)")
        return merged

    def _read_block(self, d, entry, dtype, step, what):
        """Load one shard file, CRC-verify it, restore exotic dtypes."""
        fault.inject("checkpoint.read", detail=entry["file"])
        block = onp.load(os.path.join(d, entry["file"]))
        want = entry.get("crc32")
        # pre-CRC checkpoints stay loadable (no integrity info)
        if want is not None and _crc_of(block) != want:
            raise CheckpointCorruptError(
                f"checkpoint step {step}: CRC mismatch for {what} "
                f"({entry['file']}): recorded {want:#010x}, file "
                f"has {_crc_of(block):#010x} (bit rot or a torn "
                "write)")
        # numpy serializes exotic dtypes (bf16/fp8) as raw void of the
        # same itemsize; view restores the logical dtype
        if block.dtype != dtype and block.dtype.kind == "V":
            return block.view(dtype)
        return block

    @staticmethod
    def _entries_of(meta):
        """Normalize a meta record to (shape, dtype, [shard entries]),
        each entry carrying an explicit [[start, stop], ...] index."""
        shape = list(meta["shape"])
        dtype = onp.dtype(meta["dtype"])  # ml_dtypes names resolve
        if "shards" in meta:
            return shape, dtype, meta["shards"]
        full = dict(meta)
        full["index"] = [[0, dim] for dim in shape]
        return shape, dtype, [full]

    def _load_dir(self, d, step):
        out = {}
        for name, meta in self._merged_index(d, step).items():
            shape, dtype, entries = self._entries_of(meta)
            if "shards" not in meta:
                out[name] = self._read_block(d, meta, dtype, step,
                                             repr(name))
                continue
            full = onp.zeros(shape, dtype)
            covered = 0
            for entry in entries:
                block = self._read_block(d, entry, dtype, step,
                                         f"shard of {name!r}")
                sl = tuple(slice(a, b) for a, b in entry["index"])
                full[sl] = block
                covered += int(block.size)
            if covered < int(onp.prod(shape)):
                raise CheckpointCorruptError(
                    f"checkpoint step {step} is incomplete for "
                    f"{name!r}: {covered} of "
                    f"{int(onp.prod(shape))} elements present "
                    "(a writer process likely died mid-save)")
            out[name] = full
        return out

    # ----------------------------------------------------- resharding
    def _reshard_step(self, step, tree_spec, mesh, rule_fn):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        d = self._step_dir(step)
        try:
            merged = self._merged_index(d, step)
        except CheckpointCorruptError:
            raise
        except (OSError, ValueError, EOFError, KeyError) as e:
            raise CheckpointCorruptError(
                f"checkpoint step {step} failed to load: "
                f"{type(e).__name__}: {e}") from e
        names = list(tree_spec) if tree_spec is not None else sorted(merged)
        absent = [n for n in names if n not in merged]
        if absent:
            raise ReshardError(
                f"checkpoint step {step} has no entry for {absent}: "
                f"the index carries {sorted(merged)}")
        out = {}
        for name in names:
            shape, dtype, entries = self._entries_of(merged[name])
            if tree_spec is not None and tree_spec[name] is not None:
                want = tree_spec[name]
                wshape = tuple(getattr(want, "shape", ()) or ())
                wdtype = getattr(want, "dtype", None)
                if wshape != tuple(shape) or (
                        wdtype is not None
                        and onp.dtype(wdtype) != dtype):
                    raise ReshardError(
                        f"target spec for {name!r} wants shape={wshape} "
                        f"dtype={wdtype}, but the checkpoint recorded "
                        f"shape={tuple(shape)} dtype={dtype}")
            struct = jax.ShapeDtypeStruct(tuple(shape), dtype)
            spec = (rule_fn(name, struct) if rule_fn is not None
                    else PartitionSpec())
            try:
                out[name] = self._assemble_on(
                    d, step, name, shape, dtype, entries,
                    NamedSharding(mesh, spec))
            except CheckpointCorruptError:
                raise
            except (ValueError, KeyError, TypeError) as e:
                # load-level failures were already converted to
                # CheckpointCorruptError at the read site, so whatever
                # reaches here is a spec the mesh cannot carry (unknown
                # axis, indivisible shape) — a REQUEST problem, not
                # checkpoint damage: surface it, never fall back
                raise ReshardError(
                    f"cannot lay out {name!r} (shape {tuple(shape)}, "
                    f"dtype {dtype}) as {spec} on mesh "
                    f"{dict(mesh.shape)}: {e}") from e
        return out

    def _assemble_on(self, d, step, name, shape, dtype, entries,
                     sharding):
        """Build one global array on ``sharding``, feeding each target
        shard only from the source shard files that overlap its slice
        (every file CRC-verified once, cached across target shards)."""
        import jax
        cache: dict = {}

        def _cached(entry):
            fn = entry["file"]
            if fn not in cache:
                try:
                    cache[fn] = self._read_block(d, entry, dtype, step,
                                                 f"shard of {name!r}")
                except CheckpointCorruptError:
                    raise
                except (OSError, ValueError, EOFError, KeyError) as e:
                    # a truncated .npy raises ValueError/EOFError: that
                    # is DAMAGE (newest-first fallback applies), and it
                    # must not be mistaken for a layout ValueError
                    raise CheckpointCorruptError(
                        f"checkpoint step {step} failed to load shard "
                        f"{fn!r} of {name!r}: {type(e).__name__}: "
                        f"{e}") from e
            return cache[fn]

        def _gather(index):
            starts = [sl.start or 0 for sl in index]
            stops = [sl.stop if sl.stop is not None else dim
                     for sl, dim in zip(index, shape)]
            if not shape:  # 0-d leaf: single source entry holds it all
                return onp.asarray(_cached(entries[0]))
            out = onp.zeros([b - a for a, b in zip(starts, stops)], dtype)
            covered = 0
            for entry in entries:
                src = entry["index"]
                lo = [max(a, s) for (a, _), s in zip(src, starts)]
                hi = [min(b, t) for (_, b), t in zip(src, stops)]
                if any(l >= h for l, h in zip(lo, hi)):
                    continue  # no overlap with this target shard
                block = _cached(entry)
                src_sl = tuple(slice(l - a, h - a)
                               for (a, _), l, h in zip(src, lo, hi))
                dst_sl = tuple(slice(l - s, h - s)
                               for s, l, h in zip(starts, lo, hi))
                out[dst_sl] = block[src_sl]
                covered += int(onp.prod([h - l for l, h in zip(lo, hi)]))
            if covered < int(out.size):
                raise CheckpointCorruptError(
                    f"checkpoint step {step} is incomplete for "
                    f"{name!r}: target slice "
                    f"{[(a, b) for a, b in zip(starts, stops)]} has "
                    f"{covered} of {int(out.size)} elements covered by "
                    "the recorded shards (a writer process likely died "
                    "mid-save)")
            return out

        return jax.make_array_from_callback(tuple(shape), sharding,
                                            _gather)
