"""ctypes binding to the native runtime library (libmxtpu.so).

The C++ sources live in ``src/`` at the repo root (recordio, dependency
engine, pooled storage, image-record pipeline — the TPU-native
counterparts of the reference's src/engine, src/storage, src/io). This
module finds the built library, lazily building it with ``make`` when a
toolchain is present (the role of libinfo.py:25 find_lib_path +
base.py:339 _load_lib in the reference). Everything degrades gracefully:
``lib`` is None when no library can be loaded, and pure-Python fallbacks
take over.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from ..locks import named_lock

__all__ = ["lib", "check_call", "ImageIterParams", "ENGINE_FN", "available"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_LIB_PATH = os.path.join(_HERE, "libmxtpu.so")
_lock = named_lock("native.lib")


class ImageIterParams(ctypes.Structure):
    """Mirror of MXTImageIterParams (src/include/mxt/c_api.h)."""

    _fields_ = [
        ("path_imgrec", ctypes.c_char_p),
        ("batch_size", ctypes.c_int),
        ("channels", ctypes.c_int),
        ("height", ctypes.c_int),
        ("width", ctypes.c_int),
        ("mean_r", ctypes.c_float),
        ("mean_g", ctypes.c_float),
        ("mean_b", ctypes.c_float),
        ("std_r", ctypes.c_float),
        ("std_g", ctypes.c_float),
        ("std_b", ctypes.c_float),
        ("scale", ctypes.c_float),
        ("resize", ctypes.c_int),
        ("rand_crop", ctypes.c_int),
        ("rand_mirror", ctypes.c_int),
        ("shuffle", ctypes.c_int),
        ("round_batch", ctypes.c_int),
        ("num_threads", ctypes.c_int),
        ("prefetch", ctypes.c_int),
        ("seed", ctypes.c_uint64),
        ("label_width", ctypes.c_int),
    ]


ENGINE_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.POINTER(ctypes.c_char_p))


def _try_build() -> bool:
    """Build libmxtpu.so from src/ if sources and g++ are present.

    Failures are reported on stderr (not swallowed) so a silent fallback
    to the pure-Python paths is always explained. Set
    MXNET_NATIVE_AUTOBUILD=0 to skip building at import.
    """
    makefile = os.path.join(_REPO, "src", "Makefile")
    if not os.path.exists(makefile):
        return False
    if os.environ.get("MXNET_NATIVE_AUTOBUILD", "1") == "0":
        return False
    try:
        proc = subprocess.run(["make", "-C", os.path.join(_REPO, "src")],
                              capture_output=True, timeout=600, text=True)
    except (OSError, subprocess.SubprocessError) as e:
        import sys
        print(f"[incubator_mxnet_tpu] native build failed ({e}); "
              "falling back to pure-Python runtime", file=sys.stderr)
        return False
    if proc.returncode != 0:
        import sys
        tail = "\n".join((proc.stderr or "").splitlines()[-15:])
        print("[incubator_mxnet_tpu] native build failed; falling back to "
              f"pure-Python runtime. Last compiler output:\n{tail}",
              file=sys.stderr)
        return False
    return os.path.exists(_LIB_PATH)


def _declare(dll: ctypes.CDLL) -> ctypes.CDLL:
    u64 = ctypes.c_uint64
    vp = ctypes.c_void_p
    dll.MXTGetLastError.restype = ctypes.c_char_p
    dll.MXTGetLastError.argtypes = []
    # recordio
    dll.MXTRecordIOWriterCreate.argtypes = [ctypes.c_char_p, ctypes.POINTER(vp)]
    dll.MXTRecordIOWriterWrite.argtypes = [vp, ctypes.c_char_p, u64]
    dll.MXTRecordIOWriterTell.argtypes = [vp, ctypes.POINTER(u64)]
    dll.MXTRecordIOWriterFree.argtypes = [vp]
    dll.MXTRecordIOReaderCreate.argtypes = [ctypes.c_char_p, ctypes.POINTER(vp)]
    dll.MXTRecordIOReaderNext.argtypes = [vp, ctypes.POINTER(ctypes.c_void_p),
                                          ctypes.POINTER(u64)]
    dll.MXTRecordIOReaderSeek.argtypes = [vp, u64]
    dll.MXTRecordIOReaderTell.argtypes = [vp, ctypes.POINTER(u64)]
    dll.MXTRecordIOReaderFree.argtypes = [vp]
    # engine
    dll.MXTEngineCreate.argtypes = [ctypes.c_int, ctypes.POINTER(vp)]
    dll.MXTEngineNewVar.argtypes = [vp, ctypes.POINTER(vp)]
    dll.MXTEngineVarVersion.argtypes = [vp, vp, ctypes.POINTER(u64)]
    dll.MXTEnginePush.argtypes = [vp, ENGINE_FN, vp, ctypes.POINTER(vp),
                                  ctypes.c_int, ctypes.POINTER(vp),
                                  ctypes.c_int, ctypes.c_int]
    dll.MXTEngineWaitForVar.argtypes = [vp, vp]
    dll.MXTEngineWaitAll.argtypes = [vp]
    dll.MXTEngineDeleteVar.argtypes = [vp, vp]
    dll.MXTEngineFree.argtypes = [vp]
    # storage
    dll.MXTStorageAlloc.argtypes = [u64, ctypes.POINTER(vp)]
    dll.MXTStorageFree.argtypes = [vp, u64]
    dll.MXTStorageStats.argtypes = [ctypes.POINTER(u64), ctypes.POINTER(u64)]
    dll.MXTStorageReleaseAll.argtypes = []
    # image iter
    dll.MXTImageIterCreate.argtypes = [ctypes.POINTER(ImageIterParams),
                                       ctypes.POINTER(vp)]
    dll.MXTImageIterNext.argtypes = [vp, ctypes.POINTER(ctypes.c_float),
                                     ctypes.POINTER(ctypes.c_float),
                                     ctypes.POINTER(ctypes.c_int),
                                     ctypes.POINTER(ctypes.c_int)]
    dll.MXTImageIterReset.argtypes = [vp]
    dll.MXTImageIterNumSamples.argtypes = [vp, ctypes.POINTER(u64)]
    dll.MXTImageIterFree.argtypes = [vp]
    dll.MXTImdecode.argtypes = [ctypes.c_char_p, u64,
                                ctypes.POINTER(ctypes.c_ubyte),
                                ctypes.POINTER(ctypes.c_int),
                                ctypes.POINTER(ctypes.c_int)]
    return dll


def _load() -> "ctypes.CDLL | None":
    if os.environ.get("MXNET_NATIVE_LIB_DISABLE", "0") == "1":
        return None
    with _lock:
        if not os.path.exists(_LIB_PATH) and not _try_build():
            return None
        try:
            return _declare(ctypes.CDLL(_LIB_PATH))
        except OSError:
            return None


lib = _load()


def available() -> bool:
    return lib is not None


def check_call(rc: int) -> None:
    """Raise the native error as a Python exception (c_api_error analog).

    Messages prefixed "Kind: ..." map onto the registered error class
    (error.py registry), so ``except mx.error.ValueError`` works on
    native failures; everything else raises the MXNetError base.
    """
    if rc != 0:
        from ..error import get_error_class, MXNetError
        msg = lib.MXTGetLastError().decode("utf-8", "replace")
        kind, sep, _rest = msg.partition(": ")
        cls = get_error_class(kind) if sep else MXNetError
        raise cls(f"native runtime error: {msg}")
