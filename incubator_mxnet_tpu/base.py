"""Base utilities: errors, dtype tables, registries.

TPU-native counterpart of the reference's ``python/mxnet/base.py`` (ctypes
library loading is replaced by direct JAX usage — there is no dlopen step)
and of dmlc-core's parameter/registry machinery.
"""
from __future__ import annotations

import os
import threading
import numpy as onp

import jax.numpy as jnp
from .locks import named_lock

__all__ = [
    "MXNetError",
    "classproperty",
    "string_types",
    "numeric_types",
    "integer_types",
    "dtype_np_to_jax",
    "dtype_from_any",
    "dtype_name",
    "registry",
]

string_types = (str,)
numeric_types = (float, int, onp.generic)
integer_types = (int, onp.integer)


class MXNetError(RuntimeError):
    """Framework error type (reference: python/mxnet/base.py MXNetError)."""


class classproperty:
    def __init__(self, f):
        self.f = f

    def __get__(self, obj, owner):
        return self.f(owner)


# ---------------------------------------------------------------------------
# dtypes
# ---------------------------------------------------------------------------

# Canonical dtype table.  The reference enumerates dtypes in
# include/mxnet/base.h via mshadow type flags; here the canonical identity is
# the numpy dtype object and bfloat16 is first-class (TPU native compute type).
_DTYPE_NAMES = {
    "float32": jnp.float32,
    "float64": jnp.float64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "uint8": jnp.uint8,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "uint64": jnp.uint64,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "bool": jnp.bool_,
}


def dtype_np_to_jax(dtype):
    return jnp.dtype(dtype)


def dtype_from_any(dtype):
    """Accept a string name, numpy dtype, python type, or jax dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _DTYPE_NAMES:
            raise TypeError(f"unknown dtype name {dtype!r}")
        return jnp.dtype(_DTYPE_NAMES[dtype])
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    return jnp.dtype(dtype).name


# ---------------------------------------------------------------------------
# Generic name->object registry (reference: dmlc Registry / mxnet.registry)
# ---------------------------------------------------------------------------

class _Registry:
    """A simple name registry with alias support.

    Mirrors the role of ``python/mxnet/registry.py`` in the reference: a
    decorator-based name→class table used for optimizers, initializers,
    metrics, losses and data iterators.
    """

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: dict[str, object] = {}
        self._lock = named_lock("base.registry")

    def register(self, obj=None, name: str | None = None):
        def do(o):
            key = (name or o.__name__).lower()
            with self._lock:
                self._entries[key] = o
            return o

        if obj is None:
            return do
        return do(obj)

    def alias(self, *names):
        def do(o):
            with self._lock:
                for n in names:
                    self._entries[n.lower()] = o
            return o

        return do

    def get(self, name: str):
        try:
            return self._entries[name.lower()]
        except KeyError:
            raise KeyError(
                f"{self.kind} {name!r} is not registered "
                f"(known: {sorted(self._entries)})"
            ) from None

    def find(self, name: str):
        return self._entries.get(name.lower())

    def create(self, name, *args, **kwargs):
        if isinstance(name, str):
            return self.get(name)(*args, **kwargs)
        return name  # already an instance

    def list(self):
        return sorted(self._entries)

    def __contains__(self, name):
        return name.lower() in self._entries


_REGISTRIES: dict[str, _Registry] = {}


def registry(kind: str) -> _Registry:
    if kind not in _REGISTRIES:
        _REGISTRIES[kind] = _Registry(kind)
    return _REGISTRIES[kind]


def get_env(name: str, default, dtype=str):
    """dmlc::GetEnv equivalent: typed environment variable lookup.

    The reference reads ~90 MXNET_* env vars at point of use
    (docs/static_site/src/pages/api/faq/env_var.md); we honour the same
    convention under both MXNET_* and MXTPU_* prefixes.
    """
    for candidate in (name, name.replace("MXNET_", "MXTPU_")):
        val = os.environ.get(candidate)
        if val is not None:
            if dtype is bool:
                return val not in ("0", "false", "False", "")
            return dtype(val)
    return default


def resolve_chunk_steps(chunk_steps=None):
    """K for the whole-loop-compiled training path (fuse_loop.py):
    an explicit value wins, else ``MXNET_TRAIN_CHUNK_STEPS`` (default
    1 — the per-step fused path).  Single point of truth for the env
    fallback and the >= 1 validation shared by Trainer,
    FusedTrainStep, ChunkedTrainLoop and DevicePrefetchRing."""
    k = int(chunk_steps if chunk_steps is not None
            else get_env("MXNET_TRAIN_CHUNK_STEPS", 1, int))
    if k < 1:
        raise ValueError(f"chunk_steps must be >= 1, got {k}")
    return k


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: new jax exposes it at the
    top level (replication check switch ``check_vma=``), 0.4.x under
    ``jax.experimental.shard_map`` with the same switch named
    ``check_rep=``."""
    import jax
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:
            pass
    from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def axis_size_compat(axis_name):
    """``lax.axis_size`` across jax versions; on older jax the size of
    a named mapped axis is the trace-time constant ``psum(1, axis)``."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
