"""SSD single-shot detector (reference example/ssd + the SSD symbol it
builds from src/operator/contrib/multibox_*; BASELINE config 4).

Gluon-style definition: a conv backbone is downsampled through scale
stages; every stage emits class and box convolutions plus multibox_prior
anchors. Targets/decoding ride the contrib detection ops
(ops/contrib_ops.py), so training and inference both stay inside one XLA
program — no host round-trips in the loop.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..gluon import nn, HybridBlock, loss as gloss
from ..ndarray import NDArray
from .. import ndarray as nd
from ..ops import contrib_ops as _det


def _feature_block(channels):
    blk = nn.HybridSequential()
    blk.add(nn.Conv2D(channels, 3, padding=1),
            nn.BatchNorm(), nn.Activation("relu"),
            nn.Conv2D(channels, 3, padding=1),
            nn.BatchNorm(), nn.Activation("relu"),
            nn.MaxPool2D(2))
    return blk


class SSD(HybridBlock):
    """Multi-scale SSD head over a simple VGG-style backbone.

    num_classes excludes background. sizes/ratios follow the reference
    example/ssd convention: one (sizes, ratios) pair per scale stage.
    """

    def __init__(self, num_classes=20,
                 sizes=((0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
                        (0.71, 0.79), (0.88, 0.961)),
                 ratios=((1, 2, 0.5),) * 5,
                 base_channels=16, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.sizes = sizes
        self.ratios = ratios
        self._num_stages = len(sizes)
        for i in range(self._num_stages):
            na = len(sizes[i]) + len(ratios[i]) - 1
            setattr(self, f"stage{i}",
                    _feature_block(base_channels * min(2 ** i, 4))
                    if i < self._num_stages - 1 else nn.GlobalMaxPool2D())
            setattr(self, f"cls{i}",
                    nn.Conv2D(na * (num_classes + 1), 3, padding=1))
            setattr(self, f"box{i}", nn.Conv2D(na * 4, 3, padding=1))

    def forward(self, x):
        anchors, cls_preds, box_preds = [], [], []
        for i in range(self._num_stages):
            x = getattr(self, f"stage{i}")(x)
            a = _det.multibox_prior.fn(
                x.data if isinstance(x, NDArray) else x,
                sizes=self.sizes[i], ratios=self.ratios[i])
            c = getattr(self, f"cls{i}")(x)
            b = getattr(self, f"box{i}")(x)
            anchors.append(NDArray(a, ctx=x.ctx) if isinstance(x, NDArray)
                           else a)
            # (B, A·K, H, W) → (B, H·W·A, K) flattening per stage
            cls_preds.append(self._flatten_pred(c, self.num_classes + 1))
            box_preds.append(self._flatten_pred(b, 4))
        anchors = nd.concat(*anchors, dim=1) if isinstance(anchors[0], NDArray) \
            else jnp.concatenate(anchors, axis=1)
        cls_preds = nd.concat(*cls_preds, dim=1)
        box_preds = nd.concat(*box_preds, dim=1)
        # (B, N, C+1) → (B, C+1, N) as the contrib ops expect
        cls_preds = cls_preds.transpose((0, 2, 1))
        return anchors, cls_preds, box_preds.reshape((box_preds.shape[0], -1))

    @staticmethod
    def _flatten_pred(p, k):
        # (B, A·K, H, W) → (B, H, W, A·K) → (B, H·W·A, K)
        t = p.transpose((0, 2, 3, 1))
        return t.reshape((t.shape[0], -1, k))

    # -- training / inference helpers ----------------------------------
    def targets(self, anchors, labels, cls_preds,
                overlap_threshold=0.5, negative_mining_ratio=3.0):
        """MultiBoxTarget wrapper (cls_target uses 0 = background)."""
        return nd.contrib.MultiBoxTarget(
            anchors, labels, cls_preds,
            overlap_threshold=overlap_threshold,
            negative_mining_ratio=negative_mining_ratio)

    def detections(self, cls_preds, box_preds, anchors, nms_threshold=0.45,
                   threshold=0.01, nms_topk=400):
        probs = nd.softmax(cls_preds, axis=1)
        return nd.contrib.MultiBoxDetection(
            probs, box_preds, anchors, nms_threshold=nms_threshold,
            threshold=threshold, nms_topk=nms_topk)


class SSDLoss:
    """cls softmax-CE (ignoring hard-negative-mined anchors) + smooth-L1
    box loss, the reference example/ssd training objective."""

    def __init__(self, lambd=1.0):
        self.lambd = lambd

    def __call__(self, cls_preds, box_preds, cls_target, loc_target,
                 loc_mask):
        # per-anchor CE over the class axis; anchors marked ignore_label
        # by hard negative mining contribute nothing
        logp = nd.log_softmax(cls_preds, axis=1)          # (B, C+1, N)
        ignore = cls_target < 0
        safe = nd.where(ignore, nd.zeros_like(cls_target), cls_target)
        ce = -nd.pick(logp.transpose((0, 2, 1)), safe, axis=-1)  # (B, N)
        valid = 1.0 - ignore.astype("float32")
        cls_loss = (ce * valid).sum(axis=-1) / nd.maximum(
            valid.sum(axis=-1), nd.ones((1,)))
        # smooth-L1 on masked offsets, normalized by positive count
        diff = (box_preds - loc_target) * loc_mask
        ad = nd.abs(diff)
        sl1 = nd.where(ad < 1.0, 0.5 * diff * diff, ad - 0.5)
        npos = nd.maximum(loc_mask.sum(axis=-1), nd.ones((1,)))
        box_loss = sl1.sum(axis=-1) / npos
        return cls_loss + self.lambd * box_loss


def ssd_300(num_classes=20, **kwargs):
    """Standard-config constructor (reference example/ssd symbol zoo)."""
    return SSD(num_classes=num_classes, **kwargs)
