"""LSTM language model (BASELINE config 5; reference example/rnn/word_lm).

Gluon block over the fused lax.scan RNN op — the path that replaces the
reference's cuDNN RNN kernels.
"""
from __future__ import annotations

from ..gluon import nn, rnn
from ..gluon.block import HybridBlock


class LSTMLanguageModel(HybridBlock):
    def __init__(self, vocab_size, embed_size=200, hidden_size=200,
                 num_layers=2, dropout=0.5, tie_weights=False, **kwargs):
        super().__init__(**kwargs)
        self.drop = nn.Dropout(dropout)
        self.encoder = nn.Embedding(vocab_size, embed_size)
        self.rnn = rnn.LSTM(hidden_size, num_layers, dropout=dropout,
                            input_size=embed_size)
        self.decoder = nn.Dense(vocab_size, in_units=hidden_size)
        self._hidden_size = hidden_size

    def begin_state(self, batch_size, ctx=None, **kwargs):
        return self.rnn.begin_state(batch_size, ctx=ctx, **kwargs)

    def forward(self, inputs, state=None):
        """inputs (T, B) int → logits (T, B, V)."""
        emb = self.drop(self.encoder(inputs))
        if state is None:
            output = self.rnn(emb)
            out_state = None
        else:
            output, out_state = self.rnn(emb, state)
        output = self.drop(output)
        decoded = self.decoder(
            output.reshape((-1, self._hidden_size))).reshape(
            (output.shape[0], output.shape[1], -1))
        if out_state is None:
            return decoded
        return decoded, out_state
