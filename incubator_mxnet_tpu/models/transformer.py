"""Flagship TPU-native transformer LM with 5-axis parallelism.

Pure-functional JAX model (params pytree + apply fn) designed mesh-first:

* dp — batch sharding; gradient psum fused into backward by GSPMD
* tp — attention heads + FFN hidden column/row parallel (Megatron split:
  qkv col-parallel, out-proj row-parallel → one psum per block)
* sp — sequence sharding with ring attention (collective-permute KV
  rotation, parallel/ring_attention.py) or GSPMD-gathered attention
* pp — layer-stack axis sharded over 'pp' (stage placement); an explicit
  microbatch ppermute pipeline lives in parallel/pipeline.py
* ep — optional MoE FFN with experts over 'ep' (parallel/moe.py)

No reference equivalent (SURVEY.md §2.3: TP/PP/SP/EP absent in MXNet 1.x)
— this is the "beyond reference" capability layer the TPU build requires.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.ring_attention import ring_attention
from ..parallel.moe import init_moe_params, moe_forward

__all__ = ["TransformerConfig", "TransformerLM"]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_len: int = 2048
    dtype: str = "bfloat16"
    use_moe: bool = False
    n_experts: int = 8
    attention: str = "gspmd"  # 'gspmd' | 'ring' | 'flash' (pallas kernel)

    @property
    def head_dim(self):
        return self.d_model // self.n_heads


def _dtype(cfg):
    return jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32


class TransformerLM:
    """init/apply container (functional; no gluon dependency on purpose —
    this model feeds pjit/shard_map directly)."""

    def __init__(self, config: TransformerConfig):
        self.cfg = config

    # -- parameters -------------------------------------------------------
    def init(self, key):
        cfg = self.cfg
        dt = _dtype(cfg)
        keys = jax.random.split(key, 6)
        D, H, F, L = cfg.d_model, cfg.n_heads, cfg.d_ff, cfg.n_layers
        s = lambda k, shape, scale: (jax.random.normal(k, shape, jnp.float32)
                                     * scale).astype(dt)
        params = {
            "embed": s(keys[0], (cfg.vocab_size, D), 0.02),
            "pos_embed": s(keys[1], (cfg.max_len, D), 0.02),
            "layers": {
                "wqkv": s(keys[2], (L, D, 3 * D), D ** -0.5),
                "wo": s(keys[3], (L, D, D), D ** -0.5),
                "ln1": jnp.ones((L, D), dt),
                "ln2": jnp.ones((L, D), dt),
                "w1": s(keys[4], (L, D, F), D ** -0.5),
                "w2": s(keys[5], (L, F, D), F ** -0.5),
            },
            "ln_f": jnp.ones((D,), dt),
        }
        if cfg.use_moe:
            params["moe"] = init_moe_params(
                jax.random.fold_in(key, 99), D, F, cfg.n_experts, dt)
        return params

    def partition_rules(self):
        """path-substring → PartitionSpec (consumed by shard_params)."""
        return [
            ("embed", P(None, "tp")),
            ("pos_embed", P(None, None)),
            ("wqkv", P("pp", None, "tp")),
            ("wo", P("pp", "tp", None)),
            ("ln1", P("pp", None)),
            ("ln2", P("pp", None)),
            ("w1", P("pp", None, "tp")),
            ("w2", P("pp", "tp", None)),
            ("ln_f", P(None)),
            ("moe/gate", P(None, None)),
            ("moe/w_in", P("ep", None, None)),
            ("moe/w_out", P("ep", None, None)),
        ]

    def spec_for(self, path):
        for frag, spec in self.partition_rules():
            if frag in path.replace("'", "").replace("][", "/"):
                return spec
        return P()

    def shard_params(self, params, mesh: Mesh):
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        out = []
        for path, leaf in flat:
            spec = self.spec_for(jax.tree_util.keystr(path))
            out.append(jax.device_put(leaf, NamedSharding(mesh, spec)))
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- forward ----------------------------------------------------------
    def _rmsnorm(self, x, g):
        ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                      keepdims=True)
        return (x.astype(jnp.float32) * lax.rsqrt(ms + 1e-6)).astype(x.dtype) * g

    def _attention(self, q, k, v, mesh):
        cfg = self.cfg
        if cfg.attention == "ring" and mesh is not None:
            return ring_attention(q, k, v, mesh, axis_name="sp", causal=True)
        if cfg.attention == "flash":
            from ..ops.pallas_kernels import flash_attention
            if mesh is not None and "sp" in mesh.axis_names \
                    and mesh.shape["sp"] > 1:
                # flash kernel is per-(b,h); sequence sharding needs the
                # ring schedule instead of an all-gather of K/V
                return ring_attention(q, k, v, mesh, axis_name="sp",
                                      causal=True)
            if mesh is not None:
                # keep batch/head shards local: run the kernel inside
                # shard_map so GSPMD doesn't all-gather q/k/v
                from jax.experimental.shard_map import shard_map
                spec = P("dp", "tp", None, None)
                fa = shard_map(
                    lambda q, k, v: flash_attention(q, k, v, causal=True),
                    mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
                return fa(q, k, v).astype(q.dtype)
            return flash_attention(q, k, v, causal=True).astype(q.dtype)
        logits = jnp.einsum("bhtd,bhsd->bhts", q, k,
                            preferred_element_type=jnp.float32)
        logits = logits / (cfg.head_dim ** 0.5)
        T, S = logits.shape[-2:]
        mask = jnp.tril(jnp.ones((T, S), bool))
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhts,bhsd->bhtd", probs, v)

    def _layer(self, lp, x, mesh):
        cfg = self.cfg
        B, T, D = x.shape
        H, dh = cfg.n_heads, cfg.head_dim
        h = self._rmsnorm(x, lp["ln1"])
        qkv = jnp.einsum("btd,de->bte", h, lp["wqkv"])
        if mesh is not None:
            qkv = lax.with_sharding_constraint(
                qkv, NamedSharding(mesh, P("dp", "sp", "tp")))
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, T, H, dh).transpose(0, 2, 1, 3)

        att = self._attention(heads(q), heads(k), heads(v), mesh)
        att = att.transpose(0, 2, 1, 3).reshape(B, T, D)
        x = x + jnp.einsum("btd,de->bte", att, lp["wo"])
        h = self._rmsnorm(x, lp["ln2"])
        ff = jax.nn.gelu(jnp.einsum("btd,df->btf", h, lp["w1"]))
        if mesh is not None:
            ff = lax.with_sharding_constraint(
                ff, NamedSharding(mesh, P("dp", "sp", "tp")))
        x = x + jnp.einsum("btf,fd->btd", ff, lp["w2"])
        return x

    def apply(self, params, tokens, mesh: Mesh | None = None):
        """tokens (B, T) int32 → logits (B, T, V)."""
        cfg = self.cfg
        B, T = tokens.shape
        x = params["embed"][tokens] + params["pos_embed"][:T][None]
        if mesh is not None:
            x = lax.with_sharding_constraint(
                x, NamedSharding(mesh, P("dp", "sp", None)))

        L = cfg.n_layers

        def body(x, lp):
            return self._layer(lp, x, mesh), None

        # lax.scan over the layer stack; the leading (L) axis of every
        # layer param is sharded over 'pp' (stage placement)
        x, _ = lax.scan(lambda carry, lp: (self._layer(lp, carry, mesh), None),
                        x, params["layers"])
        return self._head(params, x, mesh)

    def _head(self, params, x, mesh):
        cfg = self.cfg
        if cfg.use_moe:
            moe_out, aux = moe_forward(params["moe"], x)
            x = x + moe_out
        x = self._rmsnorm(x, params["ln_f"])
        logits = jnp.einsum("btd,vd->btv", x, params["embed"])
        if mesh is not None:
            logits = lax.with_sharding_constraint(
                logits, NamedSharding(mesh, P("dp", "sp", None)))
        return logits

    # -- pipelined forward (real pp schedule) -----------------------------
    def apply_pipelined(self, params, tokens, mesh: Mesh, n_micro: int):
        """tokens (B, T) → logits, via a microbatched circular pipeline.

        The GSPMD collective-permute pipelining pattern (GSPMD paper §3.4;
        scaling-book pipelining chapter): the layer stack is reshaped to
        (npp, L/npp, ...) with the stage axis sharded over 'pp'; a
        per-stage activation buffer advances one stage per step via
        ``jnp.roll`` on the stage-sharded axis, which XLA lowers to a
        collective-permute over the pp ring.  All stages compute every
        step (vmapped over the stage axis → SPMD over 'pp'); bubble-step
        garbage is never collected.  Because the schedule is plain
        scan+roll, ``jax.grad`` differentiates it into the reverse
        pipeline automatically — backward microbatches flow last→first
        stage with the transposed permute.  Replaces the reference's
        coarse group2ctx placement (graph_executor.cc:2048) with an
        actual overlap schedule.
        """
        cfg = self.cfg
        npp = mesh.shape["pp"]
        B, T = tokens.shape
        if B % n_micro:
            raise ValueError(
                f"n_micro ({n_micro}) must divide the batch size ({B})")
        L = cfg.n_layers
        if L % npp:
            raise ValueError(
                f"pp degree ({npp}) must divide n_layers ({L})")
        mb = B // n_micro

        x = params["embed"][tokens] + params["pos_embed"][:T][None]
        micro = x.reshape(n_micro, mb, T, cfg.d_model)
        micro = lax.with_sharding_constraint(
            micro, NamedSharding(mesh, P(None, "dp", "sp", None)))

        # (L, ...) → (npp, L/npp, ...), stage axis sharded over pp
        layers = jax.tree_util.tree_map(
            lambda a: lax.with_sharding_constraint(
                a.reshape(npp, L // npp, *a.shape[1:]),
                NamedSharding(mesh, P("pp", *([None] * a.ndim)))),
            params["layers"])

        def stage_apply(lp_stage, xb):
            """Run this stage's L/npp layers (no per-op sharding
            constraints here: specs can't follow the vmapped stage axis;
            GSPMD propagates tp/sp sharding from the param shardings)."""
            out, _ = lax.scan(
                lambda c, lp: (self._layer(lp, c, None), None), xb, lp_stage)
            return out

        buf = jnp.zeros((npp, mb, T, cfg.d_model), micro.dtype)
        outputs = jnp.zeros((n_micro, mb, T, cfg.d_model), micro.dtype)
        buf = lax.with_sharding_constraint(
            buf, NamedSharding(mesh, P("pp", "dp", "sp", None)))

        def step(carry, t):
            buf, outputs = carry
            inject = micro[jnp.clip(t, 0, n_micro - 1)]
            slot0 = jnp.where(t < n_micro, inject, buf[0])
            buf = lax.dynamic_update_index_in_dim(buf, slot0, 0, axis=0)
            new_buf = jax.vmap(stage_apply)(layers, buf)
            emit = t - (npp - 1)
            out_last = new_buf[npp - 1]
            outputs = jnp.where(
                (emit >= 0) & (emit < n_micro),
                lax.dynamic_update_index_in_dim(
                    outputs, out_last, jnp.clip(emit, 0, n_micro - 1), axis=0),
                outputs)
            # advance: stage i's output becomes stage i+1's input
            # (roll on the pp-sharded axis → collective-permute on ICI)
            buf = jnp.roll(new_buf, 1, axis=0)
            return (buf, outputs), None

        (buf, outputs), _ = lax.scan(step, (buf, outputs),
                                     jnp.arange(n_micro + npp - 1))
        x = outputs.reshape(B, T, cfg.d_model)
        x = lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("dp", "sp", None)))
        return self._head(params, x, mesh)

    # -- training ---------------------------------------------------------
    def loss_fn(self, params, tokens, mesh=None, n_micro=None):
        if n_micro is not None and mesh is not None:
            logits = self.apply_pipelined(params, tokens[:, :-1], mesh,
                                          n_micro)
        else:
            logits = self.apply(params, tokens[:, :-1], mesh)
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return jnp.mean(nll)

    def make_train_step(self, mesh: Mesh, lr=1e-3, n_micro=None,
                        donate=False):
        """SGD train step jitted over the mesh; GSPMD inserts the dp-psum
        for gradients and tp/sp/ep collectives for the sharded math.

        When the mesh has pp > 1, the forward (and its transposed
        backward) run the microbatched circular pipeline
        (``apply_pipelined``) instead of the scan-with-sharded-params
        stage fetch; n_micro defaults to 2*pp (bubble fraction
        (pp-1)/(2*pp+pp-1)) clamped to divide the batch at call time.

        ``donate=True`` donates the params (arg 0) so XLA writes the
        update in place — HBM for one param copy instead of two.  Only
        for callers that follow the ``params, loss = step(params,
        tokens)`` rebinding contract: ``shard_params`` may alias its
        input (``device_put`` is a no-op for already-placed arrays), so
        the pre-shard tree dies with the donated one.
        """
        pp = dict(mesh.shape).get("pp", 1)

        def step(params, tokens):
            nm = n_micro
            if pp > 1 and nm is None:
                # default 2*pp microbatches, clamped to a divisor of the
                # (statically known) batch so the pipeline always traces
                nm = min(2 * pp, tokens.shape[0])
                while tokens.shape[0] % nm:
                    nm -= 1
            loss, grads = jax.value_and_grad(
                lambda p: self.loss_fn(p, tokens, mesh,
                                       nm if pp > 1 else None))(params)
            new_params = jax.tree_util.tree_map(
                lambda p, g: (p.astype(jnp.float32)
                              - lr * g.astype(jnp.float32)).astype(p.dtype),
                params, grads)
            return new_params, loss

        token_sharding = NamedSharding(mesh, P("dp", None))
        return jax.jit(step, in_shardings=(None, token_sharding),
                       donate_argnums=(0,) if donate else ()), \
            token_sharding
