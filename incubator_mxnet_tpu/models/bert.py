"""BERT encoder (BASELINE config 3; gluon-nlp BERT lineage).

Gluon blocks over the fused attention op — covers the reference's
contrib BERT-era ops (src/operator/contrib/transformer.cc: interleaved
matmul self-attention) with one XLA-fused dot_product_attention.
"""
from __future__ import annotations

from .. import initializer as init_mod
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.parameter import Parameter
from ..ops.registry import invoke


class BERTSelfAttention(HybridBlock):
    def __init__(self, units, num_heads, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._heads = num_heads
        self.qkv = nn.Dense(3 * units, flatten=False, in_units=units)
        self.proj = nn.Dense(units, flatten=False, in_units=units)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x, mask=None):
        B, T, D = x.shape
        H = self._heads
        qkv = self.qkv(x)
        qkv = qkv.reshape((B, T, 3, H, D // H)).transpose((2, 0, 3, 1, 4))
        q, k, v = qkv[0], qkv[1], qkv[2]
        att_mask = None
        if mask is not None:
            att_mask = mask.reshape((B, 1, 1, T))
        out = invoke("dot_product_attention", q, k, v, *(
            [att_mask] if att_mask is not None else []))
        out = out.transpose((0, 2, 1, 3)).reshape((B, T, D))
        return self.dropout(self.proj(out))


class BERTEncoderLayer(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.attention = BERTSelfAttention(units, num_heads, dropout)
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.ffn1 = nn.Dense(hidden_size, flatten=False, in_units=units)
        self.ffn2 = nn.Dense(units, flatten=False, in_units=hidden_size)
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x, mask=None):
        x = self.ln1(x + self.attention(x, mask))
        h = self.ffn2(invoke("gelu", self.ffn1(x)))
        return self.ln2(x + self.dropout(h))


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        for i in range(num_layers):
            self.register_child(
                BERTEncoderLayer(units, hidden_size, num_heads, dropout),
                f"layer{i}")

    def forward(self, x, mask=None):
        for layer in self._children.values():
            x = layer(x, mask)
        return x


class BERTModel(HybridBlock):
    """Token+segment+position embeddings → encoder → MLM + NSP heads."""

    def __init__(self, vocab_size=30522, num_layers=12, units=768,
                 hidden_size=3072, num_heads=12, max_length=512,
                 type_vocab_size=2, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.word_embed = nn.Embedding(vocab_size, units)
        self.token_type_embed = nn.Embedding(type_vocab_size, units)
        self.pos_embed = Parameter("pos_embed", shape=(max_length, units),
                                   init=init_mod.Normal(0.02))
        self.embed_ln = nn.LayerNorm(in_channels=units)
        self.embed_dropout = nn.Dropout(dropout)
        self.encoder = BERTEncoder(num_layers, units, hidden_size, num_heads,
                                   dropout)
        self.pooler = nn.Dense(units, activation="tanh", in_units=units)
        self.mlm_decoder = nn.Dense(vocab_size, flatten=False, in_units=units)
        self.nsp_classifier = nn.Dense(2, in_units=units)

    def forward(self, tokens, token_types=None, valid_length=None):
        B, T = tokens.shape
        x = self.word_embed(tokens)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = x + self.pos_embed.data()[:T].expand_dims(0)
        x = self.embed_dropout(self.embed_ln(x))
        mask = None
        if valid_length is not None:
            from .. import ndarray as nd
            steps = nd.arange(0, T, ctx=tokens.ctx)
            mask = (steps.expand_dims(0) < valid_length.expand_dims(1))
        x = self.encoder(x, mask)
        pooled = self.pooler(x[:, 0])
        return self.mlm_decoder(x), self.nsp_classifier(pooled)
