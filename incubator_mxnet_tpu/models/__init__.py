"""Model families.

``transformer`` — the flagship TPU-native decoder LM with full 5-axis
(dp/pp/tp/sp/ep) sharding support; drives ``__graft_entry__.dryrun_multichip``.
``lstm_lm`` — LSTM language model (BASELINE config 5, reference example/rnn).
``bert`` — BERT-style encoder (BASELINE config 3, gluon-nlp lineage).
Vision models live in ``gluon.model_zoo.vision`` (reference layout).
"""
from . import transformer
from .transformer import TransformerLM, TransformerConfig
from .lstm_lm import LSTMLanguageModel
from .bert import BERTEncoder, BERTModel
