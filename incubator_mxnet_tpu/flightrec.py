"""Always-on flight recorder: the per-process operational black box.

Request-scoped tracing (:mod:`.trace`, PR 14) answers "where did THIS
request spend its time" — but it is head-sampled and request-shaped.
When a replica dies, the autoscaler makes a bad eviction, or a chaos
run leaves a wedged fleet, what explains the incident is the
*control-plane history* that preceded it: state transitions, scaling
ticks, quarantines, evictions, membership changes, compile storms.
This module is that record — the aviation black box next to the
cockpit voice recorder:

* **Always on** — a bounded ring of structured events
  ``(t, category, name, severity, fields, trace_id?)`` per process
  (``MXNET_FLIGHT_RING``, default 2048; ``0`` disables).  Emitters
  fire only on *operationally interesting* transitions (a healthy
  request appends nothing), so the steady-state cost is zero and the
  emit cost itself is one deque append (microbenched by
  ``serving_bench --flight-check``).
* **Categories** — ``lifecycle`` (process/replica/model state),
  ``scaling`` (autoscaler decisions + admin verbs), ``placement``
  (reservations/evictions under the HBM budget), ``health``
  (quarantine/readmit, failed hops, failover, hedging), ``fault``
  (every fired injection, mirroring the span event so chaos artifacts
  are self-explaining in BOTH systems), ``compile`` (executor builds,
  sentinel storms), ``checkpoint``, ``membership`` (PS join/leave/
  evict, trainer evict/rejoin, and the HA router tier's lease
  lifecycle: ``router.lease.acquired/renewed/expired``,
  ``router.lease.beat_lost``, ``router.takeover.started/completed``,
  ``router.forwarded``, ``router.exited`` — the chain
  ``router.lease.expired → router.takeover.started →
  session.restored`` is what ``tools/postmortem.py --gate`` asserts
  after a router kill), ``session``.
* **Monotonic-anchored** — event timestamps are monotonic
  (MX-TIME001); export places them on a shared cross-process timeline
  via :func:`.trace.anchor`, the ONE wall-clock anchor this process
  captured — flight dumps and trace dumps therefore merge onto the
  same timeline (``tools/postmortem.py``).
* **Dump triggers** — (a) a typed framework error crossing a server/
  router/trainer top-level boundary writes
  ``MXNET_FLIGHT_DIR/<proc>-<pid>.flight.json`` (rate-limited by
  ``MXNET_FLIGHT_DUMP_MIN_S``, best-effort, and NEVER masks the
  original error); (b) ``SIGUSR2`` dumps ring + all thread stacks +
  a metrics snapshot + recent trace ids — the "the process is wedged,
  tell me why" path; (c) ``GET /v1/flight`` on server and router for
  live inspection.

``tools/postmortem.py`` (stdlib, jax-free) merges any number of
flight + trace dumps into one causal timeline and reconstructs an
incident across processes (docs/observability.md "Flight recorder").
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from .base import get_env
from . import trace as _trace
from .locks import named_lock

__all__ = [
    "CATEGORIES", "EVENTS", "EVENT_PREFIXES",
    "LIFECYCLE", "SCALING", "PLACEMENT", "HEALTH",
    "FAULT", "COMPILE", "CHECKPOINT", "MEMBERSHIP", "SESSION",
    "Event", "enabled", "active", "configure", "reset", "record",
    "events", "stats", "health_block", "export", "export_json",
    "dump", "note_error", "install_signal_handler", "proc_name",
    "ring_capacity", "flight_dir", "dump_path",
]

LIFECYCLE = "lifecycle"
SCALING = "scaling"
PLACEMENT = "placement"
HEALTH = "health"
FAULT = "fault"
COMPILE = "compile"
CHECKPOINT = "checkpoint"
MEMBERSHIP = "membership"
SESSION = "session"

#: The closed category vocabulary — :func:`record` rejects anything
#: else (a typo'd category would silently shear the postmortem views).
CATEGORIES = (LIFECYCLE, SCALING, PLACEMENT, HEALTH, FAULT, COMPILE,
              CHECKPOINT, MEMBERSHIP, SESSION)
_CATEGORY_SET = frozenset(CATEGORIES)

#: The registered event-NAME vocabulary (mxlint MX-FLIGHT001).  Names
#: were free strings until a ``postmortem --gate`` list drifted from
#: its emitter and the mismatch surfaced only at chaos-stage runtime —
#: exactly the failure mode fault.POINTS closed for inject sites.  Now
#: every static ``record(category, "name")`` call in the linted
#: surface must name an entry here, and every gate string
#: (``postmortem --gate ev1,ev2`` argv or ``Incident(gate=...)``) must
#: too.  Keep the tuple sorted; an emitter with a new name adds its
#: row in the same PR.
EVENTS = (
    "bench.emit",
    "boundary.error",
    "checkpoint.fallback",
    "checkpoint.reshard",
    "checkpoint.restored",
    "checkpoint.save",
    "checkpoint.unrecoverable",
    "checkpoint.write_failed",
    "compile.storm",
    "executor.created",
    "fleet.rolling_reload",
    "lock.order_violation",
    "model.loaded",
    "model.unloaded",
    "model.unplaceable",
    "placer.blocked",
    "placer.evict",
    "replica.exited",
    "replica.quarantined",
    "replica.readmitted",
    "replica.state",
    "router.exited",
    "router.failover",
    "router.forwarded",
    "router.hedge_launched",
    "router.hedge_won",
    "router.hop_failed",
    "router.lease.acquired",
    "router.lease.beat_lost",
    "router.lease.expired",
    "router.lease.renewed",
    "router.scale_from_zero",
    "router.started",
    "router.takeover.completed",
    "router.takeover.started",
    "scale.apply",
    "scale.decide",
    "scale.dropped",
    "scale.failed",
    "scale.from_zero",
    "server.started",
    "session.created",
    "session.evicted",
    "session.lost",
    "session.migrated",
    "session.restored",
    "sigusr2.dump",
    "trainer.evicted",
    "trainer.rejoined",
    "worker.evicted",
    "worker.joined",
    "worker.left",
)

#: Prefix families for dynamically-formed names: ``fault.{point}``
#: (suffix validated against ``fault.POINTS`` — the two registries
#: compose) and ``fleet.{verb}`` (admin verbs fan out per call site).
EVENT_PREFIXES = ("fault.", "fleet.")

_SEVERITIES = frozenset(("info", "warn", "error"))


class Event:
    """One flight-recorder entry.  Immutable after construction; the
    ring stores these directly (no serialization on the emit path)."""

    __slots__ = ("t", "category", "name", "severity", "fields",
                 "trace_id")

    def __init__(self, t, category, name, severity, fields, trace_id):
        self.t = t                   # monotonic seconds
        self.category = category
        self.name = name
        self.severity = severity
        self.fields = fields         # dict or None
        self.trace_id = trace_id     # 16-hex id or None

    def __repr__(self):
        return (f"Event({self.category}:{self.name} "
                f"sev={self.severity} t={self.t:.3f})")


# ---------------------------------------------------------------------------
# configuration + ring
# ---------------------------------------------------------------------------

_lock = named_lock("flightrec.cfg")
_cfg = {"ring": None, "dir": None, "dump_min_s": None, "proc": None}
_provider_registered = False


def ring_capacity():
    n = _cfg["ring"]
    if n is None:
        n = _cfg["ring"] = max(
            0, get_env("MXNET_FLIGHT_RING", 2048, int))
    return n


def flight_dir():
    d = _cfg["dir"]
    if d is None:
        d = _cfg["dir"] = get_env("MXNET_FLIGHT_DIR", "") or ""
    return d or None


def dump_min_s():
    s = _cfg["dump_min_s"]
    if s is None:
        s = _cfg["dump_min_s"] = max(
            0.0, get_env("MXNET_FLIGHT_DUMP_MIN_S", 10.0, float))
    return s


def proc_name():
    """Process label in dumps/exports ("router", "server", ...).  Set
    by the serving CLIs (and :func:`configure`); defaults to the
    executable's role-agnostic ``proc``."""
    return _cfg["proc"] or "proc"


def enabled():
    """Recording on (``MXNET_FLIGHT_RING`` > 0 — the default)."""
    return ring_capacity() > 0


class _Ring:
    """Bounded event store.  The append path is deliberately LOCK-FREE:
    one ``deque.append`` (atomic under the GIL, maxlen evicts
    oldest-first in the same op) plus one counter bump.  No lock may
    sit on this path — the SIGUSR2 handler records too, and a handler
    blocking on a lock its interrupted thread holds would wedge the
    process the signal exists to diagnose.  The tradeoff: concurrent
    ``pushed += 1`` bumps can interleave at a bytecode boundary, so
    under heavy multi-thread emission the counter may run slightly
    LOW; eviction is derived (``pushed - len(ring)``, clamped at 0) —
    exact single-threaded, at-most-under concurrent."""

    __slots__ = ("cap", "_d", "pushed")

    def __init__(self, cap):
        self.cap = int(cap)
        self._d = deque(maxlen=self.cap)
        self.pushed = 0

    def push(self, ev):
        self._d.append(ev)
        self.pushed += 1

    def snapshot(self):
        return list(self._d)

    @property
    def evicted(self):
        return max(0, self.pushed - len(self._d))


_ring_obj = None

# dump bookkeeping (process-wide; reset() clears)
_dump_state = {
    "written": 0, "rate_limited": 0, "failures": 0,
    "sigusr2": 0, "sigusr2_dropped": 0,
    "last_dump_mono": None, "dumping": False,
}


def _ring():
    # LOCK-FREE first-use init (signal-path constraint, see _Ring):
    # two threads racing here build two rings and the first GIL-atomic
    # global assignment wins — the loser's ring (holding at most the
    # loser's own first event) is discarded.  Benign next to a handler
    # deadlocking on the module lock.
    global _ring_obj
    r = _ring_obj
    if r is None:
        r = _Ring(max(1, ring_capacity()))
        if _ring_obj is None:
            _ring_obj = r
        r = _ring_obj
    return r


def configure(ring=None, dir=None, proc=None, dump_min_s=None):
    """Programmatic override of the env knobs (tests, CLIs).  ``None``
    keeps the current value; changing ``ring`` re-allocates an empty
    ring (``0`` disables recording)."""
    global _ring_obj
    with _lock:
        if ring is not None:
            _cfg["ring"] = max(0, int(ring))
            _ring_obj = _Ring(max(1, _cfg["ring"]))
        if dir is not None:
            _cfg["dir"] = str(dir)
        if proc is not None:
            _cfg["proc"] = str(proc)
        if dump_min_s is not None:
            _cfg["dump_min_s"] = max(0.0, float(dump_min_s))


def reset():
    """Forget overrides, recorded events and dump counters; next use
    re-reads the env (test isolation)."""
    global _ring_obj
    with _lock:
        for k in _cfg:
            _cfg[k] = None
        _ring_obj = None
        _dump_state.update(written=0, rate_limited=0, failures=0,
                           sigusr2=0, sigusr2_dropped=0,
                           last_dump_mono=None, dumping=False)


def active():
    """Recording is observably on: enabled AND at least one event
    landed.  Gates the additive ``"flight"`` block in /healthz +
    describe() — a process that recorded nothing keeps its bare
    pinned shape."""
    return (enabled() and _ring_obj is not None
            and _ring_obj.pushed > 0)


def _ensure_provider():
    global _provider_registered
    if _provider_registered:
        return
    _provider_registered = True
    from . import profiler
    profiler.register_stats_provider("flight", stats)


# ---------------------------------------------------------------------------
# the emitter API
# ---------------------------------------------------------------------------

def record(category, name, severity="info", **fields):
    """Append one event to the ring — THE emitter call.

    Near-zero cost and exception-free by contract: emitters sit inside
    state machines (probe sweeps, PS command handlers, the autoscaler
    loop) that must never be broken by their own observability.  The
    category/severity vocabulary IS validated (a typo would silently
    shear every postmortem view), but that check is deterministic —
    any test that exercises the emitter catches it.

    ``trace_id`` may be passed explicitly in ``fields``; otherwise the
    active request trace (if any) is stamped on, linking the black box
    to the request-scoped layer."""
    if not enabled():
        return
    if category not in _CATEGORY_SET:
        raise ValueError(
            f"flightrec.record: unknown category {category!r} "
            f"(known: {', '.join(CATEGORIES)})")
    if severity not in _SEVERITIES:
        raise ValueError(
            f"flightrec.record: severity must be info|warn|error, "
            f"got {severity!r}")
    tid = fields.pop("trace_id", None) or _trace.current_trace_id()
    _ring().push(Event(time.monotonic(), category, name, severity,
                       fields or None, tid))
    _ensure_provider()


def events(category=None, name=None, severity=None):
    """Recorded events, oldest first, optionally filtered."""
    out = _ring().snapshot()
    if category is not None:
        out = [e for e in out if e.category == category]
    if name is not None:
        out = [e for e in out if e.name == name]
    if severity is not None:
        out = [e for e in out if e.severity == severity]
    return out


def stats():
    """The ``flight`` profiler stats provider."""
    r = _ring()
    return {
        "enabled": enabled(),
        "ring_capacity": ring_capacity(),
        "events_recorded": r.pushed,
        "events_in_ring": len(r._d),
        "events_evicted": r.evicted,
        "dumps_written": _dump_state["written"],
        "dumps_rate_limited": _dump_state["rate_limited"],
        "dump_failures": _dump_state["failures"],
        "sigusr2_dumps": _dump_state["sigusr2"],
        "sigusr2_dropped": _dump_state["sigusr2_dropped"],
    }


def health_block():
    """The additive ``"flight"`` block for /healthz + describe() —
    present only while :func:`active` (bare processes keep their
    pinned shape).  ``dumps`` counts dump FILES written (crash and
    SIGUSR2 alike — both go through :func:`dump`, which owns the
    counter; a stderr-fallback SIGUSR2 dump is not a file)."""
    r = _ring()
    return {"ring": ring_capacity(), "events": r.pushed,
            "evictions": r.evicted,
            "dumps": _dump_state["written"]}


# ---------------------------------------------------------------------------
# export + dumps
# ---------------------------------------------------------------------------

def _wall_us(t_mono):
    aw, am = _trace.anchor()
    return int((aw + (t_mono - am)) * 1e6)


def export(service=None, reason="inspect"):
    """The ring as one JSON-ready dict.  Event timestamps are exported
    in wall microseconds via the shared per-process anchor, so dumps
    from several processes merge onto one timeline
    (``tools/postmortem.py``)."""
    evs = []
    for e in _ring().snapshot():
        evs.append({
            "ts_us": _wall_us(e.t),
            "category": e.category,
            "name": e.name,
            "severity": e.severity,
            "fields": e.fields,
            "trace_id": e.trace_id,
        })
    r = _ring()
    return {
        "flight": 1,
        "proc": service or proc_name(),
        "pid": os.getpid(),
        "reason": reason,
        "dumped_ts_us": _wall_us(time.monotonic()),
        "ring": ring_capacity(),
        "recorded": r.pushed,
        "evicted": r.evicted,
        "events": evs,
    }


def export_json(service=None, reason="inspect"):
    return json.dumps(export(service, reason))


def dump_path(suffix=""):
    """``MXNET_FLIGHT_DIR/<proc>-<pid>[suffix].flight.json`` — or
    ``None`` when no dump directory is configured."""
    d = flight_dir()
    if d is None:
        return None
    return os.path.join(
        d, f"{proc_name()}-{os.getpid()}{suffix}.flight.json")


def dump(path=None, reason="manual", extra=None):
    """Write the ring to ``path`` (default :func:`dump_path`).
    Best-effort: ANY failure is swallowed and counted — a flight dump
    exists to explain errors, it must never add one.  Returns the
    path written, or ``None``."""
    path = path or dump_path()
    if path is None:
        return None
    payload = export(reason=reason)
    if extra:
        payload.update(extra)
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)   # a reader never sees a torn dump
    except Exception:  # mxlint: allow-broad-except(best-effort black-box write: a failed dump is counted, never surfaced — it must not mask the error being dumped)
        _dump_state["failures"] += 1
        return None
    _dump_state["written"] += 1
    return path


def note_error(boundary, error, message="", dump_now=True):
    """A typed framework error crossed a top-level boundary
    (server/router/trainer): record it, and write a rate-limited crash
    dump so the pre-error control-plane history survives the process.

    Never raises — the caller is about to surface the ORIGINAL error
    and nothing here may mask it."""
    try:
        err_name = (error if isinstance(error, str)
                    else type(error).__name__)
        record(LIFECYCLE, "boundary.error", severity="error",
               boundary=boundary, error=err_name,
               message=(message or (str(error)
                                    if not isinstance(error, str)
                                    else ""))[:200])
        if not dump_now or flight_dir() is None:
            return None
        now = time.monotonic()
        with _lock:
            last = _dump_state["last_dump_mono"]
            if last is not None and now - last < dump_min_s():
                _dump_state["rate_limited"] += 1
                return None
            _dump_state["last_dump_mono"] = now
        return dump(reason=f"error:{err_name}")
    except Exception:  # mxlint: allow-broad-except(the black box must never mask the typed error the caller is surfacing; a broken recorder is counted and ignored)
        _dump_state["failures"] += 1
        return None


# ---------------------------------------------------------------------------
# SIGUSR2: "the process is wedged, tell me why"
# ---------------------------------------------------------------------------

def _thread_stacks():
    """All thread stacks, formatted — the wedge diagnosis payload."""
    import sys
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, '?')} ({ident})"
        out[label] = traceback.format_stack(frame)
    return out


def _recent_trace_ids(limit=32):
    seen, out = set(), []
    for s in reversed(_trace.spans()):
        if s.trace_id not in seen:
            seen.add(s.trace_id)
            out.append(s.trace_id)
        if len(out) >= limit:
            break
    return out


def sigusr2_dump():
    """One wedge dump: ring + all thread stacks + a metrics snapshot +
    the recent trace ids.  Re-entrant-safe — a second signal while a
    dump is in flight is dropped and counted, never queued into a
    dump storm.

    Signal-path lock discipline: the handler runs on the main thread
    BETWEEN bytecodes of whatever it interrupted.  If that was a
    ``with _lock:`` section of this module (note_error's rate-limit
    window, configure()), a blocking acquire here would deadlock the
    process on its own diagnosis signal — so the acquire is
    non-blocking and a contended lock counts as a dropped signal."""
    if not _lock.acquire(blocking=False):
        # the interrupted thread (or a concurrent caller) holds the
        # module lock: bail out the same way a mid-dump signal does
        _dump_state["sigusr2_dropped"] += 1
        return None
    try:
        if _dump_state["dumping"]:
            _dump_state["sigusr2_dropped"] += 1
            return None
        _dump_state["dumping"] = True
    finally:
        _lock.release()
    try:
        extra = {"threads": _thread_stacks(),
                 "active_traces": _recent_trace_ids()}
        try:
            from . import profiler
            extra["metrics"] = json.loads(profiler.dumps(format="json"))
        except Exception:  # mxlint: allow-broad-except(a stats provider crashing must not lose the ring+stacks half of the wedge dump)
            extra["metrics"] = None
        record(LIFECYCLE, "sigusr2.dump",
               threads=len(extra["threads"]))
        path = dump_path(".sigusr2")
        if path is None:
            # no dump dir: the wedge report goes to stderr — losing it
            # entirely would defeat the signal's purpose
            import sys
            payload = export(reason="sigusr2")
            payload.update(extra)
            try:
                print(json.dumps(payload), file=sys.stderr, flush=True)
            except Exception:  # mxlint: allow-broad-except(stderr may be gone in a daemonized process; the dump is best-effort by contract)
                _dump_state["failures"] += 1
                return None
            _dump_state["sigusr2"] += 1
            return "<stderr>"
        written = dump(path, reason="sigusr2", extra=extra)
        if written is not None:
            # the FILE is counted by dump() ("written"); this counter
            # tracks sigusr2 dumps performed, file or stderr
            _dump_state["sigusr2"] += 1
        return written
    finally:
        # plain GIL-atomic store — no lock on the signal path
        _dump_state["dumping"] = False


def _handle_sigusr2(signum, frame):
    sigusr2_dump()


def install_signal_handler(proc=None):
    """Install the ``SIGUSR2`` wedge-dump handler (main thread only —
    the CLIs call this at startup).  Returns True when installed."""
    if proc is not None:
        configure(proc=proc)
    import signal
    if not hasattr(signal, "SIGUSR2"):
        return False
    if threading.current_thread() is not threading.main_thread():
        return False
    signal.signal(signal.SIGUSR2, _handle_sigusr2)
    return True
