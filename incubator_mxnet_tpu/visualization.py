"""Network visualization (reference python/mxnet/visualization.py)."""
from __future__ import annotations


def print_summary(symbol, shape=None):
    """Print a layer-by-layer summary of a Symbol graph."""
    nodes = symbol._topo_order()
    print(f"{'Layer':<30} {'Op':<20} {'Inputs'}")
    print("-" * 70)
    for node in nodes:
        inputs = ", ".join(i.name for i in node.inputs)
        print(f"{node.name:<30} {node.op_name or 'var':<20} {inputs}")
    print("-" * 70)
    print(f"Total nodes: {len(nodes)}")


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz plot; returns a graphviz.Digraph if graphviz is available."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("plot_network requires graphviz") from e
    dot = Digraph(name=title)
    for node in symbol._topo_order():
        if hide_weights and node.op_name is None and (
                node.name.endswith(("weight", "bias", "gamma", "beta"))):
            continue
        dot.node(node.name, f"{node.op_name or 'data'}\n{node.name}")
        for inp in node.inputs:
            if hide_weights and inp.op_name is None and (
                    inp.name.endswith(("weight", "bias", "gamma", "beta"))):
                continue
            dot.edge(inp.name, node.name)
    return dot
