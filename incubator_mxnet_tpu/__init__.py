"""incubator_mxnet_tpu — a TPU-native deep learning framework.

A from-scratch re-design of the capabilities of Apache MXNet (incubating)
for TPU hardware: JAX/XLA is the kernel generator and async runtime,
``jax.sharding`` + ``shard_map`` over a device ``Mesh`` is the distribution
substrate, and Pallas provides hand-written TPU kernels for the hot paths.

The public API mirrors the reference framework's Python surface
(``mx.nd``, ``mx.sym``, ``mx.gluon``, ``mx.autograd``, ``mx.optimizer``,
``mx.kvstore``, ``mx.io``) so that users of the reference can switch with
minimal friction, while the internals are idiomatic TPU-first designs —
not a port.  Reference: /root/reference (Apache MXNet), surveyed in
SURVEY.md at the repo root.

Typical use::

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, autograd, gluon

    x = nd.ones((2, 3), ctx=mx.tpu())
    with autograd.record():
        y = (x * 2).sum()
    y.backward()
"""

from .libinfo import __version__  # single source of truth


def _join_distributed_from_env():
    """Join the multi-process coordination service when launched by
    tools/launch.py (MXT_COORDINATOR / MXT_NUM_WORKERS / MXT_WORKER_ID —
    the role the ps-lite scheduler env plays for ``import mxnet`` in the
    reference).  Must run before ANY jax backend touch, hence at the top
    of the package import; PS-transport workers (MXT_SERVERS set) don't
    need a jax-level process group.
    """
    import os
    n = int(os.environ.get("MXT_NUM_WORKERS", "1"))
    coord = os.environ.get("MXT_COORDINATOR")
    if n <= 1 or not coord or os.environ.get("MXT_SERVERS"):
        return
    if os.environ.get("MXT_WORKER_ID_FROM_MPI") and \
            "MXT_WORKER_ID" not in os.environ:
        # mpi launcher (tools/launch.py launch_mpi): rank-dependent vars
        # can't ride mpirun -x, so derive the id from the MPI/PMI env
        for var in ("OMPI_COMM_WORLD_RANK", "PMIX_RANK", "PMI_RANK",
                    "SLURM_PROCID"):
            if var in os.environ:
                os.environ["MXT_WORKER_ID"] = os.environ[var]
                break
        else:
            raise RuntimeError(
                "MXT_WORKER_ID_FROM_MPI is set but no MPI rank variable "
                "(OMPI_COMM_WORLD_RANK/PMIX_RANK/PMI_RANK/SLURM_PROCID) "
                "is present")
    import jax
    try:
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=n,
            process_id=int(os.environ["MXT_WORKER_ID"]))
    except RuntimeError:
        pass  # backend already up (user initialized it themselves)


_join_distributed_from_env()


def _install_fork_handlers():
    """Fork safety for multiprocessing DataLoader workers (reference
    src/initialize.h:39-86 LibraryInitializer fork handlers): a forked
    child must not inherit the parent's engine lock state or reuse its
    PRNG stream."""
    import os

    def _after_fork_child():
        try:
            from . import engine
            engine.reset_engine()
        except Exception:  # mxlint: allow-broad-except(post-fork reinit is best-effort; a failure must not kill the child)
            pass
        try:
            from . import random as _random
            _random.seed(int.from_bytes(os.urandom(4), "little"))
        except Exception:  # mxlint: allow-broad-except(post-fork reseed is best-effort; a failure must not kill the child)
            pass

    if hasattr(os, "register_at_fork"):
        os.register_at_fork(after_in_child=_after_fork_child)


_install_fork_handlers()

from . import base
from .base import MXNetError
from . import error
from . import fault
from . import trace
from . import libinfo
from . import log
from . import checkpoint
from .context import (Context, cpu, gpu, tpu, current_context, num_gpus,
                      num_tpus, gpu_memory_info, tpu_memory_info,
                      memory_summary)
from . import engine
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import autograd
from . import random
from . import initializer
from . import init  # alias namespace like mx.init
from . import optimizer
from .optimizer import lr_scheduler
from . import symbol
from . import symbol as sym
from . import model
from . import module
from . import module as mod
from . import gluon
from . import kvstore
from . import kvstore as kv
from . import io
from . import recordio
from . import image
from . import parallel
from . import models
from . import profiler
from . import runtime
from . import amp
from . import contrib
from . import operator
from . import subgraph
from . import numpy as np  # mx.np NumPy-compatible namespace
from . import numpy_extension as npx
from . import callback
from . import monitor
from . import visualization as viz
from . import test_utils
from . import util
from . import library
from . import rtc
from . import executor_cache
from . import deploy
from . import serving
from .util import is_np_array, set_np, reset_np
from .attribute import AttrScope
from .name import NameManager

# Convenience re-exports matching the reference's top level (mx.nd.array,
# mx.metric, ...).
from .gluon import metric


def tpu_context_available():
    """True when a real TPU backend is attached to this process."""
    return num_tpus() > 0
