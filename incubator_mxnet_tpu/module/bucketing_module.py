"""BucketingModule: variable-length training via per-bucket executors.

Reference: python/mxnet/module/bucketing_module.py — one executor per
sequence-length bucket, all sharing weights.  TPU re-design: each bucket
is a separate XLA compilation (jit cache keyed on shape — exactly the
recompilation-avoidance policy SURVEY.md §5.7 maps bucketing onto);
parameters are shared by copying the master module's arrays into each
bucket module at switch time (arrays are device buffers — sharing is by
reference, no host copies).
"""
from __future__ import annotations

from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=None,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets: dict = {}
        self._curr_module: Module | None = None
        self._curr_bucket_key = None
        self._grad_req = "write"

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        sym, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        sym, _, _ = self._call_sym_gen(self._default_bucket_key)
        return sym.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    def _call_sym_gen(self, bucket_key):
        return self._sym_gen(bucket_key)

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._call_sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      work_load_list=self._work_load_list,
                      fixed_param_names=self._fixed_param_names,
                      state_names=self._state_names)

    # -- bind / params ----------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Switch to (creating if needed) the module for bucket_key."""
        assert self.binded, "call bind before switching buckets"
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad, force_rebind=False,
                        grad_req=self._grad_req)
            if self.params_initialized:
                arg_params, aux_params = self.get_params()
                module.init_params(arg_params=arg_params,
                                   aux_params=aux_params, force_init=True,
                                   allow_missing=False)
                if self._curr_module.optimizer_initialized:
                    module._optimizer = self._curr_module._optimizer
                    module._updater = self._curr_module._updater
                    module._kvstore = self._curr_module._kvstore
                    module._update_on_kvstore = \
                        self._curr_module._update_on_kvstore
                    module.optimizer_initialized = True
            self._buckets[bucket_key] = module
        else:
            module = self._buckets[bucket_key]
            if self.params_initialized:
                arg_params, aux_params = self.get_params()
                module.init_params(arg_params=arg_params,
                                   aux_params=aux_params, force_init=True)
        self._curr_module = module
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        assert self.binded
        if self.params_initialized and not force_init:
            return
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init,
                                      allow_extra=allow_extra)
        self.params_initialized = True

    def get_params(self):
        assert self.params_initialized
        return self._curr_module.get_params()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        self.optimizer_initialized = True

    # -- compute ----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        bucket_key = getattr(data_batch, "bucket_key",
                             self._default_bucket_key)
        self.switch_bucket(bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        assert self.optimizer_initialized or \
            self._curr_module.optimizer_initialized
        self._curr_module.update()
        # propagate updated params so other buckets see them at switch
        arg_params, aux_params = self._curr_module.get_params()
        self._curr_module._arg_params = arg_params
        self._curr_module._aux_params = aux_params

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(
            merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(
            merge_multi_context=merge_multi_context)
