"""Legacy symbolic Module API (reference python/mxnet/module/).

``Module`` wraps a Symbol with contexts, parameters and an optimizer;
``BucketingModule`` adds per-bucket executors for variable-length data.
Executors are whole-graph XLA programs; multi-context data parallelism
slices the batch and sums gradients (DataParallelExecutorGroup), while
scale-out training should use the kvstore/pjit substrate in
``incubator_mxnet_tpu.parallel``.
"""
from .base_module import BaseModule
from .module import Module
from .bucketing_module import BucketingModule
from .executor_group import DataParallelExecutorGroup, decide_slices

__all__ = ["BaseModule", "Module", "BucketingModule",
           "DataParallelExecutorGroup", "decide_slices"]
