"""Data-parallel executor group: one compiled executor per context.

Reference: python/mxnet/module/executor_group.py (DataParallelExecutorGroup
:144, decide_slices :282, forward :445, backward :581).  TPU re-design:
each context's executor is one whole-graph XLA program (Symbol.simple_bind
→ jax.jit), so "bulking"/memory planning are XLA's job; batch slicing and
gradient summation across the context list are kept so legacy
multi-device Module scripts run unchanged.  For real TPU scale-out the
kvstore/pjit path (incubator_mxnet_tpu.parallel) is preferred.
"""
from __future__ import annotations

import numpy as onp

import jax.numpy as jnp

from ..context import current_context
from ..ndarray import NDArray
from .. import ndarray as nd

__all__ = ["DataParallelExecutorGroup", "decide_slices"]


def decide_slices(batch_size, num_ctx, workload=None):
    """Split [0, batch_size) into per-context slices (reference :282)."""
    if workload is None:
        workload = [1] * num_ctx
    assert len(workload) == num_ctx
    total = sum(workload)
    sizes = [batch_size * w // total for w in workload]
    # distribute remainder to the first contexts
    rem = batch_size - sum(sizes)
    for i in range(rem):
        sizes[i % num_ctx] += 1
    slices = []
    start = 0
    for s in sizes:
        slices.append(slice(start, start + s))
        start += s
    return slices


def _slice_array(arr, slc):
    data = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
    return data[slc]


class DataParallelExecutorGroup:
    """Binds a symbol on every context with the batch sliced along axis 0."""

    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad=False,
                 shared_group=None, fixed_param_names=None, grad_req="write",
                 state_names=None):
        self.symbol = symbol
        self.contexts = list(contexts) if contexts else [current_context()]
        self.param_names = list(param_names)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.data_shapes = list(data_shapes)
        self.label_shapes = list(label_shapes) if label_shapes else None
        self.data_names = [d[0] if isinstance(d, (tuple, list)) else d.name
                           for d in self.data_shapes]
        self.label_names = ([l[0] if isinstance(l, (tuple, list)) else l.name
                             for l in self.label_shapes]
                            if self.label_shapes else [])
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()

        batch_size = self._shape_of(self.data_shapes[0])[0]
        self.batch_size = batch_size
        self.slices = decide_slices(batch_size, len(self.contexts), workload)

        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in self.arg_names}
        for n in self.arg_names:
            if n in self.fixed_param_names or (
                    n in self.data_names and not inputs_need_grad) or (
                    n in self.label_names):
                grad_req[n] = "null"
        self.grad_req = grad_req

        self.execs = []
        for ctx, slc in zip(self.contexts, self.slices):
            kwargs = {}
            for d in self.data_shapes:
                name, shape = self._name_shape(d)
                kwargs[name] = (slc.stop - slc.start,) + tuple(shape[1:])
            if self.label_shapes:
                for l in self.label_shapes:
                    name, shape = self._name_shape(l)
                    kwargs[name] = (slc.stop - slc.start,) + tuple(shape[1:])
            # params: shape comes from infer or must be provided by caller
            ex = self._bind_one(ctx, kwargs)
            self.execs.append(ex)

    @staticmethod
    def _shape_of(desc):
        return tuple(desc[1] if isinstance(desc, (tuple, list)) else desc.shape)

    @staticmethod
    def _name_shape(desc):
        if isinstance(desc, (tuple, list)):
            return desc[0], tuple(desc[1])
        return desc.name, tuple(desc.shape)

    def _bind_one(self, ctx, input_shapes):
        # simple_bind performs backward shape inference (param shapes from
        # data shapes) via Symbol._infer_args_from
        return self.symbol.simple_bind(ctx=ctx, grad_req=self.grad_req,
                                       **input_shapes)

    # -- parameters -------------------------------------------------------
    def set_params(self, arg_params, aux_params=None, allow_extra=False):
        for ex in self.execs:
            for name, val in arg_params.items():
                if name in ex.arg_dict:
                    ex.arg_dict[name]._set_data(
                        val.data if isinstance(val, NDArray) else
                        jnp.asarray(val))
                elif not allow_extra:
                    raise ValueError(f"unknown parameter {name}")
            if aux_params:
                for name, val in aux_params.items():
                    if name in getattr(ex, "aux_dict", {}):
                        ex.aux_dict[name]._set_data(
                            val.data if isinstance(val, NDArray) else
                            jnp.asarray(val))

    def get_params(self, arg_params, aux_params):
        """Copy current (first-executor) params out (reference :350)."""
        ex = self.execs[0]
        for name in self.param_names:
            if name in ex.arg_dict:
                arg_params[name] = NDArray(ex.arg_dict[name].data)
        for name, val in getattr(ex, "aux_dict", {}).items():
            aux_params[name] = NDArray(val.data)

    # -- compute ----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        data = data_batch.data
        label = getattr(data_batch, "label", None)
        for ex, slc in zip(self.execs, self.slices):
            feed = {}
            for name, arr in zip(self.data_names, data):
                feed[name] = _slice_array(arr, slc)
            if label is not None and self.label_names:
                for name, arr in zip(self.label_names, label):
                    feed[name] = _slice_array(arr, slc)
            ex.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        assert self.for_training, "re-bind with for_training=True"
        for i, (ex, slc) in enumerate(zip(self.execs, self.slices)):
            og = out_grads
            if og is not None:
                og = [_slice_array(g, slc) for g in
                      (og if isinstance(og, (list, tuple)) else [og])]
            ex.backward(out_grads=og)

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def get_outputs(self, merge_multi_context=True):
        all_outs = [ex.outputs for ex in self.execs]
        if not merge_multi_context:
            return all_outs
        n_out = len(all_outs[0])
        merged = []
        for i in range(n_out):
            parts = [outs[i].data for outs in all_outs]
            merged.append(NDArray(jnp.concatenate(parts, axis=0)
                                  if len(parts) > 1 else parts[0]))
        return merged

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        grads = []
        for name in self.data_names:
            parts = [ex.grad_dict[name].data for ex in self.execs
                     if ex.grad_dict.get(name) is not None]
            grads.append(NDArray(jnp.concatenate(parts, axis=0)
                                 if len(parts) > 1 else parts[0]))
        return grads

    def grad_arrays_for(self, name):
        """Per-context gradient buffers for one parameter."""
        return [ex.grad_dict[name] for ex in self.execs
                if ex.grad_dict.get(name) is not None]

    def sum_grad(self, name):
        """Sum gradients for `name` across contexts (local allreduce)."""
        bufs = self.grad_arrays_for(name)
        if not bufs:
            return None
        total = bufs[0].data
        for b in bufs[1:]:
            total = total + b.data
        return NDArray(total)

    def update_metric(self, eval_metric, labels):
        for ex, slc in zip(self.execs, self.slices):
            lbl = [NDArray(_slice_array(l, slc)) for l in labels]
            eval_metric.update(lbl, ex.outputs)
