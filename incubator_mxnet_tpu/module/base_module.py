"""BaseModule: the legacy symbolic training-loop interface.

Reference: python/mxnet/module/base_module.py (BaseModule.fit/score/
predict).  The intermediate-level API contract is preserved —
``bind → init_params → init_optimizer → per batch: forward_backward,
update, update_metric`` — so reference training scripts port directly;
underneath, every executor is one whole-graph XLA program.
"""
from __future__ import annotations

import logging
import time

from .. import initializer as _initializer
from .. import optimizer as _opt
from ..gluon import metric as _metric
from ..io import DataDesc
from ..ndarray import NDArray

__all__ = ["BaseModule"]


class BaseModule:
    def __init__(self, logger=None):
        self.logger = logger or logging.getLogger(__name__)
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.inputs_need_grad = False
        self._symbol = None

    # -- abstract interface ----------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    # -- composite helpers (reference base_module.py) ---------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, reset=True, epoch=0):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if isinstance(eval_metric, str):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(_BatchEndParam(epoch, nbatch, eval_metric, locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            outs = self.get_outputs()
            if eval_batch.pad:
                outs = [NDArray(o.data[:o.shape[0] - eval_batch.pad])
                        for o in outs]
            output_list.append(outs)
        if not output_list:
            return []
        if merge_batches:
            import jax.numpy as jnp
            n_out = len(output_list[0])
            merged = [NDArray(jnp.concatenate(
                [outs[i].data for outs in output_list], axis=0))
                for i in range(n_out)]
            if n_out == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """The reference's one-call training loop (base_module.py fit)."""
        assert num_epoch is not None, "please specify num_epoch"
        if initializer is None:
            initializer = _initializer.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if isinstance(eval_metric, str):
            eval_metric = _metric.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.monotonic()
            eval_metric.reset()
            for nbatch, data_batch in enumerate(train_data):
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(_BatchEndParam(epoch, nbatch, eval_metric,
                                          locals()))
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.monotonic() - tic)
            arg_p, aux_p = self.get_params()
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
            train_data.reset()

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import save_checkpoint
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self.symbol, arg_params, aux_params)

    @staticmethod
    def _parse_data_desc(data_shapes):
        out = []
        for d in data_shapes or []:
            if isinstance(d, DataDesc):
                out.append((d.name, tuple(d.shape)))
            elif isinstance(d, (tuple, list)):
                out.append((d[0], tuple(d[1])))
            else:
                raise TypeError(f"bad data desc {d!r}")
        return out


class _BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric, lcls):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = lcls


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
