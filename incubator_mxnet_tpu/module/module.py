"""Module: symbol + contexts + parameters + optimizer state.

Reference: python/mxnet/module/module.py.  TPU re-design: binding builds
a DataParallelExecutorGroup whose per-context executors are whole-graph
XLA programs; `update` runs the optimizer's Updater over summed
gradients (a local allreduce), or pushes through a kvstore when one is
given — the same contract as the reference (model.py:87 decides
update_on_kvstore).
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import initializer as _initializer
from .. import optimizer as _opt
from ..context import current_context
from ..ndarray import NDArray
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=None, context=None,
                 work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        if context is None:
            context = [current_context()]
        if not isinstance(context, (list, tuple)):
            context = [context]
        self._context = list(context)
        self._work_load_list = work_load_list
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._arg_params = None
        self._aux_params = None
        self._exec_group = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._update_on_kvstore = False
        self._data_shapes = None
        self._label_shapes = None

    # -- properties -------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = self._exec_group.execs[0].outputs
        if outs:
            return list(zip(self.output_names, [o.shape for o in outs]))
        return None

    # -- bind -------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = self._parse_data_desc(data_shapes)
        self._label_shapes = (self._parse_data_desc(label_shapes)
                              if label_shapes else None)
        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad=inputs_need_grad,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req)
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            arg_params, aux_params = shared_module.get_params()
            self.init_params(arg_params=arg_params, aux_params=aux_params,
                             force_init=True)

    # -- params -----------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        assert self.binded, "call bind before init_params"
        if self.params_initialized and not force_init:
            return
        if initializer is None and (arg_params is None):
            initializer = _initializer.Uniform(0.01)
        ex = self._exec_group.execs[0]
        self._arg_params = {}
        self._aux_params = {}
        for name in self._param_names:
            buf = NDArray(ex.arg_dict[name].data)
            if arg_params is not None and name in arg_params:
                src = arg_params[name]
                buf._set_data(src.data if isinstance(src, NDArray)
                              else jnp.asarray(src))
            elif initializer is not None:
                initializer(name, buf)
            elif not allow_missing:
                raise ValueError(f"no value for parameter {name}")
            self._arg_params[name] = buf
        for name in self._aux_names:
            buf = NDArray(ex.aux_dict[name].data)
            if aux_params is not None and name in aux_params:
                src = aux_params[name]
                buf._set_data(src.data if isinstance(src, NDArray)
                              else jnp.asarray(src))
            self._aux_params[name] = buf
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=True)
        self.params_initialized = True

    def get_params(self):
        assert self.params_initialized
        # aux states live in the executors (updated by BN forward)
        self._exec_group.get_params(self._arg_params, self._aux_params)
        return self._arg_params, self._aux_params

    # -- optimizer --------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        kv = kvstore
        if isinstance(kv, str):
            from ..kvstore import create as kv_create
            kv = kv_create(kv) if kv else None
        if isinstance(optimizer, str):
            params = dict(optimizer_params)
            # reference module.py:506-518: a string optimizer gets
            # rescale_grad = 1/(batch_size * num_workers) injected unless
            # the caller set it — layer grads are batch sums, so without
            # this the effective lr scales with batch size
            if "rescale_grad" not in params and self._data_shapes:
                batch = self._data_shapes[0][1][0]
                # num_workers enters only for dist-SYNC stores (reference
                # guard `'dist' in type and '_sync' in type`): sync sums
                # pushes across workers, async applies each push alone
                nworkers = 1
                if kv is not None and "dist" in getattr(kv, "type", "") \
                        and "sync" in kv.type:
                    nworkers = kv.num_workers
                params["rescale_grad"] = 1.0 / (batch * nworkers)
            optimizer = _opt.create(optimizer, **params)
        optimizer.param_idx2name = {i: n
                                    for i, n in enumerate(self._param_names)}
        self._optimizer = optimizer
        self._updater = _opt.get_updater(optimizer)
        self._kvstore = kv
        if kv is not None and getattr(kv, "is_capable", None) and \
                kv.is_capable("optimizer"):
            try:
                kv.set_optimizer(optimizer)
                self._update_on_kvstore = True
            except (NotImplementedError, AttributeError):
                self._update_on_kvstore = False
        if kv is not None:
            for i, name in enumerate(self._param_names):
                kv.init(i, self._arg_params[name])
        self.optimizer_initialized = True

    # -- compute ----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self._exec_group.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """Apply one optimizer step over context-summed gradients."""
        assert self.optimizer_initialized
        for i, name in enumerate(self._param_names):
            grad = self._exec_group.sum_grad(name)
            if grad is None:
                continue
            weight = self._arg_params[name]
            if self._kvstore is not None and self._update_on_kvstore:
                self._kvstore.push(i, grad)
                self._kvstore.pull(i, out=weight)
            elif self._kvstore is not None:
                self._kvstore.push(i, grad)
                agg = self._kvstore.pull(i)
                self._updater(i, agg if agg is not None else grad, weight)
            else:
                self._updater(i, grad, weight)
        self._exec_group.set_params(self._arg_params, allow_extra=True)

    def update_metric(self, eval_metric, labels):
        self._exec_group.update_metric(eval_metric, labels)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded
        return self._exec_group.get_outputs(
            merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        return self._exec_group.get_input_grads(
            merge_multi_context=merge_multi_context)

    def reshape(self, data_shapes, label_shapes=None):
        """Re-bind for new batch shapes, keeping parameters."""
        arg_params, aux_params = self.get_params()
        self.bind(data_shapes, label_shapes, for_training=self.for_training,
                  inputs_need_grad=self.inputs_need_grad, force_rebind=True)
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         force_init=True)

    def load_optimizer_states(self, fname):
        if self._updater is not None:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def save_optimizer_states(self, fname):
        if self._updater is not None:
            with open(fname, "wb") as f:
                f.write(self._updater.get_states())
