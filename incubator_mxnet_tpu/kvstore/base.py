"""KVStoreBase plugin registry (reference python/mxnet/kvstore/base.py)."""
from __future__ import annotations

__all__ = ["KVStoreBase", "register", "create"]

_KVSTORES: dict[str, type] = {}


def register(klass):
    """Register a kvstore implementation under its OPT_TYPES names."""
    names = getattr(klass, "OPT_TYPES", [klass.__name__.lower()])
    for n in names:
        _KVSTORES[n.lower()] = klass
    return klass


def create(name="local", **kwargs):
    """Create a kvstore by type string (reference kvstore.py:54 create).

    Types mirror the reference factory (src/kvstore/kvstore.cc:41-84):
    local | device | nccl(→device) | dist_sync | dist_device_sync |
    dist_async | p3 — plus any plugin registered via ``register``.
    """
    name = name.lower()
    if name not in _KVSTORES:
        raise ValueError(
            f"unknown kvstore type {name!r}; known: {sorted(_KVSTORES)}")
    return _KVSTORES[name](**kwargs)


class KVStoreBase:
    """Capability-queryable interface (reference base.py:74)."""

    OPT_TYPES: list[str] = []

    # capability flags (reference base.py is_capable)
    OPTIMIZER = "optimizer"
    PUSH_PULL = "push_pull"

    @staticmethod
    def is_capable(capability):
        return False

    @property
    def type(self):
        return type(self).OPT_TYPES[0]

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    @property
    def live_workers(self):
        """Current live fleet size; equals ``num_workers`` unless the
        store carries an elastic membership table (DistKVStore over the
        PS transport)."""
        return self.num_workers

    def join(self, rank=None):
        """Enter an elastic fleet's membership table (no-op for stores
        without membership)."""
        return None

    def leave(self):
        """Gracefully exit an elastic fleet (no-op without membership)."""
        return None

    def beat(self):
        """Membership heartbeat (no-op without membership)."""
        return None

    def init(self, key, value):
        raise NotImplementedError

    def push(self, key, value, priority=0):
        raise NotImplementedError

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out=out, priority=priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out=out, priority=priority)

    def set_optimizer(self, optimizer):
        raise NotImplementedError

    def set_gradient_compression(self, compression_params):
        raise NotImplementedError

    def barrier(self):
        pass

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise NotImplementedError

    def load_optimizer_states(self, fname):
        raise NotImplementedError
