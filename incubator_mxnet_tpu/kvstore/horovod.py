"""Horovod kvstore adapter (reference python/mxnet/kvstore/horovod.py:31-126).

A real plugin through the KVStoreBase registry: broadcast/pushpull map
onto ``horovod.mxnet``'s allreduce/broadcast when Horovod is installed.
Horovod has no TPU backend, so on this stack the adapter exists to prove
the extension point extends (reference base.py:74 registry contract) and
to run on CPU/GPU clusters where Horovod is present; construction fails
with a clear error otherwise instead of silently aliasing to dist_sync
(the round-2 behavior this replaces).
"""
from __future__ import annotations

from .base import KVStoreBase, register


@register
class HorovodKVStore(KVStoreBase):
    """kv.create('horovod') — allreduce-based, no servers."""

    OPT_TYPES = ["horovod"]

    def __init__(self):
        try:
            import horovod.mxnet as hvd
        except ImportError as e:
            raise ImportError(
                "kvstore type 'horovod' needs the horovod package "
                "(pip install horovod); for TPU data parallelism use "
                "kv.create('device') or kv.create('dist_sync') — XLA "
                "collectives over ICI play Horovod's role there") from e
        self._hvd = hvd
        hvd.init()

    @staticmethod
    def is_capable(capability):
        # allreduce path: optimizer stays worker-side
        return capability == KVStoreBase.PUSH_PULL

    @property
    def rank(self):
        return self._hvd.rank()

    @property
    def num_workers(self):
        return self._hvd.size()

    def init(self, key, value):
        pass  # nothing to initialize server-side

    def broadcast(self, key, value, out, priority=0):
        outs = out if isinstance(out, (list, tuple)) else [out]
        res = self._hvd.broadcast(tensor=value, root_rank=0, name=str(key),
                                  priority=priority)
        for o in outs:
            o[:] = res

    def push(self, key, value, priority=0):
        raise NotImplementedError(
            "horovod kvstore is allreduce-based: use pushpull "
            "(reference horovod.py raises the same)")

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        raise NotImplementedError(
            "horovod kvstore is allreduce-based: use pushpull")

    def pushpull(self, key, value, out=None, priority=0):
        hvd = self._hvd
        if out is None:
            values = value if isinstance(value, (list, tuple)) else [value]
            for v in values:
                hvd.allreduce_(v, average=False, name=str(key),
                               priority=priority)
        else:
            outs = out if isinstance(out, (list, tuple)) else [out]
            res = hvd.allreduce(value, average=False, name=str(key),
                                priority=priority)
            for o in outs:
                o[:] = res

    def set_optimizer(self, optimizer):
        raise NotImplementedError(
            "horovod has no server-side optimizer; update locally")

    def set_gradient_compression(self, compression_params):
        raise NotImplementedError(
            "use horovod's own compression knobs")


@register
class BytePSKVStore(KVStoreBase):
    """kv.create('byteps') (reference python/mxnet/kvstore/byteps.py:29)."""

    OPT_TYPES = ["byteps"]

    def __init__(self):
        try:
            import byteps.mxnet as bps
        except ImportError as e:
            raise ImportError(
                "kvstore type 'byteps' needs the byteps package; for TPU "
                "use kv.create('dist_sync') (XLA collectives) or "
                "kv.create('dist_async') (parameter server)") from e
        self._bps = bps
        bps.init()

    @staticmethod
    def is_capable(capability):
        return capability == KVStoreBase.PUSH_PULL

    @property
    def rank(self):
        return self._bps.rank()

    @property
    def num_workers(self):
        return self._bps.size()

    def init(self, key, value):
        pass

    def broadcast(self, key, value, out, priority=0):
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._bps.byteps_declare_tensor(str(key))
        for o in outs:
            o[:] = value
            self._bps.byteps_push_pull(o, name=str(key), is_average=False,
                                       priority=priority)

    def push(self, key, value, priority=0):
        raise NotImplementedError("byteps kvstore: use pushpull")

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        raise NotImplementedError("byteps kvstore: use pushpull")

    def pushpull(self, key, value, out=None, priority=0):
        bps = self._bps
        tensors = value if isinstance(value, (list, tuple)) else [value]
        for t in tensors:
            bps.byteps_push_pull(t, name=str(key), is_average=False,
                                 priority=priority)
        if out is not None:
            outs = out if isinstance(out, (list, tuple)) else [out]
            for o, t in zip(outs, tensors):
                o[:] = t

    def set_optimizer(self, optimizer):
        raise NotImplementedError("byteps has no server-side optimizer here")

    def set_gradient_compression(self, compression_params):
        raise NotImplementedError("use byteps' own compression")
