"""Parameter-server process: the dist_async / dist_sync server role.

TPU-native re-design of the reference's ps-lite server
(src/kvstore/kvstore_dist_server.h:155-359): a standalone process
holding the authoritative weights, applying updates as workers push.

* ``sync`` mode — aggregates exactly ``num_workers`` pushes per key,
  then applies the merged gradient once (server optimizer if set, else
  plain accumulate); pulls for that key block until the round completes
  (DataHandleDefault + ApplyUpdates semantics,
  kvstore_dist_server.h:325-359).
* ``async`` mode — every push is applied immediately and independently;
  no aggregation, no round barrier: workers race exactly like the
  reference's async mode (DataHandleDefault else-branch :349).

Transport is a length-prefixed pickle protocol over TCP on localhost /
DCN — the role ps-lite's ZMQ Van plays (SURVEY.md §5.8), chosen over
gRPC to keep the runtime dependency-free.  The server is pure
CPU/numpy: it never touches an accelerator, mirroring the reference
where servers are CPU processes.

Wire protocol: request = (cmd, key, payload); response = (ok, payload).
Commands: init, push, pull, set_optimizer, barrier, num_done, stop.
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading

import numpy as onp

__all__ = ["PSServer", "PSClient", "serve_forever"]


def _send_msg(sock, obj):
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(data)) + data)


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return pickle.loads(bytes(buf))


class _State:
    """Server-side store + sync-round bookkeeping."""

    def __init__(self, mode, num_workers):
        self.mode = mode
        self.num_workers = num_workers
        self.store: dict = {}
        self.merge: dict = {}           # key -> (accum, count) for sync
        self.round_done: dict = {}      # key -> round counter
        self.updater = None
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.barrier_count = 0
        self.barrier_gen = 0

    def apply_update(self, key, grad):
        if self.updater is not None:
            w = self.store[key]
            self.updater(key, grad, w)   # in-place numpy update
        elif self.mode == "async":
            # reference: "Updater needs to be set for async mode"
            # (kvstore_dist_server.h:360 CHECK)
            raise RuntimeError(
                "async parameter server requires a server-side optimizer: "
                "call kv.set_optimizer(...) before pushing")
        else:
            # sync without updater: the stored value becomes the merged
            # push (kvstore_dist_server.h:362 CopyFromTo)
            self.store[key] = onp.array(grad)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        st: _State = self.server.state  # type: ignore[attr-defined]
        sock = self.request
        try:
            while True:
                cmd, key, payload = _recv_msg(sock)
                if cmd == "stop":
                    _send_msg(sock, (True, None))
                    threading.Thread(
                        target=self.server.shutdown, daemon=True).start()
                    return
                try:
                    resp = self._dispatch(st, cmd, key, payload)
                except Exception as e:  # surfaced client-side as an error
                    resp = (False, str(e))
                _send_msg(sock, resp)
        except (ConnectionError, OSError):
            return

    @staticmethod
    def _dispatch(st: _State, cmd, key, payload):
        if cmd == "init":
            with st.lock:
                if key not in st.store:
                    st.store[key] = onp.array(payload)
                    st.round_done[key] = 0
            return True, None
        if cmd == "push":
            if st.mode == "async":
                # reference async: apply immediately, no aggregation
                with st.lock:
                    st.apply_update(key, payload)
                return True, None
            with st.cv:
                acc, cnt = st.merge.get(key, (None, 0))
                acc = payload if acc is None else acc + payload
                cnt += 1
                if cnt >= st.num_workers:
                    st.apply_update(key, acc)
                    st.merge[key] = (None, 0)
                    st.round_done[key] += 1
                    st.cv.notify_all()
                else:
                    st.merge[key] = (acc, cnt)
            return True, None
        if cmd == "pull":
            if st.mode == "async":
                with st.lock:
                    return True, onp.array(st.store[key])
            # sync: wait until no partial round is in flight for key
            with st.cv:
                st.cv.wait_for(
                    lambda: st.merge.get(key, (None, 0))[1] == 0)
                return True, onp.array(st.store[key])
        if cmd == "set_optimizer":
            from .. import optimizer as opt_mod
            opt = pickle.loads(payload)

            updater = opt_mod.get_updater(opt)

            def np_updater(k, g, w):
                from ..ndarray import NDArray
                import jax.numpy as jnp
                wn = NDArray(jnp.asarray(w))
                updater(k, NDArray(jnp.asarray(g)), wn)
                st.store[k] = onp.asarray(wn.data)

            with st.lock:
                st.updater = np_updater
            return True, None
        if cmd == "barrier":
            with st.cv:
                gen = st.barrier_gen
                st.barrier_count += 1
                if st.barrier_count >= st.num_workers:
                    st.barrier_count = 0
                    st.barrier_gen += 1
                    st.cv.notify_all()
                else:
                    st.cv.wait_for(lambda: st.barrier_gen > gen)
            return True, None
        return False, f"unknown command {cmd!r}"


class PSServer(socketserver.ThreadingTCPServer):
    """Threaded TCP parameter server (one per reference 'server' role)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr=("127.0.0.1", 0), mode="sync", num_workers=1):
        super().__init__(addr, _Handler)
        self.state = _State(mode, num_workers)

    @property
    def port(self):
        return self.server_address[1]


def serve_forever(port, mode, num_workers):
    """Entry point used by tools/launch.py server roles."""
    srv = PSServer(("127.0.0.1", port), mode=mode, num_workers=num_workers)
    srv.serve_forever()


class PSClient:
    """Worker-side connection to a PSServer (the KVWorker role)."""

    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port), timeout=60)
        self.lock = threading.Lock()

    def call(self, cmd, key=None, payload=None):
        with self.lock:
            _send_msg(self.sock, (cmd, key, payload))
            ok, out = _recv_msg(self.sock)
        if not ok:
            raise RuntimeError(f"ps server error: {out}")
        return out

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass
