"""Parameter-server process: the dist_async / dist_sync server role.

TPU-native re-design of the reference's ps-lite server
(src/kvstore/kvstore_dist_server.h:155-359): a standalone process
holding the authoritative weights, applying updates as workers push.

* ``sync`` mode — aggregates exactly ``num_workers`` pushes per key,
  then applies the merged gradient once (server optimizer if set, else
  plain accumulate); pulls for that key block until the round completes
  (DataHandleDefault + ApplyUpdates semantics,
  kvstore_dist_server.h:325-359).
* ``async`` mode — every push is applied immediately and independently;
  no aggregation, no round barrier: workers race exactly like the
  reference's async mode (DataHandleDefault else-branch :349).

Transport is a length-prefixed pickle protocol over TCP on localhost /
DCN — the role ps-lite's ZMQ Van plays (SURVEY.md §5.8), chosen over
gRPC to keep the runtime dependency-free.  The server is pure
CPU/numpy: it never touches an accelerator, mirroring the reference
where servers are CPU processes.

Fault tolerance (docs/fault_tolerance.md):

* every push carries a client session id + per-key sequence number, and
  the server remembers the last sequence applied per (session, key) —
  a retried push (response lost on the wire) is acknowledged without
  re-merging, so sync aggregation stays exactly-once (the role of
  ps-lite's per-customer timestamps);
* sync ``pull`` and ``barrier`` waits are bounded by
  ``MXNET_KVSTORE_TIMEOUT`` and surface a typed
  :class:`~incubator_mxnet_tpu.error.PSTimeoutError` naming the stalled
  key/round instead of hanging forever on a dead worker;
* :class:`PSClient` owns reconnect: any transport failure mid-call
  closes the socket (a half-read length-prefixed stream can never be
  resynchronized) and retries the whole request against a fresh
  connection with exponential backoff + jitter
  (``MXNET_KVSTORE_RETRIES`` attempts);
* ``heartbeat`` answers with server vitals for liveness probing;
* a server restart can adopt the previous :class:`_State` (checkpointed
  weights + dedup table), so recovery does not double-apply in-flight
  retries.

Elastic membership (docs/fault_tolerance.md "Elasticity"): workers
``join`` with a declared dp-rank and then ``beat`` periodically; a
member silent past ``MXNET_KVSTORE_BEAT_INTERVAL`` ×
``MXNET_KVSTORE_DEAD_AFTER`` seconds is evicted and sync rounds /
barriers re-balance to the survivors (aggregation counts LIVE members,
not the static ``num_workers`` — the push/barrier seq dedup makes
re-balancing mid-round safe).  An evicted worker's next call answers a
typed :class:`~incubator_mxnet_tpu.error.WorkerEvictedError` (its own
beat delivers the eviction notice), and a (re)``join`` re-admits it;
the joiner bootstraps by pulling current weights (a bare pull waits for
a quiescent point) before entering the next round.

Wire protocol: request = (cmd, key, payload); response = (ok, payload).
Push payloads may be wrapped as ``{"__ps__": 1, "data": .., "sess": ..,
"seq": ..}`` for dedup; bare arrays are accepted (no dedup).  Sync push
acks carry the round the push joined (``{"round": n}``) so a pull can
wait for exactly that round even after rejoin resets the client's seq.
Commands: init, push, pull, set_optimizer, barrier, heartbeat, join,
leave, beat, stop.
Error responses carry ``"Kind: message"`` and are re-raised client-side
as the registered error class (error.get_error_class).
"""
from __future__ import annotations

import logging
import pickle
import socket
import socketserver
import struct
import threading
import time
import uuid

import numpy as onp

from .. import fault, flightrec
from ..base import get_env
from ..error import PSTimeoutError, WorkerEvictedError, get_error_class
from ..locks import named_condition, named_lock

__all__ = ["PSServer", "PSClient", "serve_forever"]

_log = logging.getLogger("incubator_mxnet_tpu.kvstore.ps")


def _timeout_s():
    return get_env("MXNET_KVSTORE_TIMEOUT", 60.0, float)


def _send_msg(sock, obj):
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(data)) + data)


class _CleanClose(ConnectionError):
    """Peer closed at a message boundary — an orderly disconnect."""


def _raise_server_error(out):
    """Re-raise a marshalled ``"Kind: message"`` error response as its
    registered error class (error.get_error_class)."""
    kind, sep, msg = str(out).partition(": ")
    if sep:
        raise get_error_class(kind)(f"ps server error: {msg}")
    raise RuntimeError(f"ps server error: {out}")


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            if not hdr:
                raise _CleanClose("peer closed")
            raise ConnectionError("peer closed mid-frame")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return pickle.loads(bytes(buf))


class _State:
    """Server-side store + sync-round bookkeeping + membership."""

    def __init__(self, mode, num_workers):
        self.mode = mode
        self.num_workers = num_workers
        self.store: dict = {}
        self.merge: dict = {}           # key -> (accum, count) for sync
        self.merge_need: dict = {}      # key -> open round's threshold
        self.round_done: dict = {}      # key -> round counter
        self.seen: dict = {}            # (session, key) -> (seq, round)
        self.barrier_seen: dict = {}    # session -> (seq, gen entered)
        self.updater = None
        self.lock = named_lock("ps.server")
        self.cv = named_condition("ps.server", self.lock)
        self.barrier_count = 0
        self.barrier_gen = 0
        self.barrier_need = None        # open barrier's frozen threshold
        self.wait_timeout = _timeout_s()
        # -- elastic membership (empty table = fixed-fleet semantics) --
        self.members: dict = {}         # session -> {"rank", "last_beat"}
        self.evicted: dict = {}         # session -> eviction reason
        self.departed = 0               # evictions + leaves, net of rejoins
        self.beat_interval = get_env("MXNET_KVSTORE_BEAT_INTERVAL",
                                     5.0, float)
        self.dead_after = get_env("MXNET_KVSTORE_DEAD_AFTER", 3, int)

    # -- membership (every method below is called with the lock held) --
    def required(self):
        """Pushes/arrivals a sync round needs.

        Membership shrinks a round only through DEPARTURE (eviction or
        graceful leave) — never through a worker that has not joined
        yet: during the startup join window the floor stays at the
        launcher's ``num_workers``, so a fast first joiner cannot
        complete a "round" of one with a partial fleet's gradient
        while its peers' joins are still in flight.  With no membership
        activity at all, the static reference semantics hold."""
        if not self.members and self.departed == 0:
            return self.num_workers
        return max(1, len(self.members), self.num_workers - self.departed)

    def open_need(self, key):
        """Threshold for ``key``'s OPEN round: frozen at the membership
        when the round's first push arrived (a worker joining mid-round
        must not inflate a round the survivors are already completing;
        its own pushes count toward the NEXT round's threshold), and
        only ever lowered — by :meth:`rebalance` when a member departs
        mid-round."""
        if key not in self.merge_need:
            self.merge_need[key] = self.required()
        return self.merge_need[key]

    def check_not_evicted(self, sess, what):
        if sess is not None and sess in self.evicted:
            raise WorkerEvictedError(
                f"worker session {sess[:8]} was evicted "
                f"({self.evicted[sess]}); join again (and bootstrap by "
                f"pulling current weights) before {what}")

    def sweep(self):
        """Evict members silent past their heartbeat budget and
        re-balance open rounds/barriers to the survivors.  Returns
        False so it composes into wait predicates."""
        if not self.members:
            return False
        now = time.monotonic()
        budget = self.beat_interval * self.dead_after
        dead = [s for s, m in self.members.items()
                if now - m["last_beat"] > budget]
        for s in dead:
            m = self.members.pop(s)
            self.departed += 1
            self.evicted[s] = (
                f"missed its heartbeat budget: silent "
                f"{now - m['last_beat']:.2f}s > {self.dead_after} beats "
                f"x {self.beat_interval:.2f}s")
            flightrec.record(flightrec.MEMBERSHIP, "worker.evicted",
                             severity="warn", rank=m["rank"],
                             sess=s[:8], live=len(self.members),
                             silent_s=round(now - m["last_beat"], 2))
            _log.warning(
                "ps membership: evicted worker rank=%s sess=%s (%s); "
                "%d live member(s) remain", m["rank"], s[:8],
                self.evicted[s], len(self.members))
        if dead:
            self.rebalance()
            self.cv.notify_all()
        return False

    def rebalance(self):
        """A shrunken fleet may complete open sync rounds and the
        barrier: aggregation counts live members, and the seq dedup
        already protects against a straggler's retry re-counting.
        Open-round thresholds only ever go DOWN here — a join never
        raises them (see :meth:`open_need`)."""
        need = self.required()
        for key, (acc, cnt) in list(self.merge.items()):
            if cnt == 0:
                continue
            self.merge_need[key] = min(
                self.merge_need.get(key, need), need)
            if cnt >= self.merge_need[key]:
                self.apply_update(key, acc)
                self.merge[key] = (None, 0)
                del self.merge_need[key]
                self.round_done[key] = self.round_done.get(key, 0) + 1
        if self.barrier_count > 0:
            self.barrier_need = min(self.barrier_need or need, need)
            if self.barrier_count >= self.barrier_need:
                self.barrier_count = 0
                self.barrier_need = None
                self.barrier_gen += 1

    def wait_with_sweep(self, pred, timeout):
        """``cv.wait_for`` that additionally wakes at least once per
        half beat interval to run the eviction sweep — a dead worker
        cannot stall a round past its heartbeat budget even when every
        survivor is blocked waiting here."""
        deadline = time.monotonic() + timeout
        while True:
            self.sweep()
            if pred():
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            if self.members or self.evicted:   # membership active
                remaining = min(remaining,
                                max(self.beat_interval / 2.0, 0.01))
            self.cv.wait(remaining)

    def check_initialized(self, key, what):
        """A push/pull for a key no ``init`` ever stored is a worker
        ordering/identity bug (classically: a leaked nonzero
        MXT_WORKER_ID making every worker skip its rank-0 init), not a
        transient — surface it typed and actionable instead of a bare
        ``KeyError`` that reads like server corruption."""
        if key not in self.store:
            raise ValueError(
                f"key {key!r} was never initialized on this server "
                f"({len(self.store)} known key(s)); init() must precede "
                f"{what} — if no worker ran init, check that rank 0 "
                "really is rank 0 (a stale MXT_WORKER_ID makes every "
                "worker skip its rank-0 init calls)")

    def apply_update(self, key, grad):
        if self.updater is not None:
            # the server-side optimizer reads the stored weight; the
            # no-updater sync branch below overwrites unconditionally
            # (CopyFromTo semantics), so only this path needs init first
            self.check_initialized(key, "push")
            w = self.store[key]
            self.updater(key, grad, w)   # in-place numpy update
        elif self.mode == "async":
            # reference: "Updater needs to be set for async mode"
            # (kvstore_dist_server.h:360 CHECK)
            raise RuntimeError(
                "async parameter server requires a server-side optimizer: "
                "call kv.set_optimizer(...) before pushing")
        else:
            # sync without updater: the stored value becomes the merged
            # push (kvstore_dist_server.h:362 CopyFromTo)
            self.store[key] = onp.array(grad)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        st: _State = self.server.state  # type: ignore[attr-defined]
        sock = self.request
        last = None
        try:
            while True:
                cmd, key, payload = _recv_msg(sock)
                last = (cmd, key)
                if cmd == "stop":
                    _send_msg(sock, (True, None))
                    threading.Thread(
                        target=self.server.shutdown, daemon=True).start()
                    return
                try:
                    resp = self._dispatch(st, cmd, key, payload)
                except Exception as e:  # mxlint: allow-broad-except(marshalled into the response tuple and raised client-side)
                    resp = (False, f"{type(e).__name__}: {e}")
                _send_msg(sock, resp)
        except _CleanClose:
            return   # orderly disconnect between requests
        except (ConnectionError, OSError) as e:
            # The client vanished mid-call.  Server state is already
            # consistent: an applied push whose ack was lost is recorded
            # in st.seen, so the client's retry (on a new connection)
            # will be acknowledged without re-merging.  Log — a silent
            # return here is how half-counted rounds went undiagnosed.
            if last is not None:
                _log.warning(
                    "ps handler: client %s dropped after %s %r (%s); "
                    "state kept, retries will dedup", self.client_address,
                    last[0], last[1], e)
            return

    @staticmethod
    def _dispatch(st: _State, cmd, key, payload):
        if cmd == "init":
            with st.lock:
                if key in st.store:
                    have = st.store[key]
                    want = onp.asarray(payload)
                    if (tuple(have.shape) != tuple(want.shape)
                            or have.dtype != want.dtype):
                        raise ValueError(
                            f"init of existing key {key!r} with "
                            f"shape={tuple(want.shape)} dtype={want.dtype} "
                            f"conflicts with stored shape="
                            f"{tuple(have.shape)} dtype={have.dtype}")
                else:
                    st.store[key] = onp.array(payload)
                    st.round_done[key] = 0
            return True, None
        if cmd == "push":
            sess = seq = None
            if isinstance(payload, dict) and payload.get("__ps__") == 1:
                sess, seq = payload["sess"], payload["seq"]
                payload = payload["data"]
            if st.mode == "async":
                with st.lock:
                    st.sweep()
                    if sess is not None:
                        prev = st.seen.get((sess, key))
                        if prev is not None and seq <= prev[0]:
                            return True, None   # duplicate of applied push
                        st.check_not_evicted(sess, "pushing")
                        st.seen[(sess, key)] = (seq, None)
                    # reference async: apply immediately, no aggregation
                    st.apply_update(key, payload)
                return True, None
            with st.cv:
                st.sweep()
                if sess is not None:
                    prev = st.seen.get((sess, key))
                    if prev is not None and seq <= prev[0]:
                        # retried push: already merged — re-ack the round
                        # the ORIGINAL joined (its ack was lost)
                        return True, {"round": prev[1]}
                    st.check_not_evicted(sess, "pushing")
                # the round this push joins completes when round_done
                # reaches target; the ack carries it so the client's
                # pull waits for exactly this round (survives rejoin
                # resetting the client-side seq counter)
                target = st.round_done.get(key, 0) + 1
                if sess is not None:
                    st.seen[(sess, key)] = (seq, target)
                acc, cnt = st.merge.get(key, (None, 0))
                acc = payload if acc is None else acc + payload
                cnt += 1
                if cnt >= st.open_need(key):
                    st.apply_update(key, acc)
                    st.merge[key] = (None, 0)
                    st.merge_need.pop(key, None)
                    st.round_done[key] = st.round_done.get(key, 0) + 1
                    st.cv.notify_all()
                else:
                    st.merge[key] = (acc, cnt)
            return True, {"round": target}
        if cmd == "pull":
            sess = target = None
            if isinstance(payload, dict) and payload.get("__ps__") == 1:
                sess = payload.get("sess")
                if payload.get("round") is not None:
                    target = int(payload["round"])
                elif payload.get("after_seq") is not None:
                    target = int(payload["after_seq"]) + 1
            if st.mode == "async":
                with st.lock:
                    st.sweep()
                    st.check_not_evicted(sess, "pulling")
                    st.check_initialized(key, "pull")
                    return True, onp.array(st.store[key])
            # sync, bounded wait — a dead worker must surface, not hang
            # the fleet.  A puller that has pushed waits for the round
            # its own push joined (the round target from the push ack):
            # waiting for "no partial round" would deadlock when a fast
            # peer opens the NEXT round before this pull is served
            # (reference semantics: ApplyUpdates wakes the round's own
            # pulls).
            with st.cv:
                st.check_not_evicted(sess, "pulling")
                # fail FAST on a never-initialized key: the round wait
                # below can never be satisfied for it, and burning the
                # full sync timeout turns a deterministic client bug
                # into a load-sensitive flake
                st.check_initialized(key, "pull")
                if target is not None:
                    done = st.wait_with_sweep(
                        lambda: st.round_done.get(key, 0) >= target,
                        timeout=st.wait_timeout)
                else:  # bare puller (never pushed): any quiescent point
                    done = st.wait_with_sweep(
                        lambda: st.merge.get(key, (None, 0))[1] == 0,
                        timeout=st.wait_timeout)
                # the waiter itself may have been evicted while blocked
                st.check_not_evicted(sess, "pulling")
                if not done:
                    cnt = st.merge.get(key, (None, 0))[1]
                    raise PSTimeoutError(
                        f"sync pull of key {key!r} stalled in round "
                        f"{st.round_done.get(key, 0)}: {cnt} of "
                        f"{st.required()} pushes after "
                        f"{st.wait_timeout:.0f}s (a worker likely died "
                        "mid-round)")
                return True, onp.array(st.store[key])
        if cmd == "set_optimizer":
            from .. import optimizer as opt_mod
            opt = pickle.loads(payload)

            updater = opt_mod.get_updater(opt)

            def np_updater(k, g, w):
                from ..ndarray import NDArray
                import jax.numpy as jnp
                wn = NDArray(jnp.asarray(w))
                updater(k, NDArray(jnp.asarray(g)), wn)
                st.store[k] = onp.asarray(wn.data)

            with st.lock:
                st.updater = np_updater
            return True, None
        if cmd == "barrier":
            sess = seq = None
            if isinstance(payload, dict) and payload.get("__ps__") == 1:
                sess, seq = payload["sess"], payload["seq"]
            with st.cv:
                st.sweep()
                st.check_not_evicted(sess, "entering a barrier")
                if sess is not None:
                    prev = st.barrier_seen.get(sess)
                    if prev is not None and seq <= prev[0]:
                        # retry of an arrival already counted (the ack
                        # was lost): re-counting would release the
                        # barrier before every worker arrived — wait on
                        # the generation the original arrival joined
                        gen0 = prev[1]
                        if st.barrier_gen > gen0:
                            return True, None
                        done = st.wait_with_sweep(
                            lambda: st.barrier_gen > gen0,
                            timeout=st.wait_timeout)
                        if not done:
                            raise PSTimeoutError(
                                f"barrier generation {gen0} stalled: "
                                f"{st.barrier_count} of {st.required()} "
                                f"workers arrived after "
                                f"{st.wait_timeout:.0f}s")
                        return True, None
                    st.barrier_seen[sess] = (seq, st.barrier_gen)
                gen = st.barrier_gen
                st.barrier_count += 1
                if st.barrier_need is None:
                    # threshold frozen at the first arrival's membership
                    # (a mid-barrier joiner must not inflate it); only
                    # rebalance() may lower it
                    st.barrier_need = st.required()
                if st.barrier_count >= st.barrier_need:
                    st.barrier_count = 0
                    st.barrier_need = None
                    st.barrier_gen += 1
                    st.cv.notify_all()
                else:
                    done = st.wait_with_sweep(
                        lambda: st.barrier_gen > gen,
                        timeout=st.wait_timeout)
                    if not done:
                        cnt, st.barrier_count = st.barrier_count, \
                            st.barrier_count - 1   # leave the barrier
                        if st.barrier_count == 0:
                            st.barrier_need = None  # next barrier refreezes
                        raise PSTimeoutError(
                            f"barrier generation {gen} stalled: {cnt} of "
                            f"{st.required()} workers arrived after "
                            f"{st.wait_timeout:.0f}s")
            return True, None
        if cmd == "join":
            sess, rank = payload["sess"], payload.get("rank")
            with st.cv:
                st.sweep()
                rejoin = st.evicted.pop(sess, None) is not None
                st.members[sess] = {"rank": rank,
                                    "last_beat": time.monotonic()}
                # any join that grows the fleet past its current
                # expected size (num_workers - departed) is a departed
                # worker coming back — same-session rejoin after
                # eviction, rejoin after a graceful leave, or a fresh
                # replacement process — so net it out of `departed`
                # (startup joins stay within the expected size and
                # leave the floor alone)
                if (st.departed > 0 and len(st.members)
                        > max(0, st.num_workers - st.departed)):
                    st.departed -= 1
                flightrec.record(flightrec.MEMBERSHIP, "worker.joined",
                                 rank=rank, sess=sess[:8],
                                 rejoin=rejoin, live=len(st.members))
                _log.info("ps membership: worker rank=%s sess=%s "
                          "%sjoined; %d live", rank, sess[:8],
                          "re" if rejoin else "", len(st.members))
                return True, {"live_workers": len(st.members),
                              "rank": rank, "rejoin": rejoin,
                              "barrier_gen": st.barrier_gen}
        if cmd == "leave":
            sess = payload["sess"]
            with st.cv:
                m = st.members.pop(sess, None)
                st.evicted.pop(sess, None)  # a graceful leave, not evict
                if m is not None:
                    st.departed += 1
                    flightrec.record(flightrec.MEMBERSHIP,
                                     "worker.left", rank=m["rank"],
                                     sess=sess[:8],
                                     live=len(st.members))
                    st.rebalance()
                    st.cv.notify_all()
                return True, {"live_workers": len(st.members)}
        if cmd == "beat":
            sess = payload["sess"]
            with st.cv:
                st.sweep()
                st.check_not_evicted(sess, "beating")
                m = st.members.get(sess)
                if m is None:
                    # a beat from a session the table does not know is
                    # the same actionable notice as an eviction: (re)join
                    # and bootstrap before training on
                    raise WorkerEvictedError(
                        f"worker session {sess[:8]} is not in the "
                        "membership table (server restarted, or the "
                        "worker never joined); join again and bootstrap "
                        "by pulling current weights")
                m["last_beat"] = time.monotonic()
                return True, {"live_workers": len(st.members),
                              "rank": m["rank"],
                              "num_keys": len(st.store),
                              "barrier_gen": st.barrier_gen}
        if cmd == "heartbeat":
            with st.lock:
                st.sweep()
                return True, {"mode": st.mode,
                              "num_workers": st.num_workers,
                              "live_workers": len(st.members),
                              "num_keys": len(st.store),
                              "barrier_gen": st.barrier_gen}
        return False, f"unknown command {cmd!r}"


class PSServer(socketserver.ThreadingTCPServer):
    """Threaded TCP parameter server (one per reference 'server' role).

    ``state=`` lets a restarted server adopt a previous instance's
    :class:`_State` (weights AND the push-dedup table), so recovery
    after a crash-restart does not double-apply retried pushes.
    """

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr=("127.0.0.1", 0), mode="sync", num_workers=1,
                 state=None):
        super().__init__(addr, _Handler)
        self.state = state if state is not None else _State(mode, num_workers)
        self._conns: set = set()

    def get_request(self):
        sock, addr = super().get_request()
        # prune sockets the handler already closed (fileno -1) so the
        # live-connection set does not grow with reconnect churn
        self._conns = {s for s in self._conns if s.fileno() != -1}
        self._conns.add(sock)
        return sock, addr

    @property
    def port(self):
        return self.server_address[1]

    def kill(self):
        """Simulate a server crash: stop accepting AND sever every live
        connection (handler threads would otherwise keep serving their
        open sockets past ``server_close``).  Restart by constructing a
        new :class:`PSServer` with ``state=old.state`` — weights and the
        push-dedup table survive, exactly the recovered-from-checkpoint
        server role."""
        self.shutdown()
        self.server_close()
        for s in list(self._conns):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        self._conns.clear()


def serve_forever(port, mode, num_workers):
    """Entry point used by tools/launch.py server roles."""
    srv = PSServer(("127.0.0.1", port), mode=mode, num_workers=num_workers)
    srv.serve_forever()


class PSClient:
    """Worker-side connection to a PSServer (the KVWorker role).

    Requests are retried on transport failure: the socket is CLOSED and
    re-established first (a partial read leaves length-prefix framing
    desynced — every later decode on the same stream would be garbage),
    then the whole request is re-sent.  Pushes carry (session, seq) so
    the server deduplicates a retry whose original was applied but whose
    ack was lost.  Retry exhaustion surfaces
    :class:`~incubator_mxnet_tpu.error.PSTimeoutError` naming the
    command and key.
    """

    def __init__(self, host, port, timeout=None, max_retries=None):
        self.host, self.port = host, port
        # per-attempt socket budget sits ABOVE the server's bounded
        # sync-wait so the server's typed timeout arrives as a response,
        # not as a client-side socket timeout
        self.timeout = (timeout if timeout is not None
                        else _timeout_s() + 15.0)
        self.max_retries = (max_retries if max_retries is not None
                            else get_env("MXNET_KVSTORE_RETRIES", 5, int))
        self.session = uuid.uuid4().hex
        self._seq: dict = {}       # key -> last sequence number issued
        self._round_target: dict = {}  # key -> round our pushes reached
        self._barrier_seq = -1
        self.lock = named_lock("ps.client")
        self.sock = None
        self._connect()

    def _connect(self):
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout)
        self.sock.settimeout(self.timeout)

    def _reconnect(self, attempt, exc, sleep_s):
        _log.warning(
            "ps client %s: %s to %s:%s failed (%s); reconnecting in "
            "%.2fs (attempt %d/%d)", self.session[:8], "call", self.host,
            self.port, exc, sleep_s, attempt, self.max_retries)
        self.close()

    def _roundtrip(self, req):
        if self.sock is None:
            self._connect()
        fault.inject("kvstore.send", detail=str(req[0]))
        _send_msg(self.sock, req)
        fault.inject("kvstore.recv", detail=str(req[0]))
        return _recv_msg(self.sock)

    def call(self, cmd, key=None, payload=None):
        # seq issuance happens under the SAME lock as the roundtrip:
        # clients are shared across threads (P3's background sender +
        # the main thread), and a torn increment would hand two live
        # pushes the same seq — the server would dedup a real gradient
        with self.lock:
            if cmd == "push":
                # same seq across retries of this call: the dedup identity
                seq = self._seq[key] = self._seq.get(key, -1) + 1
                payload = {"__ps__": 1, "data": payload,
                           "sess": self.session, "seq": seq}
            elif cmd == "pull":
                # tell the server which round our own pushes reached so
                # the sync wait targets that round, not global
                # quiescence; the round target comes from the push acks
                # (robust across rejoin, which resets the seq counter).
                # A bare pull still identifies the session so an evicted
                # worker gets its typed notice instead of stale weights.
                payload = {"__ps__": 1, "sess": self.session}
                if key in self._round_target:
                    payload["round"] = self._round_target[key]
                elif key in self._seq:
                    payload["after_seq"] = self._seq[key]
            elif cmd == "barrier":
                # barriers carry a seq too: a retried arrival must not
                # count twice or the barrier releases early
                self._barrier_seq += 1
                payload = {"__ps__": 1, "sess": self.session,
                           "seq": self._barrier_seq}
            req = (cmd, key, payload)
            if cmd == "stop":
                # best-effort: a lost ack means the server is already down
                try:
                    self._roundtrip(req)
                except (ConnectionError, TimeoutError, OSError):
                    pass
                finally:
                    self.close()
                return None
            try:
                ok, out = fault.retry(  # mxlint: allow-blocking-under-lock(the client lock serializes the single shared socket; the retry+reconnect roundtrip IS the critical section — concurrent callers must queue behind it, not interleave frames on a dead socket)
                    lambda: self._roundtrip(req),
                    max_attempts=self.max_retries,
                    retryable=(ConnectionError, TimeoutError, OSError),
                    on_retry=self._reconnect)
            except (ConnectionError, TimeoutError, OSError) as e:
                self.close()
                raise PSTimeoutError(
                    f"ps {cmd} for key {key!r} failed after "
                    f"{self.max_retries} attempts to {self.host}:"
                    f"{self.port}: {e}") from e
        if not ok:
            _raise_server_error(out)
        if cmd == "push" and isinstance(out, dict) \
                and out.get("round") is not None:
            with self.lock:
                self._round_target[key] = max(
                    self._round_target.get(key, 0), out["round"])
        return out

    # -- elastic membership (docs/fault_tolerance.md "Elasticity") ------
    def join(self, rank=None):
        """Enter the server's membership table with a declared dp-rank.
        Idempotent (a retried join re-admits the same session); also the
        re-admission path after a :class:`WorkerEvictedError`."""
        return self.call("join", None,
                         {"sess": self.session, "rank": rank})

    def leave(self):
        """Gracefully exit the membership table (rounds re-balance to
        the survivors immediately, no heartbeat budget to burn)."""
        return self.call("leave", None, {"sess": self.session})

    def _oneshot(self, cmd, payload, timeout):
        """One request on a DEDICATED short-budget connection.

        Liveness traffic must not ride the main connection: it may be
        parked in a blocking sync pull under the client lock (a worker
        must never starve its own heartbeat waiting for slow peers),
        and it must not ride the retry pipeline either (a probe that
        retries for minutes answers slower than the failure it
        diagnoses; a lost beat is simply lost — that IS the missed-beat
        semantic the eviction budget counts)."""
        try:
            # injected probe/beat loss is a ConnectionError: it wraps
            # to the same typed PSTimeoutError a real lost one surfaces
            fault.inject("kvstore.heartbeat", detail=cmd)
            with socket.create_connection((self.host, self.port),
                                          timeout=timeout) as s:
                s.settimeout(timeout)
                _send_msg(s, (cmd, None, payload))
                ok, out = _recv_msg(s)
        except (ConnectionError, TimeoutError, OSError) as e:
            raise PSTimeoutError(
                f"ps {cmd} to {self.host}:{self.port} failed within "
                f"{timeout:.0f}s: {e}") from e
        if not ok:
            _raise_server_error(out)
        return out

    def beat(self, timeout=5.0):
        """Membership heartbeat: refreshes this worker's liveness and
        returns fleet vitals.  An evicted (or unknown) session receives
        the typed :class:`~incubator_mxnet_tpu.error.WorkerEvictedError`
        — the beat IS the eviction notice delivery path."""
        return self._oneshot("beat", {"sess": self.session}, timeout)

    def heartbeat(self, timeout=5.0):
        """Liveness probe: server vitals, or raises PSTimeoutError."""
        return self._oneshot("heartbeat", None, timeout)

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
