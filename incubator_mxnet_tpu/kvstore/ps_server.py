"""Parameter-server process: the dist_async / dist_sync server role.

TPU-native re-design of the reference's ps-lite server
(src/kvstore/kvstore_dist_server.h:155-359): a standalone process
holding the authoritative weights, applying updates as workers push.

* ``sync`` mode — aggregates exactly ``num_workers`` pushes per key,
  then applies the merged gradient once (server optimizer if set, else
  plain accumulate); pulls for that key block until the round completes
  (DataHandleDefault + ApplyUpdates semantics,
  kvstore_dist_server.h:325-359).
* ``async`` mode — every push is applied immediately and independently;
  no aggregation, no round barrier: workers race exactly like the
  reference's async mode (DataHandleDefault else-branch :349).

Transport is a length-prefixed pickle protocol over TCP on localhost /
DCN — the role ps-lite's ZMQ Van plays (SURVEY.md §5.8), chosen over
gRPC to keep the runtime dependency-free.  The server is pure
CPU/numpy: it never touches an accelerator, mirroring the reference
where servers are CPU processes.

Fault tolerance (docs/fault_tolerance.md):

* every push carries a client session id + per-key sequence number, and
  the server remembers the last sequence applied per (session, key) —
  a retried push (response lost on the wire) is acknowledged without
  re-merging, so sync aggregation stays exactly-once (the role of
  ps-lite's per-customer timestamps);
* sync ``pull`` and ``barrier`` waits are bounded by
  ``MXNET_KVSTORE_TIMEOUT`` and surface a typed
  :class:`~incubator_mxnet_tpu.error.PSTimeoutError` naming the stalled
  key/round instead of hanging forever on a dead worker;
* :class:`PSClient` owns reconnect: any transport failure mid-call
  closes the socket (a half-read length-prefixed stream can never be
  resynchronized) and retries the whole request against a fresh
  connection with exponential backoff + jitter
  (``MXNET_KVSTORE_RETRIES`` attempts);
* ``heartbeat`` answers with server vitals for liveness probing;
* a server restart can adopt the previous :class:`_State` (checkpointed
  weights + dedup table), so recovery does not double-apply in-flight
  retries.

Wire protocol: request = (cmd, key, payload); response = (ok, payload).
Push payloads may be wrapped as ``{"__ps__": 1, "data": .., "sess": ..,
"seq": ..}`` for dedup; bare arrays are accepted (no dedup).
Commands: init, push, pull, set_optimizer, barrier, heartbeat, stop.
Error responses carry ``"Kind: message"`` and are re-raised client-side
as the registered error class (error.get_error_class).
"""
from __future__ import annotations

import logging
import pickle
import socket
import socketserver
import struct
import threading
import uuid

import numpy as onp

from .. import fault
from ..base import get_env
from ..error import PSTimeoutError, get_error_class

__all__ = ["PSServer", "PSClient", "serve_forever"]

_log = logging.getLogger("incubator_mxnet_tpu.kvstore.ps")


def _timeout_s():
    return get_env("MXNET_KVSTORE_TIMEOUT", 60.0, float)


def _send_msg(sock, obj):
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(data)) + data)


class _CleanClose(ConnectionError):
    """Peer closed at a message boundary — an orderly disconnect."""


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            if not hdr:
                raise _CleanClose("peer closed")
            raise ConnectionError("peer closed mid-frame")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return pickle.loads(bytes(buf))


class _State:
    """Server-side store + sync-round bookkeeping."""

    def __init__(self, mode, num_workers):
        self.mode = mode
        self.num_workers = num_workers
        self.store: dict = {}
        self.merge: dict = {}           # key -> (accum, count) for sync
        self.round_done: dict = {}      # key -> round counter
        self.seen: dict = {}            # (session, key) -> last seq applied
        self.barrier_seen: dict = {}    # session -> (seq, gen entered)
        self.updater = None
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.barrier_count = 0
        self.barrier_gen = 0
        self.wait_timeout = _timeout_s()

    def apply_update(self, key, grad):
        if self.updater is not None:
            w = self.store[key]
            self.updater(key, grad, w)   # in-place numpy update
        elif self.mode == "async":
            # reference: "Updater needs to be set for async mode"
            # (kvstore_dist_server.h:360 CHECK)
            raise RuntimeError(
                "async parameter server requires a server-side optimizer: "
                "call kv.set_optimizer(...) before pushing")
        else:
            # sync without updater: the stored value becomes the merged
            # push (kvstore_dist_server.h:362 CopyFromTo)
            self.store[key] = onp.array(grad)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        st: _State = self.server.state  # type: ignore[attr-defined]
        sock = self.request
        last = None
        try:
            while True:
                cmd, key, payload = _recv_msg(sock)
                last = (cmd, key)
                if cmd == "stop":
                    _send_msg(sock, (True, None))
                    threading.Thread(
                        target=self.server.shutdown, daemon=True).start()
                    return
                try:
                    resp = self._dispatch(st, cmd, key, payload)
                except Exception as e:  # mxlint: allow-broad-except(marshalled into the response tuple and raised client-side)
                    resp = (False, f"{type(e).__name__}: {e}")
                _send_msg(sock, resp)
        except _CleanClose:
            return   # orderly disconnect between requests
        except (ConnectionError, OSError) as e:
            # The client vanished mid-call.  Server state is already
            # consistent: an applied push whose ack was lost is recorded
            # in st.seen, so the client's retry (on a new connection)
            # will be acknowledged without re-merging.  Log — a silent
            # return here is how half-counted rounds went undiagnosed.
            if last is not None:
                _log.warning(
                    "ps handler: client %s dropped after %s %r (%s); "
                    "state kept, retries will dedup", self.client_address,
                    last[0], last[1], e)
            return

    @staticmethod
    def _dispatch(st: _State, cmd, key, payload):
        if cmd == "init":
            with st.lock:
                if key in st.store:
                    have = st.store[key]
                    want = onp.asarray(payload)
                    if (tuple(have.shape) != tuple(want.shape)
                            or have.dtype != want.dtype):
                        raise ValueError(
                            f"init of existing key {key!r} with "
                            f"shape={tuple(want.shape)} dtype={want.dtype} "
                            f"conflicts with stored shape="
                            f"{tuple(have.shape)} dtype={have.dtype}")
                else:
                    st.store[key] = onp.array(payload)
                    st.round_done[key] = 0
            return True, None
        if cmd == "push":
            sess = seq = None
            if isinstance(payload, dict) and payload.get("__ps__") == 1:
                sess, seq = payload["sess"], payload["seq"]
                payload = payload["data"]
            if st.mode == "async":
                with st.lock:
                    if sess is not None:
                        if seq <= st.seen.get((sess, key), -1):
                            return True, None   # duplicate of applied push
                        st.seen[(sess, key)] = seq
                    # reference async: apply immediately, no aggregation
                    st.apply_update(key, payload)
                return True, None
            with st.cv:
                if sess is not None:
                    if seq <= st.seen.get((sess, key), -1):
                        return True, None       # retried push: already merged
                    st.seen[(sess, key)] = seq
                acc, cnt = st.merge.get(key, (None, 0))
                acc = payload if acc is None else acc + payload
                cnt += 1
                if cnt >= st.num_workers:
                    st.apply_update(key, acc)
                    st.merge[key] = (None, 0)
                    st.round_done[key] += 1
                    st.cv.notify_all()
                else:
                    st.merge[key] = (acc, cnt)
            return True, None
        if cmd == "pull":
            after_seq = None
            if isinstance(payload, dict) and payload.get("__ps__") == 1:
                after_seq = payload.get("after_seq")
            if st.mode == "async":
                with st.lock:
                    return True, onp.array(st.store[key])
            # sync, bounded wait — a dead worker must surface, not hang
            # the fleet.  A puller that has pushed waits for the round
            # its own push joined (round_done >= seq+1): waiting for
            # "no partial round" would deadlock when a fast peer opens
            # the NEXT round before this pull is served (reference
            # semantics: ApplyUpdates wakes the round's own pulls).
            with st.cv:
                if after_seq is not None:
                    target = int(after_seq) + 1
                    done = st.cv.wait_for(
                        lambda: st.round_done.get(key, 0) >= target,
                        timeout=st.wait_timeout)
                else:  # bare puller (never pushed): any quiescent point
                    done = st.cv.wait_for(
                        lambda: st.merge.get(key, (None, 0))[1] == 0,
                        timeout=st.wait_timeout)
                if not done:
                    cnt = st.merge.get(key, (None, 0))[1]
                    raise PSTimeoutError(
                        f"sync pull of key {key!r} stalled in round "
                        f"{st.round_done.get(key, 0)}: {cnt} of "
                        f"{st.num_workers} pushes after "
                        f"{st.wait_timeout:.0f}s (a worker likely died "
                        "mid-round)")
                return True, onp.array(st.store[key])
        if cmd == "set_optimizer":
            from .. import optimizer as opt_mod
            opt = pickle.loads(payload)

            updater = opt_mod.get_updater(opt)

            def np_updater(k, g, w):
                from ..ndarray import NDArray
                import jax.numpy as jnp
                wn = NDArray(jnp.asarray(w))
                updater(k, NDArray(jnp.asarray(g)), wn)
                st.store[k] = onp.asarray(wn.data)

            with st.lock:
                st.updater = np_updater
            return True, None
        if cmd == "barrier":
            sess = seq = None
            if isinstance(payload, dict) and payload.get("__ps__") == 1:
                sess, seq = payload["sess"], payload["seq"]
            with st.cv:
                if sess is not None:
                    prev = st.barrier_seen.get(sess)
                    if prev is not None and seq <= prev[0]:
                        # retry of an arrival already counted (the ack
                        # was lost): re-counting would release the
                        # barrier before every worker arrived — wait on
                        # the generation the original arrival joined
                        gen0 = prev[1]
                        if st.barrier_gen > gen0:
                            return True, None
                        done = st.cv.wait_for(
                            lambda: st.barrier_gen > gen0,
                            timeout=st.wait_timeout)
                        if not done:
                            raise PSTimeoutError(
                                f"barrier generation {gen0} stalled: "
                                f"{st.barrier_count} of {st.num_workers} "
                                f"workers arrived after "
                                f"{st.wait_timeout:.0f}s")
                        return True, None
                    st.barrier_seen[sess] = (seq, st.barrier_gen)
                gen = st.barrier_gen
                st.barrier_count += 1
                if st.barrier_count >= st.num_workers:
                    st.barrier_count = 0
                    st.barrier_gen += 1
                    st.cv.notify_all()
                else:
                    done = st.cv.wait_for(lambda: st.barrier_gen > gen,
                                          timeout=st.wait_timeout)
                    if not done:
                        cnt, st.barrier_count = st.barrier_count, \
                            st.barrier_count - 1   # leave the barrier
                        raise PSTimeoutError(
                            f"barrier generation {gen} stalled: {cnt} of "
                            f"{st.num_workers} workers arrived after "
                            f"{st.wait_timeout:.0f}s")
            return True, None
        if cmd == "heartbeat":
            with st.lock:
                return True, {"mode": st.mode,
                              "num_workers": st.num_workers,
                              "num_keys": len(st.store),
                              "barrier_gen": st.barrier_gen}
        return False, f"unknown command {cmd!r}"


class PSServer(socketserver.ThreadingTCPServer):
    """Threaded TCP parameter server (one per reference 'server' role).

    ``state=`` lets a restarted server adopt a previous instance's
    :class:`_State` (weights AND the push-dedup table), so recovery
    after a crash-restart does not double-apply retried pushes.
    """

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr=("127.0.0.1", 0), mode="sync", num_workers=1,
                 state=None):
        super().__init__(addr, _Handler)
        self.state = state if state is not None else _State(mode, num_workers)
        self._conns: set = set()

    def get_request(self):
        sock, addr = super().get_request()
        # prune sockets the handler already closed (fileno -1) so the
        # live-connection set does not grow with reconnect churn
        self._conns = {s for s in self._conns if s.fileno() != -1}
        self._conns.add(sock)
        return sock, addr

    @property
    def port(self):
        return self.server_address[1]

    def kill(self):
        """Simulate a server crash: stop accepting AND sever every live
        connection (handler threads would otherwise keep serving their
        open sockets past ``server_close``).  Restart by constructing a
        new :class:`PSServer` with ``state=old.state`` — weights and the
        push-dedup table survive, exactly the recovered-from-checkpoint
        server role."""
        self.shutdown()
        self.server_close()
        for s in list(self._conns):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        self._conns.clear()


def serve_forever(port, mode, num_workers):
    """Entry point used by tools/launch.py server roles."""
    srv = PSServer(("127.0.0.1", port), mode=mode, num_workers=num_workers)
    srv.serve_forever()


class PSClient:
    """Worker-side connection to a PSServer (the KVWorker role).

    Requests are retried on transport failure: the socket is CLOSED and
    re-established first (a partial read leaves length-prefix framing
    desynced — every later decode on the same stream would be garbage),
    then the whole request is re-sent.  Pushes carry (session, seq) so
    the server deduplicates a retry whose original was applied but whose
    ack was lost.  Retry exhaustion surfaces
    :class:`~incubator_mxnet_tpu.error.PSTimeoutError` naming the
    command and key.
    """

    def __init__(self, host, port, timeout=None, max_retries=None):
        self.host, self.port = host, port
        # per-attempt socket budget sits ABOVE the server's bounded
        # sync-wait so the server's typed timeout arrives as a response,
        # not as a client-side socket timeout
        self.timeout = (timeout if timeout is not None
                        else _timeout_s() + 15.0)
        self.max_retries = (max_retries if max_retries is not None
                            else get_env("MXNET_KVSTORE_RETRIES", 5, int))
        self.session = uuid.uuid4().hex
        self._seq: dict = {}       # key -> last sequence number issued
        self._barrier_seq = -1
        self.lock = threading.Lock()
        self.sock = None
        self._connect()

    def _connect(self):
        self.sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout)
        self.sock.settimeout(self.timeout)

    def _reconnect(self, attempt, exc, sleep_s):
        _log.warning(
            "ps client %s: %s to %s:%s failed (%s); reconnecting in "
            "%.2fs (attempt %d/%d)", self.session[:8], "call", self.host,
            self.port, exc, sleep_s, attempt, self.max_retries)
        self.close()

    def _roundtrip(self, req):
        if self.sock is None:
            self._connect()
        fault.inject("kvstore.send", detail=str(req[0]))
        _send_msg(self.sock, req)
        fault.inject("kvstore.recv", detail=str(req[0]))
        return _recv_msg(self.sock)

    def call(self, cmd, key=None, payload=None):
        # seq issuance happens under the SAME lock as the roundtrip:
        # clients are shared across threads (P3's background sender +
        # the main thread), and a torn increment would hand two live
        # pushes the same seq — the server would dedup a real gradient
        with self.lock:
            if cmd == "push":
                # same seq across retries of this call: the dedup identity
                seq = self._seq[key] = self._seq.get(key, -1) + 1
                payload = {"__ps__": 1, "data": payload,
                           "sess": self.session, "seq": seq}
            elif cmd == "pull" and key in self._seq:
                # tell the server which round our own pushes reached so
                # the sync wait targets that round, not global quiescence
                payload = {"__ps__": 1, "sess": self.session,
                           "after_seq": self._seq[key]}
            elif cmd == "barrier":
                # barriers carry a seq too: a retried arrival must not
                # count twice or the barrier releases early
                self._barrier_seq += 1
                payload = {"__ps__": 1, "sess": self.session,
                           "seq": self._barrier_seq}
            req = (cmd, key, payload)
            if cmd == "stop":
                # best-effort: a lost ack means the server is already down
                try:
                    self._roundtrip(req)
                except (ConnectionError, TimeoutError, OSError):
                    pass
                finally:
                    self.close()
                return None
            try:
                ok, out = fault.retry(
                    lambda: self._roundtrip(req),
                    max_attempts=self.max_retries,
                    retryable=(ConnectionError, TimeoutError, OSError),
                    on_retry=self._reconnect)
            except (ConnectionError, TimeoutError, OSError) as e:
                self.close()
                raise PSTimeoutError(
                    f"ps {cmd} for key {key!r} failed after "
                    f"{self.max_retries} attempts to {self.host}:"
                    f"{self.port}: {e}") from e
        if not ok:
            kind, sep, msg = str(out).partition(": ")
            if sep:
                raise get_error_class(kind)(f"ps server error: {msg}")
            raise RuntimeError(f"ps server error: {out}")
        return out

    def heartbeat(self, timeout=5.0):
        """Liveness probe: server vitals, or raises PSTimeoutError.

        One shot on a dedicated connection with a SHORT budget — a
        health probe that rides the full retry pipeline (minutes
        against a hung server) answers slower than the failure it is
        meant to diagnose."""
        try:
            with socket.create_connection((self.host, self.port),
                                          timeout=timeout) as s:
                s.settimeout(timeout)
                _send_msg(s, ("heartbeat", None, None))
                ok, out = _recv_msg(s)
        except (ConnectionError, TimeoutError, OSError) as e:
            raise PSTimeoutError(
                f"ps heartbeat to {self.host}:{self.port} failed "
                f"within {timeout:.0f}s: {e}") from e
        if not ok:
            raise RuntimeError(f"ps server error: {out}")
        return out

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
