"""2-bit gradient compression with error feedback.

Reference: src/kvstore/gradient_compression.h:38-131 (.cc/.cu kernels).
TPU re-design: the quantize/dequantize round-trip is a fused XLA kernel;
residual (error-feedback) state is kept per-key on device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["GradientCompression"]


class GradientCompression:
    def __init__(self, type="2bit", threshold=0.5):
        if type not in ("2bit", "1bit", "none"):
            raise ValueError(f"unsupported compression type {type}")
        self.type = type
        self.threshold = float(threshold)
        self._residual: dict = {}

        @jax.jit
        def _round_trip_2bit(grad, residual, threshold):
            acc = grad + residual
            q = jnp.where(acc >= threshold, threshold,
                          jnp.where(acc <= -threshold, -threshold, 0.0))
            return q, acc - q

        @jax.jit
        def _round_trip_1bit(grad, residual, threshold):
            acc = grad + residual
            q = jnp.where(acc >= 0, threshold, -threshold)
            return q, acc - q

        self._rt2 = _round_trip_2bit
        self._rt1 = _round_trip_1bit

    def compress_decompress(self, grad, key=None):
        """Quantize-then-dequantize with error feedback (what the wire
        round trip computes end-to-end)."""
        if self.type == "none":
            return grad
        k = key if key is not None else (grad.shape, str(grad.dtype))
        residual = self._residual.get(k)
        if residual is None:
            residual = jnp.zeros_like(grad)
        fn = self._rt2 if self.type == "2bit" else self._rt1
        q, new_residual = fn(grad, residual, self.threshold)
        self._residual[k] = new_residual
        return q
