"""2-bit gradient compression with error feedback.

Reference: src/kvstore/gradient_compression.h:38-131 (.cc/.cu kernels).
TPU re-design: the quantize/dequantize round-trip is a fused XLA kernel;
residual (error-feedback) state is kept per-key on device; and for
data-parallel sync the compressed codes actually cross the wire —
``make_compressed_allreduce`` packs four 2-bit codes per uint8 and
all-gathers the uint8 buffer over the mesh axis (16× less collective
traffic than fp32), dequantizing after the collective.  The reference
packs 16 codes per float32 on the push path (gradient_compression.cc
Quantize2BitKernel); same 2 bits/element density, same
{-threshold, 0, +threshold} codebook, same error-feedback recurrence.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import shard_map_compat

__all__ = ["GradientCompression", "make_compressed_allreduce"]


class GradientCompression:
    def __init__(self, type="2bit", threshold=0.5):
        if type not in ("2bit", "1bit", "none"):
            raise ValueError(f"unsupported compression type {type}")
        self.type = type
        self.threshold = float(threshold)
        self._residual: dict = {}

        @jax.jit  # mxlint: disable=MX-DONATE001(grad is the caller's live gradient and the residual read from self._residual stays bound until the returned one replaces it)
        def _round_trip_2bit(grad, residual, threshold):
            acc = grad + residual
            q = jnp.where(acc >= threshold, threshold,
                          jnp.where(acc <= -threshold, -threshold, 0.0))
            return q, acc - q

        @jax.jit  # mxlint: disable=MX-DONATE001(grad is the caller's live gradient and the residual read from self._residual stays bound until the returned one replaces it)
        def _round_trip_1bit(grad, residual, threshold):
            acc = grad + residual
            q = jnp.where(acc >= 0, threshold, -threshold)
            return q, acc - q

        self._rt2 = _round_trip_2bit
        self._rt1 = _round_trip_1bit

    def compress_decompress(self, grad, key=None):
        """Quantize-then-dequantize with error feedback (what the wire
        round trip computes end-to-end)."""
        if self.type == "none":
            return grad
        k = key if key is not None else (grad.shape, str(grad.dtype))
        residual = self._residual.get(k)
        if residual is None:
            residual = jnp.zeros_like(grad)
        fn = self._rt2 if self.type == "2bit" else self._rt1
        q, new_residual = fn(grad, residual, self.threshold)
        self._residual[k] = new_residual
        return q


def _quantize_2bit(acc, threshold):
    """(n,) float → packed uint8 codes, 4 per byte.

    Codebook (reference gradient_compression.cc Quantize2BitKernel):
    0 → 0, 1 → +threshold, 2 → -threshold.
    """
    codes = jnp.where(acc >= threshold, 1,
                      jnp.where(acc <= -threshold, 2, 0)).astype(jnp.uint8)
    n = codes.shape[0]
    pad = (-n) % 4
    codes = jnp.pad(codes, (0, pad))
    codes = codes.reshape(-1, 4)
    shifts = jnp.array([0, 2, 4, 6], jnp.uint8)
    return jnp.sum(codes << shifts, axis=1).astype(jnp.uint8)


def _dequantize_2bit(packed, n, threshold, dtype):
    shifts = jnp.array([0, 2, 4, 6], jnp.uint8)
    codes = (packed[:, None] >> shifts) & jnp.uint8(3)
    codes = codes.reshape(-1)[:n]
    return jnp.where(codes == 1, threshold,
                     jnp.where(codes == 2, -threshold, 0.0)).astype(dtype)


def make_compressed_allreduce(mesh, axis_name="dp", threshold=0.5):
    """Build ``fn(grad, residual) -> (mean_grad, new_residual)`` whose
    cross-device traffic is 2-bit-packed uint8 (16× less than fp32).

    Runs under ``shard_map`` over ``axis_name``: each rank quantizes its
    local gradient (+residual carry-over), the **packed uint8 codes**
    are all-gathered over the mesh axis — that is the only collective,
    so the wire dtype really is uint8 — and every rank dequantizes and
    averages the gathered codes.  Error feedback keeps what quantization
    dropped for the next step (reference gradient_compression.h:38-131
    semantics, re-laid onto an ICI collective instead of a PS push).

    Works on any pytree of equal-sharded (replicated over axis_name)
    gradients.
    """
    nranks = mesh.shape[axis_name]

    def _one(grad, residual):
        shape, dtype = grad.shape, grad.dtype
        flat = grad.reshape(-1).astype(jnp.float32)
        acc = flat + residual.reshape(-1).astype(jnp.float32)
        packed = _quantize_2bit(acc, threshold)
        q_local = _dequantize_2bit(packed, flat.shape[0], threshold,
                                   jnp.float32)
        new_residual = (acc - q_local).reshape(shape).astype(dtype)
        gathered = lax.all_gather(packed, axis_name)      # uint8 on wire
        total = jnp.zeros_like(flat)
        for r in range(nranks):
            total = total + _dequantize_2bit(gathered[r], flat.shape[0],
                                             threshold, jnp.float32)
        return (total / nranks).reshape(shape).astype(dtype), new_residual

    def body(grads, residuals):
        # leaves arrive as (1, ...): this rank's slice of the stacked
        # per-rank gradient/residual trees
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_r = treedef.flatten_up_to(residuals)
        outs = [_one(g[0], r[0]) for g, r in zip(flat_g, flat_r)]
        mean = jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs])
        res = jax.tree_util.tree_unflatten(treedef,
                                           [o[1][None] for o in outs])
        return mean, res

    from jax.sharding import PartitionSpec as P
    mapped = shard_map_compat(
        body, mesh=mesh,
        in_specs=(P(axis_name), P(axis_name)),
        out_specs=(P(), P(axis_name)))
    return jax.jit(mapped)  # mxlint: disable=MX-DONATE001(grad/residual trees are caller-held — callers re-run the sync on the same gradients; the donating surface is the compressed dp train step below)


def make_compressed_dp_train_step(loss_fn, mesh, lr=0.1, axis_name="dp",
                                  threshold=0.5):
    """Data-parallel SGD step whose gradient sync is 2-bit compressed.

    ``step(params, residuals, batch) -> (params, residuals, loss)``:
    batch sharded over ``axis_name``; each rank computes its local
    gradient, quantizes (+error feedback), all-gathers **uint8** codes
    (the only cross-rank traffic), dequantizes, averages, and applies
    SGD.  Params replicated; residuals carry a leading per-rank axis
    sharded over ``axis_name``.
    """
    nranks = mesh.shape[axis_name]

    def body(params, residuals, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_r = treedef.flatten_up_to(residuals)
        new_params_flat = []
        new_res_flat = []
        for g, r, p in zip(flat_g, flat_r,
                           jax.tree_util.tree_leaves(params)):
            shape, dtype = g.shape, g.dtype
            flat = g.reshape(-1).astype(jnp.float32)
            acc = flat + r[0].reshape(-1).astype(jnp.float32)
            packed = _quantize_2bit(acc, threshold)
            q_local = _dequantize_2bit(packed, flat.shape[0], threshold,
                                       jnp.float32)
            new_res_flat.append((acc - q_local).reshape(shape)
                                .astype(dtype)[None])
            gathered = lax.all_gather(packed, axis_name)  # uint8 on wire
            total = jnp.zeros_like(flat)
            for i in range(nranks):
                total = total + _dequantize_2bit(
                    gathered[i], flat.shape[0], threshold, jnp.float32)
            mean_g = (total / nranks).reshape(shape)
            new_params_flat.append(
                (p.astype(jnp.float32) - lr * mean_g).astype(p.dtype))
        new_params = jax.tree_util.tree_unflatten(treedef, new_params_flat)
        new_res = jax.tree_util.tree_unflatten(treedef, new_res_flat)
        loss_mean = lax.pmean(loss, axis_name)
        return new_params, new_res, loss_mean

    from jax.sharding import PartitionSpec as P
    mapped = shard_map_compat(
        body, mesh=mesh,
        in_specs=(P(), P(axis_name), P(axis_name)),
        out_specs=(P(), P(axis_name), P()))
    # params and residuals are pure carry state (`params, residuals,
    # loss = step(params, residuals, batch)`): donate both so the
    # update aliases them in place; the batch (arg 2) is caller-held
    return jax.jit(mapped, donate_argnums=(0, 1))
