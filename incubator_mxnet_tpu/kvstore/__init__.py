"""KVStore: data-parallel communication (reference src/kvstore/ + python/mxnet/kvstore/).

TPU re-design (SURVEY.md §5.8): ``local``/``device`` reduce over
process-local device copies; ``dist_sync``/``dist_async`` ride XLA
collectives over ICI/DCN through ``jax.distributed``-style process
groups.  The ``KVStoreBase`` plugin registry (reference
python/mxnet/kvstore/base.py:74-220) is preserved as the extension
point (Horovod/BytePS adapters plugged in there).
"""
from .base import KVStoreBase, register, create
from .kvstore import (KVStore, LocalKVStore, DeviceKVStore, DistKVStore,
                      DistAsyncKVStore, P3KVStore)
from .horovod import HorovodKVStore, BytePSKVStore
from .gradient_compression import GradientCompression
