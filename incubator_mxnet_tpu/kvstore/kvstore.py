"""KVStore implementations.

Reference internals being re-designed (SURVEY.md §2.1 "KVStore"):
``KVStoreLocal`` + Comm tree-reduce (src/kvstore/kvstore_local.h:70,
comm.h:104-741), ``KVStoreNCCL``, ``KVStoreDist`` over ps-lite
(kvstore_dist.h).  TPU mapping:

* local/device/nccl → single-controller reduce: values living on
  process-local devices are summed (XLA all-reduce over ICI when the
  arrays are sharded over a mesh; jnp adds otherwise).
* dist_sync/dist_device_sync → device-side XLA all-reduce across
  processes (jit over a process-spanning mesh) when launched via
  tools/launch.py collectives mode, or push/pull against PSServer
  processes when servers were requested; degenerates to local in a
  single process so launch scripts run unchanged.
* dist_async → real parameter-server processes (kvstore/ps_server.py):
  every push applied immediately server-side, no aggregation — the
  reference's async semantics (kvstore_dist_server.h:349), not an alias.
* p3 → priority-sliced dispatch (P3KVStore): tensors sliced at
  MXNET_KVSTORE_SLICE_THRESHOLD and sent highest-priority-first by a
  background sender (reference p3store_dist.h:40-85).
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp

from ..base import get_env
from ..locks import named_condition
from ..error import PSTimeoutError
from ..ndarray import NDArray
from .. import optimizer as opt_mod
from .base import KVStoreBase, register
from .gradient_compression import GradientCompression

__all__ = ["KVStore", "LocalKVStore", "DeviceKVStore", "DistKVStore",
           "DistAsyncKVStore", "P3KVStore"]


class _BaseStore(KVStoreBase):
    """Shared store logic: key→value dict + optional server-side optimizer."""

    def __init__(self):
        self._store: dict = {}
        self._optimizer = None
        self._updater = None
        self._compression: GradientCompression | None = None

    @staticmethod
    def is_capable(capability):
        return capability in (KVStoreBase.OPTIMIZER, KVStoreBase.PUSH_PULL)

    def init(self, key, value):
        keys = key if isinstance(key, (list, tuple)) else [key]
        values = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, values):
            if k not in self._store:
                self._store[k] = NDArray(v.data + 0, ctx=v.ctx)

    def _reduce(self, value):
        """Sum a list of per-device values (Comm::Reduce analog)."""
        if isinstance(value, (list, tuple)):
            acc = value[0].data
            for v in value[1:]:
                acc = acc + v.data
            return acc
        return value.data

    def _sync(self, summed):
        """Cross-process reduction hook; identity for local stores."""
        return summed

    def push(self, key, value, priority=0):
        keys = key if isinstance(key, (list, tuple)) else [key]
        if isinstance(key, (list, tuple)):
            values = value
        else:
            values = [value]
        for k, v in zip(keys, values):
            summed = self._reduce(v)
            if self._compression is not None:
                # key the error-feedback residual by PARAMETER, not by
                # shape: same-shaped params must not share residuals
                summed = self._compression.compress_decompress(summed,
                                                               key=k)
            summed = self._sync(summed)
            if self._updater is not None:
                # server-side optimizer (reference kvstore_dist_server.h:349)
                weight = self._store[k]
                self._updater(k if isinstance(k, int) else hash(k),
                              NDArray(summed), weight)
            else:
                self._store[k] = NDArray(summed)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = key if isinstance(key, (list, tuple)) else [key]
        outs = out if isinstance(out, (list, tuple)) else [out] * len(keys)
        results = []
        for k, o in zip(keys, outs):
            val = self._store[k]
            if o is not None:
                targets = o if isinstance(o, (list, tuple)) else [o]
                for t in targets:
                    t._set_data(val.data)
                results.append(o)
            else:
                results.append(val.copy())
        if out is not None:
            return out
        return results if isinstance(key, (list, tuple)) else results[0]

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            return self.pull(key, out=out, priority=priority)
        return None

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (reference kvstore_dist.h:558)."""
        val = self._store[key]
        if row_ids is None:
            return self.pull(key, out=out, priority=priority)
        ids = row_ids.data.astype(jnp.int32) if isinstance(row_ids, NDArray) \
            else jnp.asarray(row_ids, jnp.int32)
        rows = val.data[ids]
        if out is not None:
            out._set_data(out.data.at[ids].set(rows))
            return out
        return NDArray(rows)

    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        self._compression = GradientCompression(**dict(compression_params))

    def close(self):
        """Release any background resources (threads, sockets).  Base
        stores own none; transports with senders override and join."""

    def save_optimizer_states(self, fname, dump_optimizer=False):
        with open(fname, "wb") as f:
            if self._updater is not None:
                f.write(self._updater.get_states(dump_optimizer))
            else:
                f.write(pickle.dumps({}))

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            if self._updater is not None:
                self._updater.set_states(f.read())


@register
class LocalKVStore(_BaseStore):
    """Single-process store; CPU-side aggregation (reference 'local')."""

    OPT_TYPES = ["local", "local_allreduce_cpu"]


@register
class DeviceKVStore(_BaseStore):
    """Aggregation on accelerator (reference 'device'/'nccl' kvstores).

    Values stay on device; XLA emits the reduction (ICI collective when
    arrays are sharded over a mesh).
    """

    OPT_TYPES = ["device", "nccl", "local_allreduce_device"]


def _maybe_init_jax_distributed():
    """Join the coordination service from launcher env.  The real join
    happens at package import (incubator_mxnet_tpu._join_distributed_
    from_env — jax requires it before any backend touch); this is a
    late-import safety net for embedders that set the env after import.
    """
    from .. import _join_distributed_from_env
    _join_distributed_from_env()


def _ps_clients():
    """Connect to launcher-spawned parameter servers, if any."""
    import os
    servers = os.environ.get("MXT_SERVERS", "")
    if not servers:
        return []
    from .ps_server import PSClient
    out = []
    for hp in servers.split(","):
        host, _, port = hp.partition(":")
        out.append(PSClient(host, int(port)))
    return out


@register
class DistKVStore(_BaseStore):
    """Multi-process synchronous store (reference 'dist_sync' family,
    kvstore_dist.h:218 PushPullImpl + kvstore_dist_server.h sync mode).

    Two transports, chosen by the launcher env:

    * **collective** (no ``-s`` servers): gradients all-reduce across
      processes as a device-side XLA collective — the local summed shard
      becomes one row of a process-spanning global array and a jitted
      replicated-output sum lowers to an all-reduce over DCN/ICI
      (strictly device-side, unlike a host allgather).
    * **parameter server** (``-s N``): push/pull go to the PSServer
      processes with keys sharded over servers by hash — the reference's
      EncodeDefaultKey sharding (kvstore_dist.h:58).
    """

    OPT_TYPES = ["dist_sync", "dist_device_sync", "dist",
                 "dist_sync_device"]
    _PS_MODE = "sync"

    def __init__(self):
        super().__init__()
        _maybe_init_jax_distributed()
        self._nprocs = jax.process_count()
        self._rank = jax.process_index()
        self._clients = _ps_clients()
        import os
        if self._clients and os.environ.get("MXT_KV_MODE",
                                            self._PS_MODE) != self._PS_MODE:
            raise RuntimeError(
                f"launcher started servers in mode "
                f"{os.environ['MXT_KV_MODE']!r} but this store is "
                f"{self._PS_MODE!r}; pass --kv-mode {self._PS_MODE}")
        self._psum_cache: dict = {}
        import os as _os
        self._nworkers_env = int(_os.environ.get("MXT_NUM_WORKERS",
                                                 self._nprocs))

    @property
    def rank(self):
        import os
        return int(os.environ.get("MXT_WORKER_ID", self._rank))

    @property
    def num_workers(self):
        return max(self._nprocs, self._nworkers_env)

    # -- PS transport -----------------------------------------------------
    def _server_for(self, key):
        # stable across processes (Python hash() is per-process salted);
        # reference: EncodeDefaultKey (kvstore_dist.h:58)
        import zlib
        return self._clients[zlib.crc32(str(key).encode())
                             % len(self._clients)]

    def init(self, key, value):
        if not self._clients:
            return super().init(key, value)
        keys = key if isinstance(key, (list, tuple)) else [key]
        values = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, values):
            if self.rank == 0:
                self._server_for(k).call("init", k, _onp_of(v))
        self.barrier()

    def push(self, key, value, priority=0):
        if not self._clients:
            return super().push(key, value, priority)
        keys = key if isinstance(key, (list, tuple)) else [key]
        values = value if isinstance(key, (list, tuple)) else [value]
        for k, v in zip(keys, values):
            summed = self._reduce(v)
            if self._compression is not None:
                summed = self._compression.compress_decompress(summed,
                                                               key=k)
            self._server_for(k).call("push", k, _onp_of(summed))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if not self._clients:
            return super().pull(key, out=out, priority=priority)
        keys = key if isinstance(key, (list, tuple)) else [key]
        outs = out if isinstance(out, (list, tuple)) else [out] * len(keys)
        results = []
        for k, o in zip(keys, outs):
            val = NDArray(jnp.asarray(self._server_for(k).call("pull", k)))
            if o is not None:
                targets = o if isinstance(o, (list, tuple)) else [o]
                for t in targets:
                    t._set_data(val.data)
                results.append(o)
            else:
                results.append(val)
        if out is not None:
            return out
        return results if isinstance(key, (list, tuple)) else results[0]

    def set_optimizer(self, optimizer):
        if not self._clients:
            return super().set_optimizer(optimizer)
        # serialize to every server (reference kv.set_optimizer →
        # SendCommandToServers kvstore_dist.h:90)
        for c in self._clients:
            c.call("set_optimizer", None, pickle.dumps(optimizer))

    # -- collective transport ---------------------------------------------
    def _sync(self, summed):
        if self._nprocs <= 1 or self._clients:
            return summed
        import numpy as onp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devs = onp.asarray(jax.devices()).reshape(self._nprocs, -1)[:, 0]
        mesh = Mesh(devs, ("proc",))
        sharding = NamedSharding(mesh, P("proc"))
        local = onp.asarray(summed)[None]
        garr = jax.make_array_from_process_local_data(
            sharding, local, (self._nprocs,) + local.shape[1:])
        fn = self._psum_cache.get("fn")
        if fn is None:
            # the stacked global array is built fresh per sync: donate
            # it so the reduction reuses its buffer (memlint)
            fn = jax.jit(lambda x: jnp.sum(x, axis=0),
                         donate_argnums=(0,),
                         out_shardings=NamedSharding(mesh, P()))
            self._psum_cache["fn"] = fn
        out = fn(garr)
        return jnp.asarray(out.addressable_data(0))

    def barrier(self):
        if self._clients:
            self._server_for("__barrier__").call("barrier")
            return
        if self._nprocs > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")

    def check_health(self):
        """Probe every parameter server (``heartbeat``).  Returns a list
        of per-server vitals dicts; a dead server raises
        :class:`~incubator_mxnet_tpu.error.PSTimeoutError` naming it
        (reference role: ps-lite Postoffice heartbeat/Van monitoring).
        Collective transport has no servers: returns []."""
        return [c.heartbeat() for c in self._clients]

    # -- elastic membership (docs/fault_tolerance.md "Elasticity") ------
    def join(self, rank=None):
        """Enter the fleet: register this worker (declared dp-rank =
        launcher rank unless given) with EVERY parameter server's
        membership table.  Idempotent; also the re-admission step after
        :class:`~incubator_mxnet_tpu.error.WorkerEvictedError`.
        Collective transport has no membership: no-op."""
        rank = self.rank if rank is None else rank
        return [c.join(rank) for c in self._clients]

    def leave(self):
        """Gracefully exit the fleet: sync rounds re-balance to the
        survivors immediately instead of burning the heartbeat budget."""
        return [c.leave() for c in self._clients]

    def beat(self):
        """One membership heartbeat against every server; returns their
        vitals.  Raises the typed
        :class:`~incubator_mxnet_tpu.error.WorkerEvictedError` when any
        server has evicted this worker — the beat delivers the
        eviction notice."""
        return [c.beat() for c in self._clients]

    @property
    def live_workers(self):
        """Live fleet size, BEST-EFFORT: the smallest membership count
        any reachable server reports (servers evict independently; the
        tightest view is the safe one to re-balance on).  Falls back to
        ``num_workers`` when membership is inactive (collective
        transport, no joins) or no server answered its probe — a
        property read must never raise or hang on a dead server (use
        :meth:`check_health` for a raising probe)."""
        if not self._clients:
            return self.num_workers
        counts = []
        for c in self._clients:
            try:
                counts.append(c.heartbeat().get("live_workers", 0))
            except (ConnectionError, TimeoutError):
                continue   # unreachable server: skip, don't raise
        live = min(counts) if counts else 0
        return live if live > 0 else self.num_workers


def _onp_of(v):
    import numpy as onp
    if isinstance(v, NDArray):
        return onp.asarray(v.data)
    return onp.asarray(v)


@register
class DistAsyncKVStore(DistKVStore):
    """Asynchronous parameter-server store (reference 'dist_async',
    kvstore_dist_server.h:349 else-branch: apply every push immediately,
    no aggregation, workers race).

    Requires PS transport (launch with ``-s N --kv-mode async``); in a
    single process without servers it degrades to immediate local apply,
    which is exactly async semantics with one worker.
    """

    OPT_TYPES = ["dist_async"]
    _PS_MODE = "async"

    def _sync(self, summed):
        # async never aggregates across workers
        return summed


@register
class P3KVStore(DistKVStore):
    """Priority-sliced parameter propagation (reference 'p3',
    p3store_dist.h:40-85).

    Large tensors are sliced into ``MXNET_KVSTORE_SLICE_THRESHOLD``-
    element chunks; slices are dispatched highest-priority-first by a
    background sender so small, high-priority (late-layer-first
    backward order: priority = -key, trainer.py:390) tensors overtake
    bulk traffic — the reference's scheduling gain, reproduced at the
    transport layer.
    """

    OPT_TYPES = ["p3", "dist_sync_p3"]

    def __init__(self):
        super().__init__()
        import os
        import queue
        import threading
        self._slice = int(os.environ.get("MXNET_KVSTORE_SLICE_THRESHOLD",
                                         "40000"))
        self._q: "queue.PriorityQueue" = queue.PriorityQueue()
        self._pending: dict = {}
        self._seq = 0
        self._cv = named_condition("kvstore.sendq")
        self._gate = threading.Event()
        self._gate.set()           # tests clear this to stage a backlog
        self._sender = threading.Thread(target=self._drain, daemon=True)
        self._sender.start()
        self.send_log: list = []   # (key, slice_idx) in wire order; for tests

    def _drain(self):
        while True:
            _prio, _seq, item = self._q.get()
            if item is None:
                return
            self._gate.wait()
            key, idx, chunk = item
            try:
                self._push_slice(key, idx, chunk)
                err = None
            except Exception as e:  # mxlint: allow-broad-except(banked as the sender error and rethrown on the next pull)
                err = e
            with self._cv:
                if err is not None:
                    self._sender_error = err
                self._pending[key] -= 1
                if self._pending[key] == 0:
                    self._cv.notify_all()

    _SEND_LOG_CAP = 4096  # diagnostics ring; not a full history

    def _push_slice(self, key, idx, chunk):
        if len(self.send_log) >= self._SEND_LOG_CAP:
            del self.send_log[:self._SEND_LOG_CAP // 2]
        self.send_log.append((key, idx))
        skey = f"{key}#({idx})"
        summed = self._sync(chunk)
        if self._updater is not None and skey in self._store:
            self._updater(hash(skey), NDArray(summed), self._store[skey])
        elif self._clients:
            self._server_for(skey).call("push", skey, _onp_of(summed))
        else:
            self._store[skey] = NDArray(jnp.asarray(summed))

    def _slices(self, flat):
        n = flat.shape[0]
        return [(i // self._slice, flat[i:i + self._slice])
                for i in range(0, n, self._slice)]

    def close(self):
        """Flush the priority queue and join the background sender.

        The sentinel sorts after every real slice (``inf`` priority), so
        pending traffic still drains in wire order before the thread
        exits.  Idempotent."""
        sender = self._sender
        if sender is None:
            return
        self._gate.set()        # a test-staged backlog must not wedge the join
        self._seq += 1
        self._q.put((float("inf"), self._seq, None))
        sender.join(timeout=10.0)
        if not sender.is_alive():
            self._sender = None

    def init(self, key, value):
        keys = key if isinstance(key, (list, tuple)) else [key]
        values = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, values):
            flat = v.data.reshape(-1)
            self._shapes = getattr(self, "_shapes", {})
            self._shapes[k] = v.shape
            for idx, chunk in self._slices(flat):
                skey = f"{k}#({idx})"
                if self._clients:
                    if self.rank == 0:
                        self._server_for(skey).call("init", skey,
                                                    _onp_of(chunk))
                else:
                    self._store[skey] = NDArray(chunk + 0)
        if self._clients:
            self.barrier()

    def push(self, key, value, priority=0):
        keys = key if isinstance(key, (list, tuple)) else [key]
        values = value if isinstance(key, (list, tuple)) else [value]
        for k, v in zip(keys, values):
            summed = self._reduce(v)
            flat = summed.reshape(-1)
            chunks = self._slices(flat)
            with self._cv:
                self._pending[k] = self._pending.get(k, 0) + len(chunks)
            for idx, chunk in chunks:
                self._seq += 1
                # PriorityQueue pops smallest: negate so HIGH priority
                # (reference: priority = -key, higher = sooner) pops first
                self._q.put((-priority, self._seq, (k, idx, chunk)))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = key if isinstance(key, (list, tuple)) else [key]
        outs = out if isinstance(out, (list, tuple)) else [out] * len(keys)
        results = []
        timeout = get_env("MXNET_KVSTORE_TIMEOUT", 60.0, float)
        for k, o in zip(keys, outs):
            with self._cv:
                flushed = self._cv.wait_for(
                    lambda: self._pending.get(k, 0) == 0, timeout=timeout)
                err = getattr(self, "_sender_error", None)
                remaining = self._pending.get(k, 0)
            if err is not None:
                raise RuntimeError(
                    f"p3 background sender failed: {err}") from err
            if not flushed:
                raise PSTimeoutError(
                    f"p3 pull: {remaining} pushed slice(s) "
                    f"for key {k!r} not flushed in {timeout:.0f}s")
            shape = self._shapes[k]
            parts = []
            idx = 0
            total = 1
            for s in shape:
                total *= s
            while idx * self._slice < total:
                skey = f"{k}#({idx})"
                if self._clients:
                    parts.append(jnp.asarray(
                        self._server_for(skey).call("pull", skey)))
                else:
                    parts.append(self._store[skey].data)
                idx += 1
            val = NDArray(jnp.concatenate(parts).reshape(shape)
                          if len(parts) > 1 else parts[0].reshape(shape))
            if o is not None:
                targets = o if isinstance(o, (list, tuple)) else [o]
                for t in targets:
                    t._set_data(val.data)
                results.append(o)
            else:
                results.append(val)
        if out is not None:
            return out
        return results if isinstance(key, (list, tuple)) else results[0]


class KVStore(_BaseStore):
    """Generic facade kept for ``mx.kv.KVStore`` type checks."""

    OPT_TYPES = ["kvstore"]
