"""KVStore implementations.

Reference internals being re-designed (SURVEY.md §2.1 "KVStore"):
``KVStoreLocal`` + Comm tree-reduce (src/kvstore/kvstore_local.h:70,
comm.h:104-741), ``KVStoreNCCL``, ``KVStoreDist`` over ps-lite
(kvstore_dist.h).  TPU mapping:

* local/device/nccl → single-controller reduce: values living on
  process-local devices are summed (XLA all-reduce over ICI when the
  arrays are sharded over a mesh; jnp adds otherwise).
* dist_sync/dist_device_sync → multi-process psum via
  ``jax.make_array_from_process_local_data`` + jit-compiled global sum
  when ``jax.distributed`` is initialized; degenerates to local in a
  single process so launch scripts run unchanged.
* dist_async / p3 — the reference's parameter-server behaviors; served
  by the same sync collective with server-side-optimizer support on the
  store (set_optimizer + update-on-push), async semantics documented as
  sync-on-TPU (SPMD has no stragglers to hide).
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp

from ..ndarray import NDArray
from .. import optimizer as opt_mod
from .base import KVStoreBase, register
from .gradient_compression import GradientCompression

__all__ = ["KVStore", "LocalKVStore", "DeviceKVStore", "DistKVStore"]


class _BaseStore(KVStoreBase):
    """Shared store logic: key→value dict + optional server-side optimizer."""

    def __init__(self):
        self._store: dict = {}
        self._optimizer = None
        self._updater = None
        self._compression: GradientCompression | None = None

    @staticmethod
    def is_capable(capability):
        return capability in (KVStoreBase.OPTIMIZER, KVStoreBase.PUSH_PULL)

    def init(self, key, value):
        keys = key if isinstance(key, (list, tuple)) else [key]
        values = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, values):
            if k not in self._store:
                self._store[k] = NDArray(v.data + 0, ctx=v.ctx)

    def _reduce(self, value):
        """Sum a list of per-device values (Comm::Reduce analog)."""
        if isinstance(value, (list, tuple)):
            acc = value[0].data
            for v in value[1:]:
                acc = acc + v.data
            return acc
        return value.data

    def _sync(self, summed):
        """Cross-process reduction hook; identity for local stores."""
        return summed

    def push(self, key, value, priority=0):
        keys = key if isinstance(key, (list, tuple)) else [key]
        if isinstance(key, (list, tuple)):
            values = value
        else:
            values = [value]
        for k, v in zip(keys, values):
            summed = self._reduce(v)
            if self._compression is not None:
                summed = self._compression.compress_decompress(summed)
            summed = self._sync(summed)
            if self._updater is not None:
                # server-side optimizer (reference kvstore_dist_server.h:349)
                weight = self._store[k]
                self._updater(k if isinstance(k, int) else hash(k),
                              NDArray(summed), weight)
            else:
                self._store[k] = NDArray(summed)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = key if isinstance(key, (list, tuple)) else [key]
        outs = out if isinstance(out, (list, tuple)) else [out]
        results = []
        for k, o in zip(keys, outs):
            val = self._store[k]
            if o is not None:
                targets = o if isinstance(o, (list, tuple)) else [o]
                for t in targets:
                    t._set_data(val.data)
                results.append(o)
            else:
                results.append(val.copy())
        if out is not None:
            return out
        return results if isinstance(key, (list, tuple)) else results[0]

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            return self.pull(key, out=out, priority=priority)
        return None

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (reference kvstore_dist.h:558)."""
        val = self._store[key]
        if row_ids is None:
            return self.pull(key, out=out, priority=priority)
        ids = row_ids.data.astype(jnp.int32) if isinstance(row_ids, NDArray) \
            else jnp.asarray(row_ids, jnp.int32)
        rows = val.data[ids]
        if out is not None:
            out._set_data(out.data.at[ids].set(rows))
            return out
        return NDArray(rows)

    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        self._compression = GradientCompression(**dict(compression_params))

    def save_optimizer_states(self, fname, dump_optimizer=False):
        with open(fname, "wb") as f:
            if self._updater is not None:
                f.write(self._updater.get_states(dump_optimizer))
            else:
                f.write(pickle.dumps({}))

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            if self._updater is not None:
                self._updater.set_states(f.read())


@register
class LocalKVStore(_BaseStore):
    """Single-process store; CPU-side aggregation (reference 'local')."""

    OPT_TYPES = ["local", "local_allreduce_cpu"]


@register
class DeviceKVStore(_BaseStore):
    """Aggregation on accelerator (reference 'device'/'nccl' kvstores).

    Values stay on device; XLA emits the reduction (ICI collective when
    arrays are sharded over a mesh).
    """

    OPT_TYPES = ["device", "nccl", "local_allreduce_device"]


@register
class DistKVStore(_BaseStore):
    """Multi-process synchronous store (reference 'dist_sync' family).

    When ``jax.distributed`` has been initialized (multi-host), the sync
    step all-reduces across processes over DCN/ICI; in a single process
    it is the identity so dist launch scripts degrade gracefully.
    """

    OPT_TYPES = ["dist_sync", "dist_device_sync", "dist_async", "dist",
                 "p3", "dist_sync_device", "horovod", "byteps"]

    def __init__(self):
        super().__init__()
        self._nprocs = jax.process_count()
        self._rank = jax.process_index()

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._nprocs

    def _sync(self, summed):
        if self._nprocs <= 1:
            return summed
        from jax.experimental import multihost_utils
        return multihost_utils.process_allgather(summed).sum(axis=0)

    def barrier(self):
        if self._nprocs > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")


class KVStore(_BaseStore):
    """Generic facade kept for ``mx.kv.KVStore`` type checks."""

    OPT_TYPES = ["kvstore"]
