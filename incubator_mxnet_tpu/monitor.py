"""Monitor: tap intermediate outputs during training (reference monitor.py).

The reference installs an engine-level callback on every executor op
(MXExecutorSetMonitorCallback).  Here blocks expose a forward hook
mechanism; Monitor installs stat functions over named outputs.
"""
from __future__ import annotations

import re

from .ndarray import NDArray


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = interval
        self.stat_func = stat_func or (lambda x: NDArray(abs(x.data).mean()))
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.queue = []
        self.step = 0
        self.activated = False
        self._handles = []

    def install(self, block):
        """Attach to a gluon Block: records every child block's output."""

        def make_hook(name):
            def hook(blk, inputs, output):
                if self.activated and self.re_pattern.match(name):
                    outs = output if isinstance(output, (list, tuple)) else [output]
                    for i, o in enumerate(outs):
                        if isinstance(o, NDArray):
                            self.queue.append(
                                (self.step, f"{name}_output{i}",
                                 self.stat_func(o)))
            return hook

        for name, child in block._children.items():
            self._handles.append(child.register_forward_hook(make_hook(name)))
        return self

    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
        self.queue = []

    def toc(self):
        if not self.activated:
            self.step += 1
            return []
        self.activated = False
        self.step += 1
        res = [(n, k, v.asnumpy()) for n, k, v in self.queue]
        if self.sort:
            res.sort(key=lambda x: x[1])
        return res

    def toc_print(self):
        for n, k, v in self.toc():
            print(f"Batch: {n:7d} {k:30s} {v}")
