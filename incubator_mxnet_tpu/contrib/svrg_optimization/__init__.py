"""SVRG optimization (reference contrib/svrg_optimization/)."""
from .svrg_optimizer import SVRGOptimizer
from .svrg_module import SVRGModule
