"""SVRGModule (reference contrib/svrg_optimization/svrg_module.py:30).

Extends the Module training loop with the SVRG schedule: every
``update_freq`` epochs, snapshot the weights and accumulate the full
gradient mu over the dataset (reference update_full_grads:292); each
batch then applies the variance-reduced gradient
g_i(w) - g_i(w_snapshot) + mu through the base updater.
"""
from __future__ import annotations

from ...module import Module
from ...ndarray import NDArray
from ... import ndarray as nd


class SVRGModule(Module):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        self.update_freq = update_freq
        self._param_snapshot = {}
        self._mu = {}
        self._last_batch = None

    def forward(self, data_batch, is_train=None):
        self._last_batch = data_batch
        return super().forward(data_batch, is_train=is_train)

    def _grads(self):
        return {name: self._exec_group.sum_grad(name)
                for name in self._param_names}

    def update_full_grads(self, train_data):
        """Snapshot weights and accumulate the full-dataset gradient mu
        (reference svrg_module.py:292)."""
        arg_params, _ = self.get_params()
        self._param_snapshot = {k: v.copy() for k, v in arg_params.items()}
        sums = {k: nd.zeros(v.shape) for k, v in arg_params.items()}
        nbatch = 0
        train_data.reset()
        for batch in train_data:
            self.forward(batch, is_train=True)
            self.backward()
            for name, g in self._grads().items():
                if g is not None:
                    sums[name] = NDArray(sums[name].data + g.data)
            nbatch += 1
        train_data.reset()
        self._mu = {k: NDArray(v.data / max(nbatch, 1))
                    for k, v in sums.items()}

    def update_svrg(self):
        """One variance-reduced step for the last forwarded batch
        (falls back to a plain update before the first snapshot)."""
        if not self._param_snapshot:
            return self.update()
        assert self._last_batch is not None, "forward a batch first"
        cur_grads = {k: (v.copy() if v is not None else None)
                     for k, v in self._grads().items()}
        # gradient of the SAME batch at the snapshot weights
        current = {k: v.copy() for k, v in self.get_params()[0].items()}
        self._exec_group.set_params(self._param_snapshot, allow_extra=True)
        super().forward(self._last_batch, is_train=True)
        self.backward()
        snap_grads = {k: (v.copy() if v is not None else None)
                      for k, v in self._grads().items()}
        self._exec_group.set_params(current, allow_extra=True)
        for i, name in enumerate(self._param_names):
            g, gs = cur_grads[name], snap_grads[name]
            if g is None:
                continue
            corrected = NDArray(g.data - gs.data + self._mu[name].data)
            self._updater(i, corrected, self._arg_params[name])
        self._exec_group.set_params(self._arg_params, allow_extra=True)

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            kvstore="local", num_epoch=1, initializer=None,
            batch_end_callback=None, epoch_end_callback=None):
        """Training loop with the SVRG schedule (reference
        svrg_module.py fit): refresh the snapshot every update_freq
        epochs, variance-reduced updates in between."""
        from ...gluon import metric as metric_mod
        if not self.binded:
            first = next(iter(train_data))
            train_data.reset()
            self.bind(
                data_shapes=[(self._data_names[0], first.data[0].shape)],
                label_shapes=[(self._label_names[0],
                               first.label[0].shape)],
                for_training=True)
        if not self.params_initialized:
            self.init_params(initializer) if initializer is not None \
                else self.init_params()
        if not self.optimizer_initialized:
            self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                optimizer_params=optimizer_params)
        metric = metric_mod.create(eval_metric) \
            if isinstance(eval_metric, str) else eval_metric
        for epoch in range(num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            train_data.reset()
            metric.reset()
            for batch in train_data:
                self.forward(batch, is_train=True)
                self.backward()
                # score BEFORE update_svrg: it re-forwards the batch at
                # the snapshot weights, which would poison the metric
                self.update_metric(metric, batch.label)
                self.update_svrg()
            if epoch_end_callback is not None:
                epoch_end_callback(epoch, self._symbol, *self.get_params())
        if eval_data is not None:
            val = metric_mod.create(eval_metric) \
                if isinstance(eval_metric, str) else eval_metric
            self.score(eval_data, val)
        return metric
