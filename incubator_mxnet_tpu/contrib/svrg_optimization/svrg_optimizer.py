"""SVRG optimizer wrapper (reference contrib/svrg_optimization/
svrg_optimizer.py:66).

Stochastic Variance Reduced Gradient: the effective gradient for a
batch is  g_i(w) - g_i(w_snapshot) + mu  where mu is the full-dataset
gradient at the snapshot weights.  This wrapper delegates the actual
update to any registered optimizer after the variance correction; the
special index convention (the reference routes snapshot-gradient slots
through the same kvstore by key offset) is replaced here by explicit
arrays handed in by SVRGModule.
"""
from __future__ import annotations

from ... import optimizer as opt_mod
from ...ndarray import NDArray


class SVRGOptimizer(opt_mod.Optimizer):
    """update(w) with variance-reduced gradient; wraps a base optimizer."""

    def __init__(self, default_optimizer="sgd", **kwargs):
        base_kwargs = dict(kwargs)
        super().__init__(**{k: v for k, v in kwargs.items()
                            if k in ("learning_rate", "rescale_grad", "wd",
                                     "clip_gradient", "lr_scheduler")})
        if isinstance(default_optimizer, str):
            self.default_opt = opt_mod.create(default_optimizer, **base_kwargs)
        else:
            self.default_opt = default_optimizer

    def create_state(self, index, weight):
        return self.default_opt.create_state(index, weight)

    def update_svrg(self, index, weight, grad, grad_snapshot, mu, state):
        """The SVRG correction + delegated update."""
        corrected = NDArray(grad.data - grad_snapshot.data + mu.data)
        self.default_opt.update(index, weight, corrected, state)

    def update(self, index, weight, grad, state):
        # plain passthrough (used before the first snapshot exists)
        self.default_opt.update(index, weight, grad, state)
