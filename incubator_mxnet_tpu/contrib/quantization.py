"""Post-training int8 quantization (reference python/mxnet/contrib/
quantization.py + src/operator/quantization/calibrate.cc,
quantize_graph_pass.cc).

Flow kept from the reference: (1) run calibration batches through the
fp32 net collecting per-layer output stats, (2) pick thresholds by
``calib_mode`` — 'naive' (min/max) or 'entropy' (KL-divergence optimal
clip, calibrate.cc LogKL histogram search), (3) rewrite the network so
Dense/Conv2D run as int8 MXU ops with (de)quantize glue. Instead of the
reference's symbol-graph pass the rewrite wraps Gluon blocks — the XLA
graph after hybridize sees the same quantize→int8-op→dequantize chain
and fuses the glue.
"""
from __future__ import annotations

import numpy as onp

from .. import ndarray as nd
from ..ndarray import NDArray
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ops import quantization_ops as qops

__all__ = ["quantize_net", "CalibrationCollector", "optimal_threshold_kl"]


def optimal_threshold_kl(arr, num_bins=8001, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| for int8 clipping (reference
    calibrate.cc:LogKL / the original TensorRT-style search)."""
    arr = onp.abs(onp.asarray(arr, dtype=onp.float64).ravel())
    amax = float(arr.max()) if arr.size else 0.0
    if amax <= 0:
        return 1e-8
    hist, edges = onp.histogram(arr, bins=num_bins, range=(0, amax))
    total = hist.sum()
    best_div, best_t = onp.inf, amax
    # candidate thresholds sweep the upper half of the histogram
    for i in range(num_quantized_bins, num_bins + 1,
                   max((num_bins - num_quantized_bins) // 64, 1)):
        t = edges[i] if i < len(edges) else amax
        sliced = hist[:i].astype(onp.float64)
        p = sliced.copy()
        p[-1] += hist[i:].sum()  # reference keeps the clipped mass in p
        if p.sum() == 0:
            continue
        # q approximates the UNCLIPPED slice with num_quantized_bins
        # levels — the clipped tail mass present in p but not q is what
        # penalizes over-aggressive thresholds (calibrate.cc SmoothDistribution)
        factor = i / num_quantized_bins
        idx = onp.minimum((onp.arange(i) / factor).astype(onp.int64),
                          num_quantized_bins - 1)
        q_small = onp.zeros(num_quantized_bins)
        onp.add.at(q_small, idx, sliced)
        counts = onp.zeros(num_quantized_bins)
        onp.add.at(counts, idx, (sliced > 0).astype(onp.float64))
        q = onp.zeros(i)
        nz = counts[idx] > 0
        safe = onp.maximum(counts[idx], 1.0)
        q[nz] = (q_small[idx] / safe)[nz]
        p_n = p / p.sum()
        qs = q.sum()
        if qs == 0:
            continue
        q_n = q / qs
        mask = p_n > 0
        div = float(onp.sum(p_n[mask] *
                            onp.log(p_n[mask] / onp.maximum(q_n[mask],
                                                            1e-12))))
        if div < best_div:
            best_div, best_t = div, t
    return float(best_t)


class CalibrationCollector:
    """Accumulates per-layer activation stats over calibration batches
    (reference _LayerOutputMinMaxCollector / _LayerHistogramCollector).

    Entropy mode accumulates a symmetric HISTOGRAM per layer (the
    reference's _LayerHistogramCollector approach) instead of retaining
    raw samples — calibration memory is O(num_bins) per layer however
    many batches run.  The range starts at 2x the first batch's amax
    and GROWS when a later batch exceeds it: prior counts are rebinned
    into the widened histogram by bin center (the reference's
    include_layer rebinning compromise), so a degenerate first batch
    (e.g. all-zero padding) cannot freeze the range and clip every
    subsequent real activation into the edge bins."""

    def __init__(self, mode="naive", num_bins=8001):
        assert mode in ("naive", "entropy")
        self.mode = mode
        self.num_bins = num_bins if num_bins % 2 == 1 else num_bins + 1
        self.minmax: dict = {}
        self.hists: dict = {}
        self.edges: dict = {}

    def collect(self, name, arr):
        a = onp.asarray(arr.asnumpy() if isinstance(arr, NDArray) else arr)
        lo, hi = float(a.min()), float(a.max())
        if name in self.minmax:
            plo, phi = self.minmax[name]
            self.minmax[name] = (min(lo, plo), max(hi, phi))
        else:
            self.minmax[name] = (lo, hi)
        if self.mode == "entropy":
            amax = max(abs(lo), abs(hi), 1e-8) * 2.0
            if name not in self.hists:
                self.edges[name] = onp.linspace(-amax, amax,
                                                self.num_bins + 1)
                self.hists[name] = onp.zeros(self.num_bins, onp.float64)
            elif amax > self.edges[name][-1]:
                # widen and rebin accumulated counts by old-bin center
                old_edges, old_hist = self.edges[name], self.hists[name]
                new_edges = onp.linspace(-amax, amax, self.num_bins + 1)
                centers = (old_edges[:-1] + old_edges[1:]) / 2.0
                self.hists[name], _ = onp.histogram(
                    centers, bins=new_edges, weights=old_hist)
                self.edges[name] = new_edges
            edges = self.edges[name]
            clipped = onp.clip(a.ravel(), edges[0], edges[-1])
            h, _ = onp.histogram(clipped, bins=edges)
            self.hists[name] += h

    def thresholds(self, name):
        lo, hi = self.minmax[name]
        if self.mode == "entropy" and name in self.hists:
            from ..ops.quantization_ops import calibrate_entropy
            t, _ = calibrate_entropy.fn(self.hists[name], self.edges[name])
            t = float(t)
            return (-t, t)
        return (lo, hi)


def _apply_activation(y, act):
    if act is None:
        return y
    return getattr(nd, act)(y)


class QuantizedDense(HybridBlock):
    """Dense replacement running int8×int8→int32 on the MXU."""

    def __init__(self, fp_layer, in_range, **kwargs):
        super().__init__(**kwargs)
        w = fp_layer.weight.data()
        self._wq, self._wmin, self._wmax = qops.quantize.fn(w.data)
        self._bias = None if fp_layer.bias is None \
            else fp_layer.bias.data().data
        self._flatten = fp_layer._flatten
        self._act = fp_layer._activation
        self._in_range = in_range

    def forward(self, x):
        data = x.data if isinstance(x, NDArray) else x
        if self._flatten and data.ndim > 2:
            data = data.reshape(data.shape[0], -1)
        lo, hi = self._in_range
        xq, xmin, xmax = qops.quantize.fn(data, lo, hi)
        acc, omin, omax = qops.quantized_dense.fn(
            xq, self._wq, self._bias, xmin, xmax, self._wmin, self._wmax)
        out = qops.dequantize.fn(acc, omin, omax)
        y = NDArray(out, ctx=x.ctx) if isinstance(x, NDArray) else out
        return _apply_activation(y, self._act)


class QuantizedConv2D(HybridBlock):
    """Conv2D replacement running int8 conv with int32 accumulation."""

    def __init__(self, fp_layer, in_range, **kwargs):
        super().__init__(**kwargs)
        w = fp_layer.weight.data()
        self._wq, self._wmin, self._wmax = qops.quantize.fn(w.data)
        self._bias = None if fp_layer.bias is None \
            else fp_layer.bias.data().data
        self._stride = fp_layer._strides
        self._pad = fp_layer._padding
        self._dilate = fp_layer._dilation
        self._act = fp_layer._activation
        self._in_range = in_range

    def forward(self, x):
        data = x.data if isinstance(x, NDArray) else x
        lo, hi = self._in_range
        xq, xmin, xmax = qops.quantize.fn(data, lo, hi)
        acc, omin, omax = qops.quantized_conv2d.fn(
            xq, self._wq, self._bias, xmin, xmax, self._wmin, self._wmax,
            stride=self._stride, pad=self._pad, dilate=self._dilate)
        out = qops.dequantize.fn(acc, omin, omax)
        y = NDArray(out, ctx=x.ctx) if isinstance(x, NDArray) else out
        return _apply_activation(y, self._act)


def _iter_children(block):
    for name, child in list(getattr(block, "_children", {}).items()):
        yield block, name, child


def quantize_net(net, calib_data=None, calib_mode="naive",
                 quantized_dtype="int8", exclude_layers=(),
                 num_calib_batches=None):
    """Post-training quantization of a Gluon net (reference
    contrib/quantization.py:quantize_net).

    calib_data: iterable of input batches (NDArray) for calibration.
    Rewrites the net IN PLACE (Dense/Conv2D → int8 versions) and returns
    it, mirroring the reference's convert-and-return contract.
    """
    assert quantized_dtype == "int8", "int8 is the TPU-native path"
    # hybridized nets dispatch through a CachedOp built from the fp32
    # trace — calibration hooks would never fire and the swap would be a
    # no-op (or hooks would see tracers). Deactivate + drop every cache
    # first; the caller may re-hybridize the quantized net afterwards.
    def _dehybridize(block):
        if hasattr(block, "_active"):
            block._active = False
        if getattr(block, "_cached_op", None) is not None:
            block._cached_op = None
        for child in getattr(block, "_children", {}).values():
            _dehybridize(child)

    _dehybridize(net)
    collector = CalibrationCollector(calib_mode)

    # 1+2: record every quantizable layer's INPUT range by hooking calls
    targets = []

    def walk(prefix, block):
        for parent, name, child in _iter_children(block):
            full = f"{prefix}{name}"
            if isinstance(child, (nn.Dense, nn.Conv2D)) \
                    and full not in exclude_layers:
                targets.append((parent, name, full, child))
            walk(full + ".", child)

    walk("", net)
    if calib_data is not None:
        hooks = []
        for _, _, full, child in targets:
            orig = child.forward

            def hooked(x, _full=full, _orig=orig):
                collector.collect(_full, x)
                return _orig(x)
            child.forward = hooked
            hooks.append((child, orig))
        n = 0
        for batch in calib_data:
            net(batch if isinstance(batch, NDArray) else nd.array(batch))
            n += 1
            if num_calib_batches is not None and n >= num_calib_batches:
                break
        for child, orig in hooks:
            child.forward = orig

    # 3: swap in quantized layers
    for parent, name, full, child in targets:
        in_range = collector.thresholds(full) if full in collector.minmax \
            else (-1.0, 1.0)
        q = QuantizedDense(child, in_range) if isinstance(child, nn.Dense) \
            else QuantizedConv2D(child, in_range)
        setattr(parent, name, q)
        parent._children[name] = q
    return net
