"""Vocabulary (reference contrib/text/vocab.py Vocabulary)."""
from __future__ import annotations

__all__ = ["Vocabulary"]


class Vocabulary:
    """Token index, frequency-ordered (reference vocab.py:33).

    Index 0 is the unknown token; reserved tokens follow; the remaining
    tokens are ordered by descending frequency then insertion order.
    """

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise ValueError("unknown_token must not be reserved")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens
        self._idx_to_token = [unknown_token] + reserved_tokens
        if counter is not None:
            seen = set(self._idx_to_token)
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq >= min_freq and tok not in seen:
                    self._idx_to_token.append(tok)
                    seen.add(tok)
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        if isinstance(tokens, str):
            return self._token_to_idx.get(tokens, 0)
        return [self._token_to_idx.get(t, 0) for t in tokens]

    def to_tokens(self, indices):
        if isinstance(indices, int):
            return self._idx_to_token[indices]
        return [self._idx_to_token[i] for i in indices]
