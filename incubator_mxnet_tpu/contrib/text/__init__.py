"""Text utilities (reference python/mxnet/contrib/text/)."""
from . import vocab
from . import embedding
from . import utils
from .vocab import Vocabulary
