"""Token embeddings (reference contrib/text/embedding.py).

Pretrained-vector loading from the GloVe/fastText text format
("token v1 v2 ... vn" per line).  The reference's downloadable registry
(GloVe/FastText classes with URL tables) maps here onto
``CustomEmbedding`` over local files — network egress is environment-
dependent, the file format is identical.
"""
from __future__ import annotations

import numpy as onp

from ... import ndarray as nd
from ...ndarray import NDArray

__all__ = ["TokenEmbedding", "CustomEmbedding", "CompositeEmbedding",
           "register", "create", "get_pretrained_file_names"]

_REG: dict = {}


def register(cls):
    _REG[cls.__name__.lower()] = cls
    return cls


def create(name, **kwargs):
    return _REG[name.lower()](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """The reference returns its download registry; this build is
    offline — pretrained files are supplied locally via
    CustomEmbedding(pretrained_file_path=...)."""
    return {name: [] for name in _REG} if embedding_name is None else []


class TokenEmbedding:
    """Base: token → vector with <unk> fallback (reference
    embedding.py:139 _TokenEmbedding)."""

    def __init__(self, unknown_token="<unk>"):
        self._unknown_token = unknown_token
        self._idx_to_token = [unknown_token]
        self._token_to_idx = {unknown_token: 0}
        self._idx_to_vec = None

    def _load_text_file(self, path, elem_delim=" ", encoding="utf8"):
        toks, vecs = [], []
        with open(path, encoding=encoding) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if lineno == 0 and len(parts) == 2:
                    try:  # fastText .vec header: "<count> <dim>"
                        int(parts[0]), int(parts[1])
                        continue
                    except ValueError:
                        pass
                if len(parts) < 2:
                    continue
                toks.append(parts[0])
                vecs.append(onp.asarray([float(x) for x in parts[1:]],
                                        onp.float32))
        dim = vecs[0].shape[0] if vecs else 0
        bad = [i for i, v in enumerate(vecs) if v.shape[0] != dim]
        if bad:
            raise ValueError(
                f"{path}: line {bad[0] + 1} has {vecs[bad[0]].shape[0]} "
                f"values, expected {dim} (inconsistent embedding rows)")
        self._idx_to_token = [self._unknown_token] + toks
        self._token_to_idx = {t: i for i, t in
                              enumerate(self._idx_to_token)}
        mat = onp.zeros((len(self._idx_to_token), dim), onp.float32)
        for i, v in enumerate(vecs):
            mat[i + 1] = v
        self._idx_to_vec = nd.array(mat)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def vec_len(self):
        return int(self._idx_to_vec.shape[1]) if self._idx_to_vec is not None else 0

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        idx = []
        for t in toks:
            i = self._token_to_idx.get(t)
            if i is None and lower_case_backup:
                i = self._token_to_idx.get(t.lower())
            idx.append(0 if i is None else i)
        out = NDArray(self._idx_to_vec.data[onp.asarray(idx)])
        return NDArray(out.data[0]) if single else out

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else list(tokens)
        vecs = new_vectors.asnumpy().reshape(len(toks), -1)
        mat = onp.array(self._idx_to_vec.asnumpy())  # writable copy
        for t, v in zip(toks, vecs):
            if t not in self._token_to_idx:
                raise ValueError(f"token {t!r} unknown")
            mat[self._token_to_idx[t]] = v
        self._idx_to_vec = nd.array(mat)


@register
class CustomEmbedding(TokenEmbedding):
    """Embedding from a local pretrained text file (reference
    embedding.py CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", **kwargs):
        super().__init__(**kwargs)
        self._load_text_file(pretrained_file_path, elem_delim, encoding)


@register
class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary (reference
    embedding.py CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        super().__init__(unknown_token=vocabulary.unknown_token)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = []
        for emb in token_embeddings:
            parts.append(emb.get_vecs_by_tokens(
                self._idx_to_token).asnumpy())
        self._idx_to_vec = nd.array(onp.concatenate(parts, axis=1))
