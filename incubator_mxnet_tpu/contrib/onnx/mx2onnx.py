"""Symbol → ONNX exporter (reference python/mxnet/contrib/onnx/mx2onnx/).

Walks the Symbol DAG (symbol/__init__.py _SymNode) in topological order
and emits an ONNX ModelProto (opset 13) through the hand-rolled protobuf
writer. Parameters become graph initializers (raw_data TensorProto).
"""
from __future__ import annotations

import numpy as onp

from ._protobuf import Writer

__all__ = ["export_model", "export_bytes"]

# onnx.proto3 TensorProto.DataType
_DTYPE = {"float32": 1, "uint8": 2, "int8": 3, "int32": 6, "int64": 7,
          "bool": 9, "float16": 10, "float64": 11, "bfloat16": 16}


def _tensor(name, arr):
    arr = onp.ascontiguousarray(arr)
    w = Writer()
    w.packed_int64(1, arr.shape)                    # dims
    w.varint(2, _DTYPE[str(arr.dtype)])             # data_type
    w.string(8, name)                               # name
    w.bytes_(9, arr.tobytes())                      # raw_data
    return w


def _attr_int(name, v):
    return Writer().string(1, name).varint(3, int(v)).varint(20, 2)


def _attr_float(name, v):
    return Writer().string(1, name).float32(2, float(v)).varint(20, 1)


def _attr_ints(name, vs):
    return Writer().string(1, name).packed_int64(8, vs).varint(20, 7)


def _attr_str(name, v):
    return Writer().string(1, name).string(4, v).varint(20, 3)


def _node(op_type, inputs, outputs, name, attrs=()):
    w = Writer()
    for i in inputs:
        w.string(1, i)
    for o in outputs:
        w.string(2, o)
    w.string(3, name)
    w.string(4, op_type)
    for a in attrs:
        w.message(5, a)
    return w


def _value_info(name, shape, dtype="float32"):
    shp = Writer()
    for d in shape:
        shp.message(1, Writer().varint(1, int(d)))
    tt = Writer().varint(1, _DTYPE[dtype]).message(2, shp)
    tp = Writer().message(1, tt)
    return Writer().string(1, name).message(2, tp)


def _tuple(v, n=2):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


class _Exporter:
    def __init__(self):
        self.nodes: list[Writer] = []
        self.extra_inits: list[Writer] = []
        self._uid = 0

    def uid(self, base):
        self._uid += 1
        return f"{base}_{self._uid}"

    def shape_const(self, name, values):
        self.extra_inits.append(
            _tensor(name, onp.asarray(values, onp.int64)))
        return name

    # one handler per op: (node, in_names, out_name) -> emits node(s)
    def emit(self, node, ins, out):
        kw = node.kwargs
        op = node.op_name
        H = _HANDLERS.get(op)
        if H is None:
            raise NotImplementedError(
                f"ONNX export: op {op!r} has no handler")
        H(self, node, ins, out, kw)


def _h_conv(ex, node, ins, out, kw):
    attrs = [_attr_ints("kernel_shape", _tuple(kw.get("kernel"))),
             _attr_ints("strides", _tuple(kw.get("stride", (1, 1)))),
             _attr_ints("dilations", _tuple(kw.get("dilate", (1, 1)))),
             _attr_int("group", kw.get("num_group", 1))]
    pad = _tuple(kw.get("pad", (0, 0)))
    attrs.append(_attr_ints("pads", pad + pad))
    inputs = ins if not kw.get("no_bias", False) else ins[:2]
    ex.nodes.append(_node("Conv", inputs, [out], node.name, attrs))


def _h_fc(ex, node, ins, out, kw):
    data = ins[0]
    if kw.get("flatten", True):
        flat = ex.uid(node.name + "_flat")
        ex.nodes.append(_node("Flatten", [data], [flat],
                              flat, [_attr_int("axis", 1)]))
        data = flat
    attrs = [_attr_float("alpha", 1.0), _attr_float("beta", 1.0),
             _attr_int("transB", 1)]
    inputs = [data, ins[1]] + (list(ins[2:3]) if not kw.get("no_bias", False)
                               else [])
    ex.nodes.append(_node("Gemm", inputs, [out], node.name, attrs))


def _h_act(ex, node, ins, out, kw):
    act = kw.get("act_type", "relu")
    op = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "softrelu": "Softplus", "softsign": "Softsign"}[act]
    ex.nodes.append(_node(op, ins[:1], [out], node.name))


def _h_bn(ex, node, ins, out, kw):
    attrs = [_attr_float("epsilon", kw.get("eps", 1e-5)),
             _attr_float("momentum", kw.get("momentum", 0.9))]
    # mx order: data gamma beta mean var == onnx X scale B mean var
    ex.nodes.append(_node("BatchNormalization", ins[:5], [out],
                          node.name, attrs))


def _h_pool(ex, node, ins, out, kw):
    ptype = kw.get("pool_type", "max")
    if kw.get("global_pool", False):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
        ex.nodes.append(_node(op, ins[:1], [out], node.name))
        return
    op = {"max": "MaxPool", "avg": "AveragePool"}[ptype]
    pad = _tuple(kw.get("pad", (0, 0)))
    attrs = [_attr_ints("kernel_shape", _tuple(kw.get("kernel"))),
             _attr_ints("strides", _tuple(kw.get("stride", (1, 1)))),
             _attr_ints("pads", pad + pad)]
    if op == "AveragePool":
        attrs.append(_attr_int("count_include_pad", 1))
    ex.nodes.append(_node(op, ins[:1], [out], node.name, attrs))


def _h_softmax(ex, node, ins, out, kw):
    ex.nodes.append(_node("Softmax", ins[:1], [out], node.name,
                          [_attr_int("axis", kw.get("axis", -1))]))


def _h_flatten(ex, node, ins, out, kw):
    ex.nodes.append(_node("Flatten", ins[:1], [out], node.name,
                          [_attr_int("axis", 1)]))


def _h_elemwise(onnx_op):
    def h(ex, node, ins, out, kw):
        ex.nodes.append(_node(onnx_op, ins[:2], [out], node.name))
    return h


def _h_unary(onnx_op):
    def h(ex, node, ins, out, kw):
        ex.nodes.append(_node(onnx_op, ins[:1], [out], node.name))
    return h


def _h_concat(ex, node, ins, out, kw):
    ex.nodes.append(_node("Concat", ins, [out], node.name,
                          [_attr_int("axis", kw.get("dim", 1))]))


def _h_reshape(ex, node, ins, out, kw):
    shape_name = ex.uid(node.name + "_shape")
    ex.shape_const(shape_name, kw.get("shape"))
    ex.nodes.append(_node("Reshape", [ins[0], shape_name], [out], node.name))


def _h_transpose(ex, node, ins, out, kw):
    axes = kw.get("axes")
    attrs = [_attr_ints("perm", axes)] if axes else []
    ex.nodes.append(_node("Transpose", ins[:1], [out], node.name, attrs))


def _h_dropout(ex, node, ins, out, kw):
    ex.nodes.append(_node("Dropout", ins[:1], [out], node.name))


def _h_leaky(ex, node, ins, out, kw):
    ex.nodes.append(_node("LeakyRelu", ins[:1], [out], node.name,
                          [_attr_float("alpha", kw.get("slope", 0.25))]))


def _h_fullsoftmaxout(ex, node, ins, out, kw):
    # SoftmaxOutput's inference semantics = Softmax over data
    ex.nodes.append(_node("Softmax", ins[:1], [out], node.name,
                          [_attr_int("axis", -1)]))


def _h_clip(ex, node, ins, out, kw):
    lo = ex.uid(node.name + "_min")
    hi = ex.uid(node.name + "_max")
    ex.extra_inits.append(_tensor(lo, onp.asarray(kw.get("a_min", 0.0),
                                                  onp.float32)))
    ex.extra_inits.append(_tensor(hi, onp.asarray(kw.get("a_max", 1.0),
                                                  onp.float32)))
    ex.nodes.append(_node("Clip", [ins[0], lo, hi], [out], node.name))


def _h_split(ex, node, ins, outs, kw):
    if isinstance(outs, str):
        outs = [outs]
    ex.nodes.append(_node("Split", [ins[0]], outs, node.name,
                          [_attr_int("axis", kw.get("axis", 1))]))


_HANDLERS = {
    "Convolution": _h_conv,
    "split": _h_split,
    "SliceChannel": _h_split,
    "FullyConnected": _h_fc,
    "Activation": _h_act,
    "BatchNorm": _h_bn,
    "Pooling": _h_pool,
    "softmax": _h_softmax,
    "log_softmax": _h_unary("LogSoftmax"),
    "SoftmaxOutput": _h_fullsoftmaxout,
    "flatten": _h_flatten,
    "concat": _h_concat,
    "reshape": _h_reshape,
    "transpose": _h_transpose,
    "Dropout": _h_dropout,
    "LeakyReLU": _h_leaky,
    "clip": _h_clip,
    "add": _h_elemwise("Add"),
    "subtract": _h_elemwise("Sub"),
    "multiply": _h_elemwise("Mul"),
    "divide": _h_elemwise("Div"),
    "maximum": _h_elemwise("Max"),
    "minimum": _h_elemwise("Min"),
    "matmul": _h_elemwise("MatMul"),
    "dot": _h_elemwise("MatMul"),
    "relu": _h_unary("Relu"),
    "sigmoid": _h_unary("Sigmoid"),
    "tanh": _h_unary("Tanh"),
    "exp": _h_unary("Exp"),
    "log": _h_unary("Log"),
    "sqrt": _h_unary("Sqrt"),
    "abs": _h_unary("Abs"),
    "negative": _h_unary("Neg"),
    "mean": _h_unary("ReduceMean"),
    "elemwise_add": _h_elemwise("Add"),
    "broadcast_add": _h_elemwise("Add"),
    "broadcast_mul": _h_elemwise("Mul"),
}


def export_bytes(sym, params, input_shape, input_dtype="float32",
                 opset=13):
    """Serialize (symbol, params) to ONNX ModelProto bytes.

    params: dict name → NDArray/ndarray for every non-data variable.
    input_shape: shape of the single data input (dict for multi-input).
    """
    nodes = sym._topo_order()
    params = {k: (v.asnumpy() if hasattr(v, "asnumpy") else onp.asarray(v))
              for k, v in (params or {}).items()}

    ex = _Exporter()
    # names are keyed by (producer key, output_index): input edges may be
    # output-selecting clones of the producer, so id() is not stable
    names: dict[tuple, str] = {}
    inputs = []
    inits = []
    for n in nodes:
        if n.op_name is None:  # variable
            names[(n.key, 0)] = n.name
            if n.name in params:
                inits.append(_tensor(n.name, params[n.name]))
            else:
                shape = input_shape[n.name] if isinstance(input_shape, dict) \
                    else input_shape
                inputs.append(_value_info(n.name, shape, input_dtype))
        else:
            if n.num_outputs == 1:
                out_names = [n.name]
            else:
                out_names = [f"{n.name}_out{i}"
                             for i in range(n.num_outputs)]
            for i, nm in enumerate(out_names):
                names[(n.key, i)] = nm
            ins = [names[(i.key, i.output_index)] for i in n.inputs]
            ex.emit(n, ins,
                    out_names[0] if len(out_names) == 1 else out_names)

    outputs = [_value_info(names[(h.key, h.output_index)], ())
               for h in sym._head_entries()]

    g = Writer()
    for nd_ in ex.nodes:
        g.message(1, nd_)
    g.string(2, "incubator_mxnet_tpu")
    for t in inits + ex.extra_inits:
        g.message(5, t)
    for vi in inputs:
        g.message(11, vi)
    for vo in outputs:
        g.message(12, vo)

    opset_w = Writer().string(1, "").varint(2, opset)
    m = Writer()
    m.varint(1, 8)                     # ir_version
    m.string(2, "incubator_mxnet_tpu") # producer_name
    m.string(3, "1.0")
    m.message(7, g)
    m.message(8, opset_w)
    return m.tobytes()


def export_model(sym, params, input_shape, onnx_file_path,
                 input_dtype="float32", opset=13):
    """Reference mx2onnx.export_model surface: writes the .onnx file and
    returns its path."""
    data = export_bytes(sym, params, input_shape, input_dtype, opset)
    with open(onnx_file_path, "wb") as f:
        f.write(data)
    return onnx_file_path
