"""ONNX → Symbol importer (reference python/mxnet/contrib/onnx/onnx2mx/).

Parses ModelProto wire bytes with the hand-rolled codec and rebuilds a
Symbol graph plus arg/aux param dicts — ``import_model`` keeps the
reference's (sym, arg_params, aux_params) return contract.
"""
from __future__ import annotations

import struct

import numpy as onp

from ._protobuf import parse_fields, unpack_packed_int64
from ... import symbol as sym_mod
from ... import ndarray as nd

__all__ = ["import_model", "import_bytes"]

_NP_DTYPE = {1: onp.float32, 2: onp.uint8, 3: onp.int8, 6: onp.int32,
             7: onp.int64, 9: onp.bool_, 10: onp.float16, 11: onp.float64}


def _parse_tensor(data: bytes):
    dims, dtype, name, raw = [], 1, "", b""
    float_data, int32_data, int64_data = [], [], []
    for f, wt, v in parse_fields(data):
        if f == 1:
            dims += unpack_packed_int64(v) if wt == 2 else [v]
        elif f == 2:
            dtype = v
        elif f == 8:
            name = v.decode()
        elif f == 9:
            raw = v
        elif f == 4:
            float_data += list(struct.unpack(f"<{len(v) // 4}f", v)) \
                if wt == 2 else [struct.unpack("<f", v)[0]]
        elif f == 5:
            int32_data += unpack_packed_int64(v) if wt == 2 else [v]
        elif f == 7:
            int64_data += unpack_packed_int64(v) if wt == 2 else [v]
    np_dtype = _NP_DTYPE.get(dtype, onp.float32)
    if raw:
        arr = onp.frombuffer(raw, np_dtype).reshape(dims)
    elif float_data:
        arr = onp.asarray(float_data, np_dtype).reshape(dims)
    elif int64_data:
        arr = onp.asarray(int64_data, np_dtype).reshape(dims)
    elif int32_data:
        arr = onp.asarray(int32_data, np_dtype).reshape(dims)
    else:
        arr = onp.zeros(dims, np_dtype)
    return name, arr


def _parse_attr(data: bytes):
    name, atype = "", 0
    f_val, i_val, s_val, ints, floats = 0.0, 0, b"", [], []
    t_val = None
    for f, wt, v in parse_fields(data):
        if f == 1:
            name = v.decode()
        elif f == 2:
            f_val = struct.unpack("<f", v)[0]
        elif f == 3:
            i_val = v
        elif f == 4:
            s_val = v
        elif f == 5:
            t_val = _parse_tensor(v)
        elif f == 7:
            floats += list(struct.unpack(f"<{len(v) // 4}f", v)) \
                if wt == 2 else [struct.unpack("<f", v)[0]]
        elif f == 8:
            ints += unpack_packed_int64(v) if wt == 2 else [v]
        elif f == 20:
            atype = v
    value = {1: f_val, 2: i_val, 3: s_val.decode() if s_val else "",
             4: t_val, 6: floats, 7: ints}.get(atype)
    if value is None:  # infer when type field missing
        value = ints or floats or (s_val.decode() if s_val else i_val)
    return name, value


def _parse_node(data: bytes):
    inputs, outputs, attrs = [], [], {}
    name, op_type = "", ""
    for f, wt, v in parse_fields(data):
        if f == 1:
            inputs.append(v.decode())
        elif f == 2:
            outputs.append(v.decode())
        elif f == 3:
            name = v.decode()
        elif f == 4:
            op_type = v.decode()
        elif f == 5:
            k, val = _parse_attr(v)
            attrs[k] = val
    return op_type, name, inputs, outputs, attrs


def _value_info_name(data: bytes):
    for f, _, v in parse_fields(data):
        if f == 1:
            return v.decode()
    return ""


def _pads_to_mx(pads):
    if not pads:
        return (0, 0)
    half = len(pads) // 2
    return tuple(pads[:half])


class _Importer:
    """Rebuilds Symbol nodes from ONNX ops."""

    def __init__(self, params):
        self.params = params
        self.tensors: dict = {}     # onnx name → Symbol
        self.consts: dict = {}      # onnx name → ndarray (shape inputs etc.)

    def get(self, name):
        if name in self.tensors:
            return self.tensors[name]
        if name in self.params:
            v = sym_mod.var(name)
            self.tensors[name] = v
            return v
        raise KeyError(f"ONNX input {name!r} not found")

    def convert(self, op_type, name, inputs, outputs, attrs):
        H = _IMPORT_HANDLERS.get(op_type)
        if H is None:
            raise NotImplementedError(
                f"ONNX import: op {op_type!r} has no handler")
        out = H(self, name, inputs, attrs)
        if isinstance(out, tuple):
            for o_name, o_sym in zip(outputs, out):
                self.tensors[o_name] = o_sym
        else:
            self.tensors[outputs[0]] = out


def _i_conv(im, name, ins, attrs):
    kernel = tuple(attrs.get("kernel_shape", (1, 1)))
    kw = dict(kernel=kernel,
              stride=tuple(attrs.get("strides", (1,) * len(kernel))),
              dilate=tuple(attrs.get("dilations", (1,) * len(kernel))),
              pad=_pads_to_mx(attrs.get("pads")),
              num_group=attrs.get("group", 1))
    w = im.params[ins[1]]
    kw["num_filter"] = w.shape[0]
    args = [im.get(i) for i in ins]
    kw["no_bias"] = len(ins) < 3
    return sym_mod.Convolution(*args, name=name, **kw)


def _i_gemm(im, name, ins, attrs):
    w = im.params[ins[1]]
    num_hidden = w.shape[0] if attrs.get("transB", 0) else w.shape[1]
    args = [im.get(i) for i in ins]
    return sym_mod.FullyConnected(*args, num_hidden=num_hidden,
                                  no_bias=len(ins) < 3, flatten=False,
                                  name=name)


def _i_bn(im, name, ins, attrs):
    args = [im.get(i) for i in ins]
    # running mean/var are auxiliary states (reference FListAuxState)
    for aux_sym in args[3:5]:
        for n in aux_sym._nodes:
            if n.op_name is None:
                n.attrs["__aux__"] = "1"
    return sym_mod.BatchNorm(*args,
                             eps=attrs.get("epsilon", 1e-5),
                             momentum=attrs.get("momentum", 0.9),
                             name=name)


def _i_pool(ptype, global_pool=False):
    def h(im, name, ins, attrs):
        kw = dict(pool_type=ptype, global_pool=global_pool)
        if not global_pool:
            kw.update(kernel=tuple(attrs.get("kernel_shape", (2, 2))),
                      stride=tuple(attrs.get("strides", (1, 1))),
                      pad=_pads_to_mx(attrs.get("pads")))
        return sym_mod.Pooling(im.get(ins[0]), name=name, **kw)
    return h


def _i_act(mx_act):
    def h(im, name, ins, attrs):
        return sym_mod.Activation(im.get(ins[0]), act_type=mx_act, name=name)
    return h


def _i_elemwise(op_name):
    def h(im, name, ins, attrs):
        return getattr(sym_mod, op_name)(*[im.get(i) for i in ins])
    return h


def _i_unary(op_name):
    def h(im, name, ins, attrs):
        return getattr(sym_mod, op_name)(im.get(ins[0]))
    return h


def _i_softmax(im, name, ins, attrs):
    return sym_mod.softmax(im.get(ins[0]), axis=attrs.get("axis", -1))


def _i_flatten(im, name, ins, attrs):
    return sym_mod.flatten(im.get(ins[0]))


def _i_reshape(im, name, ins, attrs):
    shape = im.consts.get(ins[1])
    if shape is None:
        shape = im.params.get(ins[1])
    return sym_mod.reshape(im.get(ins[0]),
                           shape=tuple(int(s) for s in shape))


def _i_transpose(im, name, ins, attrs):
    return sym_mod.transpose(im.get(ins[0]),
                             axes=tuple(attrs.get("perm", ())) or None)


def _i_concat(im, name, ins, attrs):
    return sym_mod.concat(*[im.get(i) for i in ins],
                          dim=attrs.get("axis", 1))


def _i_dropout(im, name, ins, attrs):
    return im.get(ins[0])  # inference graph: identity


def _i_leaky(im, name, ins, attrs):
    return sym_mod.LeakyReLU(im.get(ins[0]),
                             slope=attrs.get("alpha", 0.01))


def _i_clip(im, name, ins, attrs):
    a_min = attrs.get("min", 0.0)
    a_max = attrs.get("max", 1.0)
    if len(ins) > 1:
        c = im.consts.get(ins[1], im.params.get(ins[1]))
        if c is not None:
            a_min = float(c)
    if len(ins) > 2:
        c = im.consts.get(ins[2], im.params.get(ins[2]))
        if c is not None:
            a_max = float(c)
    return sym_mod.clip(im.get(ins[0]), a_min=a_min, a_max=a_max)


_IMPORT_HANDLERS = {
    "Conv": _i_conv,
    "Gemm": _i_gemm,
    "BatchNormalization": _i_bn,
    "MaxPool": _i_pool("max"),
    "AveragePool": _i_pool("avg"),
    "GlobalMaxPool": _i_pool("max", True),
    "GlobalAveragePool": _i_pool("avg", True),
    "Relu": _i_act("relu"),
    "Sigmoid": _i_act("sigmoid"),
    "Tanh": _i_act("tanh"),
    "Softplus": _i_act("softrelu"),
    "Softmax": _i_softmax,
    "LogSoftmax": _i_unary("log_softmax"),
    "Flatten": _i_flatten,
    "Reshape": _i_reshape,
    "Transpose": _i_transpose,
    "Concat": _i_concat,
    "Dropout": _i_dropout,
    "LeakyRelu": _i_leaky,
    "Clip": _i_clip,
    "Add": _i_elemwise("add"),
    "Sub": _i_elemwise("subtract"),
    "Mul": _i_elemwise("multiply"),
    "Div": _i_elemwise("divide"),
    "Max": _i_elemwise("maximum"),
    "Min": _i_elemwise("minimum"),
    "MatMul": _i_elemwise("matmul"),
    "Exp": _i_unary("exp"),
    "Log": _i_unary("log"),
    "Sqrt": _i_unary("sqrt"),
    "Abs": _i_unary("abs"),
    "Neg": _i_unary("negative"),
}


def import_bytes(data: bytes):
    graph = None
    for f, _, v in parse_fields(data):
        if f == 7:
            graph = v
    if graph is None:
        raise ValueError("no GraphProto in model")

    raw_nodes, inits, g_inputs, g_outputs = [], {}, [], []
    for f, _, v in parse_fields(graph):
        if f == 1:
            raw_nodes.append(_parse_node(v))
        elif f == 5:
            name, arr = _parse_tensor(v)
            inits[name] = arr
        elif f == 11:
            g_inputs.append(_value_info_name(v))
        elif f == 12:
            g_outputs.append(_value_info_name(v))

    im = _Importer(inits)
    # shape-ish int64 initializers double as constants for Reshape etc.
    im.consts = {k: v for k, v in inits.items() if v.dtype == onp.int64}
    for iname in g_inputs:
        if iname not in inits:
            im.tensors[iname] = sym_mod.var(iname)
    # Constant nodes become consts
    for op_type, name, ins, outs, attrs in raw_nodes:
        if op_type == "Constant":
            t = attrs.get("value")
            if t is not None:
                im.consts[outs[0]] = t[1]
            continue
        im.convert(op_type, name, ins, outs, attrs)

    out_syms = [im.tensors[o] for o in g_outputs]
    sym = out_syms[0] if len(out_syms) == 1 else sym_mod.Group(out_syms)

    used = set()
    for n in sym._topo_order():
        if n.op_name is None:
            used.add(n.name)
    aux_names = set(sym.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    for k, v in inits.items():
        if k not in used or v.dtype == onp.int64:
            continue
        (aux_params if k in aux_names else arg_params)[k] = nd.array(v)
    return sym, arg_params, aux_params


def import_model(model_file):
    """Reference onnx2mx.import_model surface: returns
    (sym, arg_params, aux_params)."""
    with open(model_file, "rb") as f:
        data = f.read()
    return import_bytes(data)
