"""Minimal protobuf wire-format codec for ONNX messages.

The environment has no ``onnx`` package, so the exporter/importer
(reference python/mxnet/contrib/onnx/) speak the protobuf wire format
directly. Only what ONNX needs: varints, length-delimited fields, 32/64
bit scalars, packed repeated numerics. Field numbers follow onnx.proto3
(see each message builder in mx2onnx.py / parser in onnx2mx.py).
"""
from __future__ import annotations

import struct

__all__ = ["Writer", "parse_fields", "decode_varint"]

_WT_VARINT = 0
_WT_64BIT = 1
_WT_LEN = 2
_WT_32BIT = 5


def _varint(value: int) -> bytes:
    if value < 0:  # protobuf encodes negative int64 as 10-byte varint
        value += 1 << 64
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class Writer:
    """Append-only message builder."""

    def __init__(self):
        self._parts: list[bytes] = []

    def varint(self, field: int, value: int):
        if value is None:
            return self
        self._parts.append(_varint((field << 3) | _WT_VARINT))
        self._parts.append(_varint(int(value)))
        return self

    def string(self, field: int, value) -> "Writer":
        if value is None:
            return self
        data = value.encode("utf-8") if isinstance(value, str) else bytes(value)
        self._parts.append(_varint((field << 3) | _WT_LEN))
        self._parts.append(_varint(len(data)))
        self._parts.append(data)
        return self

    bytes_ = string

    def float32(self, field: int, value: float):
        self._parts.append(_varint((field << 3) | _WT_32BIT))
        self._parts.append(struct.pack("<f", value))
        return self

    def message(self, field: int, sub: "Writer"):
        return self.string(field, sub.tobytes())

    def packed_int64(self, field: int, values):
        body = b"".join(_varint(int(v)) for v in values)
        return self.string(field, body)

    def packed_float(self, field: int, values):
        return self.string(field, struct.pack(f"<{len(values)}f", *values))

    def tobytes(self) -> bytes:
        return b"".join(self._parts)


def decode_varint(data: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    if result >= 1 << 63:  # negative int64
        result -= 1 << 64
    return result, pos


def parse_fields(data: bytes):
    """Yield (field_number, wire_type, value) over a serialized message.
    LEN fields yield bytes; varints ints; 32/64-bit raw bytes."""
    pos = 0
    n = len(data)
    while pos < n:
        tag, pos = decode_varint(data, pos)
        field, wt = tag >> 3, tag & 7
        if wt == _WT_VARINT:
            val, pos = decode_varint(data, pos)
        elif wt == _WT_LEN:
            ln, pos = decode_varint(data, pos)
            val = data[pos:pos + ln]
            pos += ln
        elif wt == _WT_32BIT:
            val = data[pos:pos + 4]
            pos += 4
        elif wt == _WT_64BIT:
            val = data[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def unpack_packed_int64(data: bytes):
    out = []
    pos = 0
    while pos < len(data):
        v, pos = decode_varint(data, pos)
        out.append(v)
    return out


def unpack_packed_float(data: bytes):
    return list(struct.unpack(f"<{len(data) // 4}f", data))
