"""ONNX interop (reference python/mxnet/contrib/onnx/) — self-contained
protobuf wire codec, no ``onnx`` package dependency."""
from .mx2onnx import export_model, export_bytes
from .onnx2mx import import_model, import_bytes

__all__ = ["export_model", "export_bytes", "import_model", "import_bytes"]
