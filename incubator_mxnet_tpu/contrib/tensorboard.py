"""TensorBoard logging bridge (reference python/mxnet/contrib/tensorboard.py).

``LogMetricsCallback`` plugs into the fit/epoch callback slots and
writes EvalMetric values as TensorBoard scalars.  Uses tensorboardX (or
tensorboard's SummaryWriter) when available.
"""
from __future__ import annotations

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    """Log metric values each callback invocation (reference
    contrib/tensorboard.py:33).

    Usage::

        cb = LogMetricsCallback('logs/train')
        model.fit(..., batch_end_callback=[cb])
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        try:
            from tensorboardX import SummaryWriter
        except ImportError:
            try:
                from torch.utils.tensorboard import SummaryWriter
            except ImportError as e:
                raise ImportError(
                    "LogMetricsCallback needs tensorboardX or torch "
                    "(pip install tensorboardX)") from e
        self.summary_writer = SummaryWriter(logging_dir)

    def __call__(self, param):
        """BatchEndParam-style callback (reference model.py callbacks)."""
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            self.summary_writer.add_scalar(name, value, self.step)

    def close(self):
        self.summary_writer.close()
