"""RecordIO: packed binary record format (reference python/mxnet/recordio.py
+ dmlc-core recordio; C++ reader in src/io/).

Format kept wire-compatible with the reference: each record is
``[magic:u32][lrecord:u32][data][pad to 4]`` where lrecord encodes
cflag (3 bits) | length (29 bits) — see dmlc-core/include/dmlc/recordio.h.
A C++ fast-path reader lives in src/ (native/), used when built; this
pure-Python implementation is the always-available fallback.
"""
from __future__ import annotations

import numbers
import os
import struct

import numpy as onp

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IndexedRecordIO",
           "IRHeader", "pack", "unpack", "pack_img", "unpack_img"]

_MAGIC = 0xced7230a


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.record = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError(f"invalid flag {self.flag}")
        self.pid = os.getpid()

    def close(self):
        if self.record is not None:
            self.record.close()
            self.record = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["record"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf: bytes):
        assert self.writable
        self.record.write(struct.pack("<II", _MAGIC, len(buf)))
        self.record.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def tell(self):
        return self.record.tell()

    def read(self):
        assert not self.writable
        header = self.record.read(8)
        if len(header) < 8:
            return None
        magic, lrec = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise IOError(f"invalid record magic {magic:#x} in {self.uri}")
        length = lrec & ((1 << 29) - 1)
        buf = self.record.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.record.read(pad)
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO via .idx file (reference MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.exists(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) == 2:
                        key = key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)

    def close(self):
        if getattr(self, "writable", False) and self.record is not None:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def seek(self, idx):
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


IndexedRecordIO = MXIndexedRecordIO


class IRHeader:
    """Image record header (reference recordio.py IRHeader)."""

    __slots__ = ("flag", "label", "id", "id2")

    def __init__(self, flag, label, id, id2):
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2


_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    label = header.label
    if isinstance(label, numbers.Number):
        packed = struct.pack(_IR_FORMAT, 0, float(label), header.id,
                             header.id2)
    else:
        label = onp.asarray(label, dtype=onp.float32)
        packed = struct.pack(_IR_FORMAT, label.size, 0.0, header.id,
                             header.id2) + label.tobytes()
    return packed + s


def unpack(s: bytes):
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = onp.frombuffer(s[:flag * 4], dtype=onp.float32)
        s = s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    from . import image
    buf = image.imencode(img, img_fmt, quality)
    return pack(header, buf)


def unpack_img(s, iscolor=1):
    from . import image
    header, buf = unpack(s)
    return header, image.imdecode_np(buf, iscolor)
