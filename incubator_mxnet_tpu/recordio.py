"""RecordIO: packed binary record format (reference python/mxnet/recordio.py
+ dmlc-core recordio; C++ reader in src/io/).

Format kept wire-compatible with the reference: each record is
``[magic:u32][lrecord:u32][data][pad to 4]`` where lrecord encodes
cflag (3 bits) | length (29 bits) — see dmlc-core/include/dmlc/recordio.h.
A C++ fast-path reader lives in src/ (native/), used when built; this
pure-Python implementation is the always-available fallback.
"""
from __future__ import annotations

import numbers
import os
import struct

import numpy as onp

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IndexedRecordIO",
           "IRHeader", "pack", "unpack", "pack_img", "unpack_img",
           "pack_raw", "unpack_raw"]

_MAGIC = 0xced7230a
_MAGIC_BYTES = struct.pack("<I", _MAGIC)


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.record = None
        self.open()

    def open(self):
        from . import native
        self._nh = None
        if self.flag == "w":
            self.writable = True
        elif self.flag == "r":
            self.writable = False
        else:
            raise ValueError(f"invalid flag {self.flag}")
        # any URI (incl. file://) routes through the filesystem registry;
        # bare paths keep the native fast path
        remote = "://" in self.uri
        if native.available() and not remote:
            import ctypes
            h = ctypes.c_void_p()
            create = (native.lib.MXTRecordIOWriterCreate if self.writable
                      else native.lib.MXTRecordIOReaderCreate)
            native.check_call(create(self.uri.encode(), ctypes.byref(h)))
            self._nh = h
            # cache the free fn now: close() may run at interpreter
            # teardown when module globals are already None
            self._nh_free = (native.lib.MXTRecordIOWriterFree if self.writable
                             else native.lib.MXTRecordIOReaderFree)
            self.record = True  # truthy marker: stream is open
        elif remote:
            # s3:// / hdfs:// stream through the filesystem registry
            # (dmlc-core SeekStream role; reference s3_integration.md)
            from .filesystem import open_uri
            self.record = open_uri(self.uri,
                                   "wb" if self.writable else "rb")
        else:
            self.record = open(self.uri, "wb" if self.writable else "rb")
        self.pid = os.getpid()

    def close(self):
        if getattr(self, "_nh", None) is not None:
            try:
                self._nh_free(self._nh)
            except Exception:  # mxlint: allow-broad-except(interpreter teardown: the native lib may already be unloaded)
                pass
            self._nh = None
            self.record = None
        elif self.record is not None and self.record is not True:
            self.record.close()
            self.record = None
        else:
            self.record = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["record"] = None
        d["_nh"] = None
        d.pop("_nh_free", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf: bytes):
        assert self.writable
        if self._nh is not None:
            from . import native
            native.check_call(
                native.lib.MXTRecordIOWriterWrite(self._nh, buf, len(buf)))
            return
        # pure-Python fallback: split payloads at magic words like dmlc
        # recordio so readers can always resync (recordio.h SplitWrite)
        splits = [i for i in range(0, len(buf) - 3, 4)
                  if buf[i:i + 4] == _MAGIC_BYTES]
        chunks = []
        if not splits:
            chunks.append((0, buf))
        else:
            bounds = [0] + [s for s in splits] + [len(buf)]
            for k in range(len(bounds) - 1):
                lo = bounds[k] + (4 if k > 0 else 0)
                cflag = 1 if k == 0 else (3 if k == len(bounds) - 2 else 2)
                chunks.append((cflag, buf[lo:bounds[k + 1]]))
        for cflag, chunk in chunks:
            lrec = (cflag << 29) | len(chunk)
            self.record.write(struct.pack("<II", _MAGIC, lrec))
            self.record.write(chunk)
            pad = (4 - len(chunk) % 4) % 4
            if pad:
                self.record.write(b"\x00" * pad)

    def tell(self):
        if self._nh is not None:
            from . import native
            import ctypes
            pos = ctypes.c_uint64()
            fn = (native.lib.MXTRecordIOWriterTell if self.writable
                  else native.lib.MXTRecordIOReaderTell)
            native.check_call(fn(self._nh, ctypes.byref(pos)))
            return pos.value
        return self.record.tell()

    def _seek(self, pos):
        assert not self.writable
        if self._nh is not None:
            from . import native
            native.check_call(native.lib.MXTRecordIOReaderSeek(self._nh, pos))
        else:
            self.record.seek(pos)

    def read(self):
        assert not self.writable
        if self._nh is not None:
            from . import native
            import ctypes
            buf = ctypes.c_void_p()
            size = ctypes.c_uint64()
            native.check_call(native.lib.MXTRecordIOReaderNext(
                self._nh, ctypes.byref(buf), ctypes.byref(size)))
            if not buf.value:
                return None  # EOF (empty records come back non-NULL)
            if size.value == 0:
                return b""
            return ctypes.string_at(buf.value, size.value)
        parts = []
        multipart = False
        while True:
            header = self.record.read(8)
            if len(header) < 8:
                if multipart:
                    # EOF between continuation chunks: fail like the
                    # native reader (RecordIOReader::Next 'truncated
                    # header') instead of returning partial data
                    raise IOError(
                        f"truncated multipart record in {self.uri}")
                return None
            magic, lrec = struct.unpack("<II", header)
            if magic != _MAGIC:
                raise IOError(f"invalid record magic {magic:#x} in {self.uri}")
            cflag = (lrec >> 29) & 7
            length = lrec & ((1 << 29) - 1)
            if multipart:
                parts.append(_MAGIC_BYTES)
            parts.append(self.record.read(length))
            pad = (4 - length % 4) % 4
            if pad:
                self.record.read(pad)
            if cflag in (0, 3):
                break
            multipart = True
        return b"".join(parts)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO via .idx file (reference MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.exists(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) == 2:
                        key = key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)

    def close(self):
        if getattr(self, "writable", False) and self.record is not None:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def seek(self, idx):
        self._seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


IndexedRecordIO = MXIndexedRecordIO


class IRHeader:
    """Image record header (reference recordio.py IRHeader)."""

    __slots__ = ("flag", "label", "id", "id2")

    def __init__(self, flag, label, id, id2):
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2


_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    label = header.label
    if isinstance(label, numbers.Number):
        packed = struct.pack(_IR_FORMAT, 0, float(label), header.id,
                             header.id2)
    else:
        label = onp.asarray(label, dtype=onp.float32)
        packed = struct.pack(_IR_FORMAT, label.size, 0.0, header.id,
                             header.id2) + label.tobytes()
    return packed + s


def unpack(s: bytes):
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = onp.frombuffer(s[:flag * 4], dtype=onp.float32)
        s = s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    from . import image
    buf = image.imencode(img, img_fmt, quality)
    return pack(header, buf)


def unpack_img(s, iscolor=1):
    from . import image
    header, buf = unpack(s)
    return header, image.imdecode_np(buf, iscolor)


def pack_raw(header, img):
    """Pack a pre-decoded HWC uint8 image ("MXTR" passthrough format).

    The native iterator (src/image_iter.cc ProcessSample) detects the
    magic and skips JPEG decode — for pre-decoded datasets and IO
    benchmarks where decode throughput would measure the host CPU
    rather than the pipeline.
    """
    img = onp.ascontiguousarray(img, dtype=onp.uint8)
    if img.ndim != 3 or img.shape[2] != 3:
        raise ValueError(f"pack_raw needs HWC RGB uint8, got {img.shape}")
    h, w = img.shape[:2]
    payload = b"MXTR" + struct.pack("<ii", h, w) + img.tobytes()
    return pack(header, payload)


def unpack_raw(s):
    """Inverse of pack_raw (pure-Python side)."""
    header, buf = unpack(s)
    if buf[:4] != b"MXTR":
        raise ValueError("not a raw MXTR record")
    h, w = struct.unpack("<ii", buf[4:12])
    img = onp.frombuffer(buf, onp.uint8, count=3 * h * w,
                         offset=12).reshape(h, w, 3)
    return header, img
