"""Packed-function FFI — Python side (native side: src/ffi.cc).

Role parity with the reference's new FFI (python/mxnet/_ffi/ over
src/runtime/packed_func.h + registry.h): ONE calling convention for
every crossing of the C boundary.  Functions registered from C++
(native built-ins) and from Python (callbacks) live in the same global
name table; either side calls either side without per-function ctypes
signatures.

    from incubator_mxnet_tpu import _ffi
    ver = _ffi.get_global_func("mxt.runtime.version")()

    @_ffi.register_func("frontend.scale")
    def scale(x, k):
        return x * k
    # now callable from C++ via MXTFuncCallByName("frontend.scale", ...)
"""
from __future__ import annotations

import ctypes

from ..native import lib as _lib

__all__ = ["available", "get_global_func", "list_global_func_names",
           "register_func", "Function"]

TYPE_INT, TYPE_FLOAT, TYPE_STR, TYPE_HANDLE, TYPE_NULL = range(5)


class MXTValue(ctypes.Union):
    """Mirror of MXTValue (src/include/mxt/ffi.h)."""

    _fields_ = [("v_int", ctypes.c_int64), ("v_float", ctypes.c_double),
                ("v_handle", ctypes.c_void_p), ("v_str", ctypes.c_char_p)]


PACKED_CFUNC = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.POINTER(MXTValue), ctypes.POINTER(ctypes.c_int),
    ctypes.c_int, ctypes.POINTER(MXTValue), ctypes.POINTER(ctypes.c_int),
    ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p))

_libc = ctypes.CDLL(None, use_errno=True)
_libc.strdup.restype = ctypes.c_void_p
_libc.strdup.argtypes = [ctypes.c_char_p]

# registered ctypes callbacks must outlive their registration
_registered: dict[str, object] = {}
_declared = False


def _declare():
    global _declared
    if _declared or _lib is None:
        return
    vp = ctypes.c_void_p
    _lib.MXTFuncRegister.argtypes = [ctypes.c_char_p, PACKED_CFUNC, vp,
                                     ctypes.c_int]
    _lib.MXTFuncGet.argtypes = [ctypes.c_char_p, ctypes.POINTER(vp)]
    _lib.MXTFuncListNames.argtypes = [
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p))]
    _lib.MXTFuncCall.argtypes = [vp, ctypes.POINTER(MXTValue),
                                 ctypes.POINTER(ctypes.c_int), ctypes.c_int,
                                 ctypes.POINTER(MXTValue),
                                 ctypes.POINTER(ctypes.c_int)]
    _lib.MXTFuncRetStr.argtypes = [ctypes.c_char_p,
                                   ctypes.POINTER(MXTValue),
                                   ctypes.POINTER(ctypes.c_int)]
    _declared = True


def available() -> bool:
    return _lib is not None


def _check(rc):
    if rc != 0:
        raise RuntimeError("FFI error: "
                           + _lib.MXTGetLastError().decode("utf-8",
                                                           "replace"))


def _marshal(pyargs):
    """Python values -> (MXTValue[], int[], keepalive list)."""
    n = len(pyargs)
    vals = (MXTValue * max(n, 1))()
    codes = (ctypes.c_int * max(n, 1))()
    keep = []
    for i, a in enumerate(pyargs):
        if a is None:
            codes[i] = TYPE_NULL
        elif isinstance(a, bool) or isinstance(a, int):
            vals[i].v_int = int(a)
            codes[i] = TYPE_INT
        elif isinstance(a, float):
            vals[i].v_float = a
            codes[i] = TYPE_FLOAT
        elif isinstance(a, str):
            b = a.encode()
            keep.append(b)  # the union holds a borrowed pointer
            vals[i].v_str = b
            codes[i] = TYPE_STR
        elif isinstance(a, ctypes.c_void_p):
            vals[i].v_handle = a.value
            codes[i] = TYPE_HANDLE
        else:
            raise TypeError(f"FFI cannot marshal {type(a).__name__}; "
                            "pass int/float/str/None")
    return vals, codes, keep


def _unmarshal(val: MXTValue, code: int):
    if code == TYPE_INT:
        return val.v_int
    if code == TYPE_FLOAT:
        return val.v_float
    if code == TYPE_STR:
        return val.v_str.decode() if val.v_str is not None else ""
    if code == TYPE_HANDLE:
        return val.v_handle
    return None


class Function:
    """A handle to a packed function in the global table."""

    def __init__(self, handle, name):
        self._handle = handle
        self.name = name

    def __call__(self, *args):
        vals, codes, keep = _marshal(args)
        ret = MXTValue()
        ret_code = ctypes.c_int(TYPE_NULL)
        _check(_lib.MXTFuncCall(self._handle, vals, codes, len(args),
                                ctypes.byref(ret), ctypes.byref(ret_code)))
        del keep
        return _unmarshal(ret, ret_code.value)

    def __repr__(self):
        return f"<ffi.Function {self.name}>"


def get_global_func(name: str) -> Function:
    if _lib is None:
        raise RuntimeError("native runtime library unavailable — the FFI "
                           "needs libmxtpu.so (see native/__init__.py)")
    _declare()
    h = ctypes.c_void_p()
    _check(_lib.MXTFuncGet(name.encode(), ctypes.byref(h)))
    return Function(h, name)


def list_global_func_names():
    if _lib is None:
        return []
    _declare()
    n = ctypes.c_uint32()
    names = ctypes.POINTER(ctypes.c_char_p)()
    _check(_lib.MXTFuncListNames(ctypes.byref(n), ctypes.byref(names)))
    return [names[i].decode() for i in range(n.value)]


def register_func(name, f=None, override=False):
    """Register a Python callable under a global FFI name.

    Usable directly (``register_func("n", fn)``) or as a decorator
    (reference python/mxnet/_ffi style)::

        @_ffi.register_func("frontend.scale")
        def scale(x, k): return x * k
    """
    if f is None:
        return lambda fn: register_func(name, fn, override=override)
    if _lib is None:
        raise RuntimeError("native runtime library unavailable — the FFI "
                           "needs libmxtpu.so (see native/__init__.py)")
    _declare()

    def packed(args, codes, num, ret, ret_code, _resource, err_msg):
        try:
            pyargs = [_unmarshal(args[i], codes[i]) for i in range(num)]
            out = f(*pyargs)
            if out is None:
                ret_code[0] = TYPE_NULL
            elif isinstance(out, bool) or isinstance(out, int):
                ret[0].v_int = int(out)
                ret_code[0] = TYPE_INT
            elif isinstance(out, float):
                ret[0].v_float = out
                ret_code[0] = TYPE_FLOAT
            elif isinstance(out, str):
                # native-side thread-local storage owns the copy
                _check(_lib.MXTFuncRetStr(out.encode(), ret, ret_code))
            else:
                raise TypeError(
                    f"FFI cannot marshal return {type(out).__name__}")
            return 0
        except Exception as e:  # mxlint: allow-broad-except(marshalled into the C error slot and surfaced to the caller via the -1 return)
            err_msg[0] = ctypes.cast(
                _libc.strdup(f"{type(e).__name__}: {e}".encode()),
                ctypes.c_char_p)
            return -1

    cb = PACKED_CFUNC(packed)
    _check(_lib.MXTFuncRegister(name.encode(), cb, None,
                                1 if override else 0))
    _registered[name] = cb  # keep the ctypes thunk alive
    return f
