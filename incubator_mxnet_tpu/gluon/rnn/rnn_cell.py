"""Recurrent cells (reference python/mxnet/gluon/rnn/rnn_cell.py).

Cells are fine-grained Blocks for custom recurrences; ``unroll`` runs a
Python loop eagerly or is captured by hybridize into a static graph.
The fused layers in rnn_layer.py are the performance path.
"""
from __future__ import annotations

from ... import initializer as init_mod
from ... import ndarray as nd
from ...ops.registry import invoke
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
           "ResidualCell", "ZoneoutCell"]


class RecurrentCell(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=nd.zeros, ctx=None, **kwargs):
        return [func(info["shape"], ctx=ctx, **kwargs)
                for info in self.state_info(batch_size)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        axis = layout.find("T")
        batch = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch, ctx=inputs.ctx)
        states = begin_state
        outputs = []
        for t in range(length):
            idx = [slice(None)] * inputs.ndim
            idx[axis] = t
            out, states = self(inputs[tuple(idx)], states)
            outputs.append(out)
        if merge_outputs or merge_outputs is None:
            outputs = invoke("stack", *outputs, axis=axis)
        return outputs, states

    def forward(self, inputs, states):
        raise NotImplementedError


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._activation = activation
        self.i2h_weight = Parameter("i2h_weight", shape=(hidden_size, input_size),
                                    init=init_mod.Xavier(), allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight", shape=(hidden_size, hidden_size),
                                    init=init_mod.Xavier())
        self.i2h_bias = Parameter("i2h_bias", shape=(hidden_size,),
                                  init=init_mod.Zero())
        self.h2h_bias = Parameter("h2h_bias", shape=(hidden_size,),
                                  init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _ensure(self, x, factor=1):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (self._hidden_size * factor, x.shape[-1])
            self.i2h_weight._finish_deferred_init()

    def forward(self, inputs, states):
        self._ensure(inputs)
        i2h = invoke("FullyConnected", inputs, self.i2h_weight.data(),
                     self.i2h_bias.data(), num_hidden=self._hidden_size,
                     flatten=False)
        h2h = invoke("FullyConnected", states[0], self.h2h_weight.data(),
                     self.h2h_bias.data(), num_hidden=self._hidden_size,
                     flatten=False)
        out = invoke("Activation", i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        H = hidden_size
        self.i2h_weight = Parameter("i2h_weight", shape=(4 * H, input_size),
                                    init=init_mod.Xavier(), allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight", shape=(4 * H, H),
                                    init=init_mod.Xavier())
        self.i2h_bias = Parameter("i2h_bias", shape=(4 * H,), init=init_mod.Zero())
        self.h2h_bias = Parameter("h2h_bias", shape=(4 * H,), init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, inputs, states):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (4 * self._hidden_size, inputs.shape[-1])
            self.i2h_weight._finish_deferred_init()
        H = self._hidden_size
        gates = invoke("FullyConnected", inputs, self.i2h_weight.data(),
                       self.i2h_bias.data(), num_hidden=4 * H, flatten=False) + \
            invoke("FullyConnected", states[0], self.h2h_weight.data(),
                   self.h2h_bias.data(), num_hidden=4 * H, flatten=False)
        i, f, g, o = invoke("split", gates, num_outputs=4, axis=-1)
        c = invoke("sigmoid", f) * states[1] + \
            invoke("sigmoid", i) * invoke("tanh", g)
        h = invoke("sigmoid", o) * invoke("tanh", c)
        return h, [h, c]


class GRUCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        H = hidden_size
        self.i2h_weight = Parameter("i2h_weight", shape=(3 * H, input_size),
                                    init=init_mod.Xavier(), allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight", shape=(3 * H, H),
                                    init=init_mod.Xavier())
        self.i2h_bias = Parameter("i2h_bias", shape=(3 * H,), init=init_mod.Zero())
        self.h2h_bias = Parameter("h2h_bias", shape=(3 * H,), init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, inputs, states):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (3 * self._hidden_size, inputs.shape[-1])
            self.i2h_weight._finish_deferred_init()
        H = self._hidden_size
        i2h = invoke("FullyConnected", inputs, self.i2h_weight.data(),
                     self.i2h_bias.data(), num_hidden=3 * H, flatten=False)
        h2h = invoke("FullyConnected", states[0], self.h2h_weight.data(),
                     self.h2h_bias.data(), num_hidden=3 * H, flatten=False)
        i2h_r, i2h_z, i2h_n = invoke("split", i2h, num_outputs=3, axis=-1)
        h2h_r, h2h_z, h2h_n = invoke("split", h2h, num_outputs=3, axis=-1)
        r = invoke("sigmoid", i2h_r + h2h_r)
        z = invoke("sigmoid", i2h_z + h2h_z)
        n = invoke("tanh", i2h_n + r * h2h_n)
        out = (1.0 - z) * n + z * states[0]
        return out, [out]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return sum((c.state_info(batch_size)
                    for c in self._children.values()), [])

    def begin_state(self, batch_size=0, **kwargs):
        return sum((c.begin_state(batch_size, **kwargs)
                    for c in self._children.values()), [])

    def forward(self, inputs, states):
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, st = cell(inputs, states[pos:pos + n])
            pos += n
            next_states.extend(st)
        return inputs, next_states


class ModifierCell(RecurrentCell):
    """Base for cells that wrap a base_cell and modify its behavior
    (reference rnn/rnn_cell.py ModifierCell — parent of Residual/
    Zoneout): delegates state handling to the wrapped cell."""

    def __init__(self, base_cell, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size, **kwargs)


class DropoutCell(RecurrentCell):
    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def forward(self, inputs, states):
        from ... import autograd, random as _random
        from ...ndarray import NDArray as _ND
        if self._rate and autograd.is_training():
            key = _ND(_random.next_key(), ctx=inputs.ctx)
            inputs = invoke("Dropout", inputs, key, p=self._rate,
                            mode="training")
        return inputs, states


class ResidualCell(ModifierCell):
    def forward(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0,
                 **kwargs):
        super().__init__(base_cell, **kwargs)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def begin_state(self, batch_size=0, **kwargs):
        self._prev_output = None
        return self.base_cell.begin_state(batch_size, **kwargs)

    def forward(self, inputs, states):
        from ... import autograd
        from ... import ndarray as nd_mod
        out, new_states = self.base_cell(inputs, states)
        if autograd.is_training():
            def mask(rate, like):
                return nd_mod.random.bernoulli(1 - rate, like.shape,
                                               ctx=like.ctx)
            if self._zo:
                prev = self._prev_output if self._prev_output is not None \
                    else nd_mod.zeros_like(out)
                m = mask(self._zo, out)
                out = m * out + (1 - m) * prev
            if self._zs:
                new_states = [mask(self._zs, ns) * ns + (1 - mask(self._zs, ns)) * s
                              for ns, s in zip(new_states, states)]
        self._prev_output = out
        return out, new_states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return self.l_cell.state_info(batch_size) + \
            self.r_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.l_cell.begin_state(batch_size, **kwargs) + \
            self.r_cell.begin_state(batch_size, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        axis = layout.find("T")
        batch = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch, ctx=inputs.ctx)
        nl = len(self.l_cell.state_info())
        l_out, l_states = self.l_cell.unroll(
            length, inputs, begin_state[:nl], layout, True)
        rev = invoke("flip", inputs, axis=axis)
        r_out, r_states = self.r_cell.unroll(
            length, rev, begin_state[nl:], layout, True)
        r_out = invoke("flip", r_out, axis=axis)
        out = invoke("concat", l_out, r_out, dim=-1)
        return out, l_states + r_states

    def forward(self, inputs, states):
        raise NotImplementedError("BidirectionalCell supports unroll() only")


# Hybrid aliases: every cell here is already a HybridBlock (whole-graph
# jit via hybridize), so the reference's separate Hybrid* hierarchy
# (rnn/rnn_cell.py HybridRecurrentCell/HybridSequentialRNNCell)
# collapses to aliases.
HybridRecurrentCell = RecurrentCell
HybridSequentialRNNCell = SequentialRNNCell
