"""Fused RNN layers over the lax.scan RNN op.

Reference: python/mxnet/gluon/rnn/rnn_layer.py (_RNNLayer) backed by the
cuDNN fused ``RNN`` op (src/operator/rnn-inl.h); here the op is a
lax.scan whose per-step body fuses into MXU matmuls (BASELINE config 5).
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import initializer as init_mod
from ... import ndarray as nd
from ...ndarray import NDArray
from ...ops.registry import invoke
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RNN", "LSTM", "GRU"]

_NGATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, mode, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        self.params_flat = Parameter(
            "rnn_param", shape=(self._param_size(input_size),) if input_size
            else (0,), init=i2h_weight_initializer or init_mod.Xavier(),
            allow_deferred_init=True)

    def _param_size(self, input_size):
        if not input_size:
            return 0
        ng = _NGATES[self._mode]
        H, D = self._hidden_size, self._dir
        size = 0
        for layer in range(self._num_layers):
            in_dim = input_size if layer == 0 else H * D
            size += D * ng * H * (in_dim + H)  # weights
        for layer in range(self._num_layers):
            size += D * 2 * ng * H  # biases
        return size

    def state_info(self, batch_size=0):
        num = self._num_layers * self._dir
        shapes = [{"shape": (num, batch_size, self._hidden_size),
                   "__layout__": "LNC"}]
        if self._mode == "lstm":
            shapes.append({"shape": (num, batch_size, self._hidden_size),
                           "__layout__": "LNC"})
        return shapes

    def begin_state(self, batch_size=0, func=nd.zeros, ctx=None, **kwargs):
        return [func(info["shape"], ctx=ctx, **kwargs)
                for info in self.state_info(batch_size)]

    def forward(self, inputs, states=None):
        if self._layout == "NTC":
            inputs = inputs.transpose((1, 0, 2))
        T, B, I = inputs.shape
        if self.params_flat._data is None:
            self.params_flat.shape = (self._param_size(I),)
            self.params_flat._finish_deferred_init()
        return_states = states is not None
        if states is None:
            states = self.begin_state(B, ctx=inputs.ctx,
                                      dtype=str(inputs.dtype))
        if isinstance(states, NDArray):
            states = [states]
        args = [inputs, self.params_flat.data(), states[0]]
        if self._mode == "lstm":
            args.append(states[1])
        outs = invoke("RNN", *args, state_size=self._hidden_size,
                      num_layers=self._num_layers, mode=self._mode,
                      bidirectional=self._dir == 2, p=self._dropout)
        if self._mode == "lstm":
            out, hN, cN = outs
            new_states = [hN, cN]
        else:
            out, hN = outs
            new_states = [hN]
        if self._layout == "NTC":
            out = out.transpose((1, 0, 2))
        if return_states:
            return out, new_states
        return out


class RNN(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, mode, **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)
