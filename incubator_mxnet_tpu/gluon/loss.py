"""Loss functions (reference python/mxnet/gluon/loss.py)."""
from __future__ import annotations

from ..ndarray import NDArray
from ..ops.registry import invoke
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CosineEmbeddingLoss", "CTCLoss",
           "PoissonNLLLoss", "SDMLLoss"]


def _apply_weighting(loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(pred, label):
    if label.shape != pred.shape:
        label = label.reshape(pred.shape)
    return label


class Loss(HybridBlock):
    """Base loss (reference loss.py:54): weight + batch_axis, mean over
    non-batch axes."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def _mean(self, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        if axes:
            return invoke("mean", loss, axis=axes)
        return loss


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = invoke("square", label - pred)
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return self._mean(loss)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = invoke("abs", label - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(pred, label)
        if not self._from_sigmoid:
            # max(x,0) - x*z + log(1+exp(-|x|)) — numerically stable BCE
            loss = invoke("relu", pred) - pred * label + \
                invoke("log1p", invoke("exp", -invoke("abs", pred)))
            if pos_weight is not None:
                loss = loss + (pos_weight - 1) * label * (
                    invoke("log1p", invoke("exp", -invoke("abs", pred))) +
                    invoke("relu", -pred))
        else:
            eps = 1e-12
            loss = -(invoke("log", pred + eps) * label +
                     invoke("log", 1.0 - pred + eps) * (1.0 - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Softmax CE (reference loss.py SoftmaxCrossEntropyLoss)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        if (self._sparse_label and not self._from_logits and pred.ndim == 2
                and self._axis in (-1, 1)):
            # fused path: one Pallas pass, softmax never materialized
            # (ops/nn_ops.py softmax_xent; XLA fallback built in)
            loss = invoke("softmax_xent", pred, label)
            loss = invoke("reshape", loss, shape=(-1, 1))
            loss = _apply_weighting(loss, self._weight, sample_weight)
            return self._mean(loss)
        if not self._from_logits:
            pred = invoke("log_softmax", pred, axis=self._axis)
        if self._sparse_label:
            loss = -invoke("pick", pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(pred, label)
            loss = -invoke("sum", pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = invoke("log_softmax", pred, axis=self._axis)
        loss = label * (invoke("log", label + 1e-12) - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = invoke("abs", label - pred)
        loss = invoke("where", loss > self._rho,
                      loss - 0.5 * self._rho,
                      (0.5 / self._rho) * invoke("square", loss))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = invoke("relu", self._margin - pred * label)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = invoke("square", invoke("relu", self._margin - pred * label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = invoke("relu", pred) - pred * label + \
            invoke("log1p", invoke("exp", -invoke("abs", pred)))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(pred, positive)
        negative = _reshape_like(pred, negative)
        loss = invoke("sum", invoke("square", pred - positive) -
                      invoke("square", pred - negative),
                      axis=tuple(range(1, pred.ndim)))
        loss = invoke("relu", loss + self._margin)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        def cos_sim(a, b):
            num = invoke("sum", a * b, axis=-1)
            den = invoke("norm", a, axis=-1) * invoke("norm", b, axis=-1)
            return num / (den + 1e-12)

        sim = cos_sim(input1, input2)
        label = label.reshape((-1,))
        loss = invoke("where", label == 1, 1.0 - sim,
                      invoke("relu", sim - self._margin))
        return _apply_weighting(loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """CTC (reference loss.py CTCLoss; op src/operator/nn/ctc_loss.cc)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        from .. import ndarray as nd
        if self._layout == "NTC":
            pred = pred.transpose((1, 0, 2))
        if self._label_layout == "TN":
            label = label.transpose((1, 0))
        B = pred.shape[1]
        if pred_lengths is None:
            pred_lengths = nd.full((B,), pred.shape[0], dtype="int32",
                                   ctx=pred.ctx)
        if label_lengths is None:
            label_lengths = nd.full((B,), label.shape[1], dtype="int32",
                                    ctx=pred.ctx)
        loss = invoke("ctc_loss", pred, label, pred_lengths, label_lengths)
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    """Poisson negative log likelihood (reference loss.py:800):
    from_logits → exp(pred) - target*pred; else pred - target*log(pred+eps);
    compute_full adds the Stirling approximation for target > 1."""

    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight=weight, batch_axis=batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, target, sample_weight=None, epsilon=1e-08):
        import math as _math
        target = _reshape_like(pred, target)
        if self._from_logits:
            loss = invoke("exp", pred) - target * pred
        else:
            loss = pred - target * invoke("log", pred + epsilon)
        if self._compute_full:
            # guard the masked-out region: 0*log(0) would NaN the whole
            # mean even though the mask zeroes it (the reference formula
            # has this hazard; evaluate Stirling on clamped targets)
            safe_t = invoke("maximum", target, invoke("ones_like", target))
            stirling = (safe_t * invoke("log", safe_t) - safe_t
                        + 0.5 * invoke("log", 2 * _math.pi * safe_t))
            loss = loss + stirling * (target > 1)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return invoke("mean", loss)


class SDMLLoss(Loss):
    """Batchwise Smoothed Deep Metric Learning loss (reference
    loss.py:935): aligned batches x1/x2, softmax over negative pairwise
    euclidean distances against a label-smoothed identity target via KL
    divergence (Pereyra et al., arXiv:1701.06548)."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight=weight, batch_axis=batch_axis, **kwargs)
        self.kl_loss = KLDivLoss(from_logits=True)
        self.smoothing_parameter = smoothing_parameter

    def forward(self, x1, x2):
        batch_size, dim = x1.shape
        # distances/labels via recorded ops so gradients flow
        x1e = invoke("broadcast_to", invoke("expand_dims", x1, axis=1),
                     shape=(batch_size, batch_size, dim))
        x2e = invoke("broadcast_to", invoke("expand_dims", x2, axis=0),
                     shape=(batch_size, batch_size, dim))
        distances = invoke("sum", invoke("square", x1e - x2e), axis=2)
        gold = invoke("eye", N=batch_size)
        labels = (gold * (1 - self.smoothing_parameter)
                  + (1 - gold) * self.smoothing_parameter
                  / (batch_size - 1))
        log_probs = invoke("log_softmax", -distances, axis=1)
        return self.kl_loss(log_probs, labels) * batch_size
