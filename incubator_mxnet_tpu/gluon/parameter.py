"""Parameter and ParameterDict (reference python/mxnet/gluon/parameter.py)."""
from __future__ import annotations

import re
import threading

import jax.numpy as jnp

from ..base import dtype_from_any
from ..context import Context, current_context
from ..ndarray import NDArray
from .. import initializer as init_mod

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(RuntimeError):
    """Parameter accessed before shape inference completed."""


# Thread-local map Parameter -> NDArray installed during hybridize tracing /
# functional apply, so ``param.data()`` yields tracer-backed arrays inside a
# jit trace (the CachedOp mechanism — see block.py).
_trace_state = threading.local()


def _trace_map():
    return getattr(_trace_state, "map", None)


class _TraceParams:
    def __init__(self, mapping):
        self.mapping = mapping

    def __enter__(self):
        self._prev = getattr(_trace_state, "map", None)
        _trace_state.map = self.mapping
        return self

    def __exit__(self, *exc):
        _trace_state.map = self._prev


class Parameter:
    """A weight/bias/aux tensor with lazy shape inference and grad buffer.

    Reference: gluon/parameter.py Parameter — deferred initialization
    (shape dims of 0 resolved at first forward), grad_req write/add/null,
    lr_mult/wd_mult consumed by the optimizer.
    """

    def __init__(self, name="param", grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype_from_any(dtype) or jnp.float32
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._data: NDArray | None = None
        self._deferred_init_args = None
        self._ctx = None

    # -- shape ------------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is not None:
            # merge: 0 / -1 dims are unknown
            assert len(self._shape) == len(new_shape), \
                f"shape mismatch for {self.name}: {self._shape} vs {new_shape}"
            merged = []
            for a, b in zip(self._shape, new_shape):
                if a in (0, -1):
                    merged.append(b)
                elif b in (0, -1) or a == b:
                    merged.append(a)
                else:
                    raise ValueError(
                        f"shape mismatch for {self.name}: {self._shape} vs {new_shape}")
            new_shape = tuple(merged)
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._grad = None
                self._data._grad_req = None
            else:
                self._data.attach_grad(req)

    def _shape_complete(self):
        return self._shape is not None and all(s > 0 for s in self._shape)

    # -- init -------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=init_mod.Uniform,
                   force_reinit=False):
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]  # single logical device; sharding handles multi-chip
        self._ctx = ctx
        if not self._shape_complete():
            if self.allow_deferred_init:
                self._deferred_init_args = (init, ctx, default_init)
                return
            raise DeferredInitializationError(
                f"Parameter {self.name} has unknown shape {self._shape}")
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init=init_mod.Uniform):
        data = NDArray(jnp.zeros(self._shape, self.dtype), ctx=ctx)
        initializer = init or self.init or default_init()
        if isinstance(initializer, str):
            initializer = init_mod.create(initializer)
        elif isinstance(initializer, type):
            initializer = initializer()
        initializer(self.name, data)
        self._data = data
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req)
        self._deferred_init_args = None

    def _finish_deferred_init(self):
        if self._deferred_init_args is None:
            return
        if not self._shape_complete():
            raise DeferredInitializationError(
                f"Parameter {self.name} still has unknown shape {self._shape}")
        init, ctx, default_init = self._deferred_init_args
        self._finish_init(init, ctx, default_init)

    # -- access -----------------------------------------------------------
    def _check_and_get(self):
        if self._data is None:
            if self._deferred_init_args is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name} deferred; run a forward pass or "
                    f"provide in_units/in_channels")
            raise RuntimeError(
                f"Parameter {self.name} has not been initialized; call "
                f".initialize() first")
        return self._data

    def data(self, ctx=None) -> NDArray:
        tm = _trace_map()
        if tm is not None and self in tm:
            return tm[self]
        return self._check_and_get()

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None) -> NDArray:
        d = self._check_and_get()
        if d.grad is None:
            raise RuntimeError(f"Parameter {self.name} has grad_req='null'")
        return d.grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        return [self._ctx or current_context()]

    def zero_grad(self):
        d = self._check_and_get()
        d.zero_grad()

    def set_data(self, data):
        d = self._check_and_get()
        if isinstance(data, NDArray):
            data = data.data
        d._set_data(jnp.asarray(data, d.data.dtype))

    def reset_ctx(self, ctx):
        self._ctx = ctx
        if self._data is not None:
            self._data = self._data.as_in_context(ctx)
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req)

    def cast(self, dtype):
        self.dtype = dtype_from_any(dtype)
        if self._data is not None:
            had_grad = self._data._grad is not None
            self._data = NDArray(self._data.data.astype(self.dtype),
                                 ctx=self._ctx)
            if had_grad:
                self._data.attach_grad(self._grad_req)

    def var(self):
        from .. import symbol
        return symbol.var(self.name, shape=self._shape, dtype=self.dtype)

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self._shape}, "
                f"dtype={jnp.dtype(self.dtype).name})")


class Constant(Parameter):
    """Non-trainable constant parameter (reference parameter.py Constant)."""

    def __init__(self, name, value=None):
        if value is None:
            name, value = "const", name
        if isinstance(value, NDArray):
            value_nd = value
        else:
            value_nd = NDArray(value)
        super().__init__(name=name, grad_req="null", shape=value_nd.shape,
                         dtype=value_nd.data.dtype,
                         init=init_mod.Constant(0))
        self._value = value_nd

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if self._data is not None and not force_reinit:
            return
        self._ctx = ctx or current_context()
        self._data = self._value.as_in_context(self._ctx)


class ParameterDict:
    """Ordered name→Parameter mapping with bulk ops (reference
    parameter.py ParameterDict).  Returned by ``Block.collect_params``."""

    def __init__(self, prefix="", shared=None):
        self.prefix = prefix
        self._params: dict[str, Parameter] = {}
        self._shared = shared

    def __repr__(self):
        body = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict(\n{body}\n)"

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def get(self, name, **kwargs):
        """Create-or-retrieve (reference ParameterDict.get)."""
        full = self.prefix + name
        if full in self._params:
            param = self._params[full]
            if "shape" in kwargs and kwargs["shape"] is not None:
                param.shape = kwargs["shape"] if not isinstance(
                    kwargs["shape"], int) else (kwargs["shape"],)
            return param
        if self._shared is not None and full in self._shared:
            param = self._shared[full]
        else:
            param = Parameter(full, **kwargs)
        self._params[full] = param
        return param

    def update(self, other):
        for k, v in other.items():
            self._params[k] = v

    def _add(self, name, param):
        self._params[name] = param

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        for p in self.values():
            p.initialize(init=init, ctx=ctx, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            if p.grad_req != "null" and p._data is not None:
                p.zero_grad()

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def save(self, filename, strip_prefix=""):
        from .. import ndarray as nd
        arrays = {}
        for name, p in self.items():
            key = name[len(strip_prefix):] if name.startswith(strip_prefix) else name
            arrays[key] = p.data()
        nd.save(filename, arrays)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from .. import ndarray as nd
        loaded = nd.load(filename)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self.items():
            if name in loaded:
                if p._data is None:
                    p.shape = loaded[name].shape
                    p.initialize(ctx=ctx)
                p.set_data(loaded[name])
            elif not allow_missing:
                raise KeyError(f"parameter {name} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self.keys())
            if extra:
                raise KeyError(f"extra parameters in {filename}: {sorted(extra)}")
