"""Trainer: applies an optimizer to a set of Parameters with KVStore sync.

Reference: python/mxnet/gluon/trainer.py:29 — `_init_kvstore` :183,
`step` :329, `_allreduce_grads` :380-404.  On TPU the gradient sync is an
XLA collective (psum over the device mesh) handled by the kvstore layer;
single-device training is a straight optimizer application.

Elastic mode (``elastic=True``, docs/fault_tolerance.md "Elasticity"):
the trainer joins the parameter servers' membership table, beats every
``MXNET_KVSTORE_BEAT_INTERVAL`` seconds from a background thread, and
treats a :class:`~incubator_mxnet_tpu.error.WorkerEvictedError` — from
its own beat (the eviction notice) or from a push/pull — as the signal
to checkpoint synchronously (``checkpoint_dir``) and surface the typed
error.  The driving loop then either lets this worker die (the
survivors' sync rounds have already re-balanced server-side) or calls
:meth:`rejoin` to re-enter the fleet and bootstrap from the current
server weights.  Fleet-size changes observed between steps are recorded
(``fleet_changes``) and checkpointed, and :meth:`reshard_restore` lands
a checkpoint saved on ANY mesh shape back onto the live parameters via
:meth:`AsyncCheckpointManager.reshard_restore`.

With chunked training (``chunk_steps=K`` / ``MXNET_TRAIN_CHUNK_STEPS``,
docs/fault_tolerance.md "Chunk boundaries"): a banked eviction notice
drains the current K-step chunk and surfaces — with its checkpoint —
only at the chunk boundary (worst case K steps), matching the
whole-loop-compiled path where mid-chunk steps live inside one XLA
dispatch.  Hard evictions raised by the sync itself still surface
immediately.
"""
from __future__ import annotations

import logging
import threading

from .. import fault
from .. import optimizer as opt_mod
from ..base import get_env, resolve_chunk_steps
from ..error import WorkerEvictedError
from ..ndarray import NDArray

__all__ = ["Trainer"]

_log = logging.getLogger("incubator_mxnet_tpu.gluon.trainer")


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None,
                 elastic=False, checkpoint_dir=None, checkpoint_keep=5,
                 chunk_steps=None):
        if isinstance(params, (dict,)) or hasattr(params, "values"):
            self._param_names = list(params.keys()) if hasattr(params, "keys") else None
            params = list(params.values())
        else:
            params = list(params)
            self._param_names = [p.name for p in params]
        self._params = params
        self._scale = 1.0
        if isinstance(optimizer, opt_mod.Optimizer):
            self._optimizer = optimizer
            if optimizer_params:
                raise ValueError(
                    "optimizer_params must be None when optimizer is an instance")
        else:
            optimizer_params = optimizer_params or {}
            self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer.param_dict = {
            i: p for i, p in enumerate(self._params)}
        self._updaters = [opt_mod.get_updater(self._optimizer)]
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._compression_params = compression_params
        self._update_on_kvstore = update_on_kvstore
        self._uokv = False
        # -- elastic runtime state ------------------------------------
        self._elastic = bool(elastic)
        self._ckpt = None
        if checkpoint_dir is not None:
            from ..checkpoint import AsyncCheckpointManager
            self._ckpt = AsyncCheckpointManager(checkpoint_dir,
                                                keep=checkpoint_keep)
        # chunk budget (MXNET_TRAIN_CHUNK_STEPS, docs/performance.md
        # "Chunked training loop"): elastic checkpoint/eviction
        # boundaries land BETWEEN K-step chunks — a banked eviction
        # notice drains the current chunk before surfacing, mirroring
        # the scanned loop where mid-chunk steps are inside one XLA
        # dispatch and cannot be interrupted anyway
        self._chunk_steps = resolve_chunk_steps(chunk_steps)
        self._step_count = 0
        self._evicted_reason = None
        self._live = None              # fleet size from the last beat
        self._last_fleet = None
        self.fleet_changes: list = []  # (step, old live, new live)
        self._beat_stop = threading.Event()
        self._beat_thread = None

    @property
    def optimizer(self):
        return self._optimizer

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        """Create the kvstore lazily (reference trainer.py:183)."""
        from .. import kvstore as kv_mod
        if self._kvstore_type is None:
            self._kvstore = None
        elif isinstance(self._kvstore_type, str):
            self._kvstore = kv_mod.create(self._kvstore_type)
            if self._compression_params:
                self._kvstore.set_gradient_compression(self._compression_params)
        else:
            self._kvstore = self._kvstore_type
        self._kv_initialized = True
        if self._kvstore is not None:
            # update_on_kvstore: the store/server applies the optimizer
            # and holds the AUTHORITATIVE weights (reference
            # trainer.py:183 dist default).  This is the mode in which a
            # rejoining elastic worker can bootstrap by pulling current
            # weights — under plain gradient aggregation the server only
            # holds merged gradients, so there is nothing to pull.
            self._uokv = bool(self._update_on_kvstore)
            if self._uokv:
                import copy
                opt = copy.copy(self._optimizer)
                # the server needs the update rule, not this trainer's
                # param_dict (live Parameters wrap device arrays and
                # locks — unpicklable, and meaningless server-side)
                opt.param_dict = {}
                # the client pre-scales every pushed gradient
                # (_sync_on_kvstore), so the server copy must not
                # rescale AGAIN with whatever the constructor captured
                opt.rescale_grad = 1.0
                self._kvstore.set_optimizer(opt)
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.init(i, p.data())
            if self._elastic:
                self._join_fleet()

    # ----------------------------------------------------- elasticity
    def _stop_beats(self):
        self._beat_stop.set()
        if self._beat_thread is not None and self._beat_thread.is_alive():
            self._beat_thread.join(timeout=10.0)
        self._beat_thread = None
        # a fresh Event per thread generation: a parked old thread can
        # never clear the stop flag out from under the new one
        self._beat_stop = threading.Event()

    def _join_fleet(self):
        kv = self._kvstore
        # stop the old heartbeat FIRST: a beat already in flight when we
        # rejoin could deliver a stale eviction notice and bank it over
        # the fresh membership
        self._stop_beats()
        infos = kv.join(getattr(kv, "rank", 0)) or []
        self._evicted_reason = None
        # fleet size from the join acks (the heartbeat probe may be
        # chaos-degraded; the join already rode the retry pipeline)
        live = min((i.get("live_workers", 0) for i in infos),
                   default=0)
        self._last_fleet = self._live = (
            live if live > 0 else getattr(kv, "num_workers", 1))
        stop = self._beat_stop
        interval = get_env("MXNET_KVSTORE_BEAT_INTERVAL", 5.0, float)
        self._beat_thread = threading.Thread(
            target=self._beat_loop, args=(interval, stop), daemon=True,
            name="trainer-heartbeat")
        self._beat_thread.start()

    def _beat_loop(self, interval, stop):
        while not stop.wait(interval):
            try:
                vitals = self._kvstore.beat()
            except WorkerEvictedError as e:
                # the beat IS the eviction-notice delivery path: bank
                # it; the next step() checkpoints and surfaces it
                if not stop.is_set():
                    self._evicted_reason = str(e)
                return
            except Exception as e:  # mxlint: allow-broad-except(a dead heartbeat thread silently evicts a HEALTHY worker — any failure here (injected PermanentFault, marshalled server error) must be logged and survived, never kill the loop)
                _log.warning("trainer heartbeat failed (%s: %s); a "
                             "missed beat burns eviction budget, "
                             "retrying next interval",
                             type(e).__name__, e)
                continue
            if vitals:
                live = min(v.get("live_workers", 0) for v in vitals)
                if live > 0:
                    self._live = live

    def _at_chunk_boundary(self):
        """Whether the trainer sits between chunks: ``_step_count``
        completed steps, so a boundary is any multiple of the chunk
        budget (including 0 — before the first chunk starts)."""
        return (self._chunk_steps <= 1
                or self._step_count % self._chunk_steps == 0)

    def _param_tree(self):
        tree = {}
        for i, p in enumerate(self._params):
            if p._data is None:
                continue
            name = (self._param_names[i] if self._param_names is not None
                    else str(i))
            tree[name] = p.data()
        return tree

    def _on_evicted(self, reason):
        """Checkpoint-on-eviction-notice, then surface the typed error."""
        self._evicted_reason = reason
        saved = ""
        if self._ckpt is not None:
            self._ckpt.save(self._step_count, self._param_tree(),
                            wait=True)
            saved = (f"; eviction checkpoint saved at step "
                     f"{self._step_count} in {self._ckpt.directory}")
        from .. import flightrec
        flightrec.record(flightrec.MEMBERSHIP, "trainer.evicted",
                         severity="error", step=self._step_count,
                         reason=str(reason)[:200],
                         checkpointed=self._ckpt is not None)
        err = WorkerEvictedError(
            f"worker evicted from the fleet at step {self._step_count} "
            f"({reason}){saved}; call rejoin() to re-enter and "
            "bootstrap from current weights")
        # the eviction is about to cross the trainer's top boundary:
        # the black box dumps the membership/checkpoint history that
        # led here (rate-limited, best-effort, never masks the raise)
        flightrec.note_error("trainer", err)
        raise err

    def rejoin(self, bootstrap=True):
        """Re-enter the fleet after a
        :class:`~incubator_mxnet_tpu.error.WorkerEvictedError`: join the
        membership table again, bootstrap, and restart the heartbeat.

        Bootstrap depends on who holds the weights:

        * ``update_on_kvstore=True`` — the server applies the optimizer
          and holds the authoritative weights: pull them, so this
          worker enters the next round on the SURVIVORS' state, not its
          stale pre-eviction one;
        * gradient-aggregation mode — the server only holds merged
          gradients (pulling those into the weights would destroy the
          model): restore the newest local checkpoint instead, which is
          exactly the eviction checkpoint this trainer saved on notice.
        """
        if not self._kv_initialized:
            self._init_kvstore()
            return
        if self._kvstore is None:
            # a config mistake, NOT an eviction notice: the documented
            # `except WorkerEvictedError: rejoin()` recovery loop must
            # not swallow it and retry forever
            raise ValueError("rejoin() needs a kvstore-backed trainer")
        self._join_fleet()
        from .. import flightrec
        flightrec.record(flightrec.MEMBERSHIP, "trainer.rejoined",
                         step=self._step_count, bootstrap=bootstrap)
        if not bootstrap:
            return
        if self._uokv:
            for i, p in enumerate(self._params):
                if p.grad_req != "null" and p._data is not None:
                    self._kvstore.pull(i, out=p.data())
        elif self._ckpt is not None and self._ckpt.all_steps():
            tree = self._ckpt.restore()
            for i, p in enumerate(self._params):
                name = (self._param_names[i]
                        if self._param_names is not None else str(i))
                if name in tree and p._data is not None:
                    p.set_data(tree[name])

    def close(self):
        """Stop the heartbeat and gracefully leave the fleet (sync
        rounds re-balance immediately instead of burning the dead-after
        budget)."""
        self._stop_beats()
        if (self._elastic and self._kvstore is not None
                and self._evicted_reason is None):
            try:
                self._kvstore.leave()
            except (ConnectionError, TimeoutError):
                pass   # the fleet is gone; eviction will reap us

    @property
    def live_workers(self):
        """Live fleet size as of the last heartbeat (elastic mode), or
        the kvstore's static worker count."""
        if self._live is not None:
            return self._live
        if self._kvstore is not None:
            return self._kvstore.num_workers
        return 1

    def _note_fleet(self):
        live = self._live
        if live is None:
            return
        if self._last_fleet is not None and live != self._last_fleet:
            self.fleet_changes.append((self._step_count,
                                       self._last_fleet, live))
            _log.warning(
                "trainer: fleet size changed %d -> %d at step %d%s",
                self._last_fleet, live, self._step_count,
                "; checkpointing" if self._ckpt is not None else "")
            if self._ckpt is not None:
                # a fleet-size change is a reshard point: persist now so
                # a restore can re-lay the state out on the new shape
                self._ckpt.save(self._step_count, self._param_tree())
        self._last_fleet = live

    def reshard_restore(self, mesh, rule_fn=None, step=None):
        """Load a checkpoint saved on ANY mesh shape back into the live
        parameters, re-laid out on ``mesh`` via ``rule_fn`` (see
        :meth:`AsyncCheckpointManager.reshard_restore`).  Returns the
        restored ``{name: jax.Array}`` tree."""
        if self._ckpt is None:
            # config mistake, not an eviction — see rejoin()
            raise ValueError(
                "reshard_restore() needs checkpoint_dir configured")
        names = {}
        for i, p in enumerate(self._params):
            if p._data is None:
                continue
            name = (self._param_names[i] if self._param_names is not None
                    else str(i))
            names[name] = p
        tree = self._ckpt.reshard_restore(
            tree_spec={n: None for n in names}, mesh=mesh,
            rule_fn=rule_fn, step=step)
        for name, arr in tree.items():
            names[name].set_data(NDArray(arr))
        return tree

    # ------------------------------------------------------- training
    def allreduce_grads(self):
        """Sum gradients across devices/workers (reference trainer.py:380)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is None:
            return
        if self._kvstore.num_workers <= 1 and not self._elastic:
            # elastic mode always syncs through the server: the PS holds
            # the state a rejoiner bootstraps from, and the push/pull is
            # where an eviction notice surfaces
            return
        for i, p in enumerate(self._params):
            if p.grad_req != "null":
                grad = p.grad()
                self._kvstore.pushpull(i, grad, out=grad,
                                       priority=-i)

    def _sync_on_kvstore(self):
        """update_on_kvstore step: push (pre-scaled) gradients, pull
        the server-updated weights back (reference trainer.py:329
        _update_on_kvstore branch).  The rescale is applied client-side
        because the server's pickled optimizer was captured at
        ``set_optimizer`` time."""
        rescale = self._optimizer.rescale_grad
        for i, p in enumerate(self._params):
            if p.grad_req != "null" and p._data is not None:
                self._kvstore.push(i, p.grad() * rescale, priority=-i)
        for i, p in enumerate(self._params):
            if p.grad_req != "null" and p._data is not None:
                self._kvstore.pull(i, out=p.data(), priority=-i)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + rescale + optimizer update (reference trainer.py:329).

        ``batch_size`` is the GLOBAL batch: under elastic re-balancing
        the survivors take over the departed worker's share of the data,
        so the summed gradient — and this constant rescale — is
        fleet-size invariant (that is what makes an elastic run converge
        to the uninterrupted run's weights)."""
        fault.inject("trainer.step")
        if not self._kv_initialized:
            self._init_kvstore()
        if (self._elastic and self._evicted_reason is not None
                and self._at_chunk_boundary()):
            # a notice banked by the beat thread drains the current
            # chunk before surfacing: the eviction checkpoint then
            # lands ON a chunk boundary (worst-case notice latency =
            # chunk_steps steps, docs/fault_tolerance.md).  A hard
            # eviction raised by the sync itself (below) cannot be
            # deferred — the server already dropped us
            self._on_evicted(self._evicted_reason)
        self._optimizer.rescale_grad = self._scale / batch_size
        try:
            if self._uokv:
                self._sync_on_kvstore()
            else:
                self.allreduce_grads()
        except WorkerEvictedError as e:
            self._on_evicted(str(e))
        if self._elastic and self._at_chunk_boundary():
            self._note_fleet()
        if not self._uokv:
            self._update(ignore_stale_grad)
        self._step_count += 1

    def update(self, batch_size, ignore_stale_grad=False):
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updater = self._updaters[0]
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            updater(i, p.grad(), p.data())

    def zero_grad(self):
        for p in self._params:
            if p.grad_req != "null" and p._data is not None:
                p.zero_grad()

    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self._updaters[0].get_states(dump_optimizer=False))

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self._updaters[0].set_states(f.read())
