"""Trainer: applies an optimizer to a set of Parameters with KVStore sync.

Reference: python/mxnet/gluon/trainer.py:29 — `_init_kvstore` :183,
`step` :329, `_allreduce_grads` :380-404.  On TPU the gradient sync is an
XLA collective (psum over the device mesh) handled by the kvstore layer;
single-device training is a straight optimizer application.
"""
from __future__ import annotations

from .. import optimizer as opt_mod
from ..ndarray import NDArray

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict,)) or hasattr(params, "values"):
            self._param_names = list(params.keys()) if hasattr(params, "keys") else None
            params = list(params.values())
        else:
            params = list(params)
            self._param_names = [p.name for p in params]
        self._params = params
        self._scale = 1.0
        if isinstance(optimizer, opt_mod.Optimizer):
            self._optimizer = optimizer
            if optimizer_params:
                raise ValueError(
                    "optimizer_params must be None when optimizer is an instance")
        else:
            optimizer_params = optimizer_params or {}
            self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer.param_dict = {
            i: p for i, p in enumerate(self._params)}
        self._updaters = [opt_mod.get_updater(self._optimizer)]
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._compression_params = compression_params
        self._update_on_kvstore = update_on_kvstore

    @property
    def optimizer(self):
        return self._optimizer

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        """Create the kvstore lazily (reference trainer.py:183)."""
        from .. import kvstore as kv_mod
        if self._kvstore_type is None:
            self._kvstore = None
        elif isinstance(self._kvstore_type, str):
            self._kvstore = kv_mod.create(self._kvstore_type)
            if self._compression_params:
                self._kvstore.set_gradient_compression(self._compression_params)
        else:
            self._kvstore = self._kvstore_type
        self._kv_initialized = True
        if self._kvstore is not None:
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.init(i, p.data())

    def allreduce_grads(self):
        """Sum gradients across devices/workers (reference trainer.py:380)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is None or self._kvstore.num_workers <= 1:
            return
        for i, p in enumerate(self._params):
            if p.grad_req != "null":
                grad = p.grad()
                self._kvstore.pushpull(i, grad, out=grad,
                                       priority=-i)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + rescale + optimizer update (reference trainer.py:329)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self.allreduce_grads()
        self._update(ignore_stale_grad)

    def update(self, batch_size, ignore_stale_grad=False):
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updater = self._updaters[0]
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            updater(i, p.grad(), p.data())

    def zero_grad(self):
        for p in self._params:
            if p.grad_req != "null" and p._data is not None:
                p.zero_grad()

    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self._updaters[0].get_states(dump_optimizer=False))

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self._updaters[0].set_states(f.read())
