"""Block / HybridBlock: the define-by-run API with whole-graph compilation.

TPU-native re-design of the reference Gluon core
(python/mxnet/gluon/block.py — Block :251, HybridBlock :854, hybridize
:1172 → _build_cache :985 → CachedOp; C++ side src/imperative/cached_op.h).

The reference's CachedOp traces the block into an NNVM graph and replays
it through the engine.  Here ``hybridize()`` compiles the *entire* block
into one XLA executable via ``jax.jit``:

* Tracing: parameters are temporarily mapped to tracer-backed NDArrays
  (see parameter._TraceParams), the block's ``forward`` runs once under
  ``jax.jit`` tracing, and the jaxpr is compiled.  This is the analog of
  deferred-compute tracing (reference block.py:1340) + whole-graph bind.
* Autograd: when recording, the compiled forward runs under ``jax.vjp``
  and lands on the tape as a *single* node — backward through the block
  is one compiled XLA call (the CachedOp::Backward analog).
* Mutable state (BatchNorm moving stats): collected during tracing as
  extra outputs and written back after execution, replacing the
  reference's in-place aux-state mutation with a functional round-trip.
* static_alloc → XLA buffer donation of input activations;
  static_shape → cache keyed on input shapes (shape buckets).
"""
from __future__ import annotations

import threading

import jax
import numpy as onp

from .. import autograd
from .. import executor_cache as _xc
from .. import random as _random
from ..context import current_context
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict, _TraceParams, \
    DeferredInitializationError

__all__ = ["Block", "HybridBlock", "CachedOp"]

_state_updates = threading.local()


def register_state_update(param: Parameter, new_value):
    """BatchNorm-style aux-state update: defer if tracing, else apply."""
    collector = getattr(_state_updates, "stack", None)
    if collector:
        collector[-1].append((param, new_value))
    else:
        with autograd.pause():
            param._check_and_get()._set_data(
                new_value.data if isinstance(new_value, NDArray) else new_value)


class _CollectStateUpdates:
    def __enter__(self):
        if not hasattr(_state_updates, "stack"):
            _state_updates.stack = []
        self.updates = []
        _state_updates.stack.append(self.updates)
        return self.updates

    def __exit__(self, *exc):
        _state_updates.stack.pop()


class Block:
    """Base building block (reference gluon/block.py:251)."""

    def __init__(self, prefix=None, params=None):
        self._prefix = prefix or ""
        self._children: dict[str, Block] = {}
        self._reg_params: dict[str, Parameter] = {}
        self._forward_hooks: list = []
        self._forward_pre_hooks: list = []
        self._shared_params = params

    # -- registration -----------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            self.__dict__.setdefault("_children", {})[name] = value
        elif isinstance(value, Parameter):
            shared = self.__dict__.get("_shared_params")
            if shared is not None:
                # parameter sharing (reference Block(params=...) semantics):
                # an existing parameter of the same name is reused
                if name in shared:
                    value = shared[name]
                else:
                    suffix = [p for k, p in shared.items()
                              if k.endswith("." + name)]
                    if len(suffix) == 1:
                        value = suffix[0]
                    elif len(suffix) > 1:
                        raise ValueError(
                            f"shared params have multiple candidates for "
                            f"{name!r}: pass an unambiguous params dict "
                            "(e.g. layer.collect_params(), not the whole "
                            "net's)")
            self.__dict__.setdefault("_reg_params", {})[name] = value
            if not value.name or value.name == "param":
                value.name = name
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block
        return block

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix.rstrip("_") or type(self).__name__.lower()

    def name_scope(self):
        """Compat no-op scope (the reference used it for name prefixes)."""
        from ..name import Prefix
        return Prefix(self._prefix)

    @property
    def params(self) -> ParameterDict:
        d = ParameterDict(self._prefix)
        for name, p in self._reg_params.items():
            d._add(p.name if p.name != "param" else name, p)
        return d

    def collect_params(self, select=None) -> ParameterDict:
        """All params of self + descendants, qualified names
        (reference block.py collect_params)."""
        out = ParameterDict(self._prefix)
        self._collect_params_into(out, prefix="")
        if select is not None:
            import re
            pat = re.compile(select)
            filtered = ParameterDict(self._prefix)
            for k, v in out.items():
                if pat.match(k):
                    filtered._add(k, v)
            return filtered
        return out

    def _collect_params_into(self, out: ParameterDict, prefix: str):
        for name, p in self._reg_params.items():
            out._add(prefix + name, p)
        for cname, child in self._children.items():
            child._collect_params_into(out, prefix + cname + ".")

    # -- lifecycle --------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init=init, ctx=ctx,
                                         force_reinit=force_reinit)
        return self

    def cast(self, dtype):
        for p in self.collect_params().values():
            p.cast(dtype)
        for child in self._children.values():
            pass  # params already collected recursively
        self._cast_hook(dtype)
        return self

    def _cast_hook(self, dtype):
        for child in self._children.values():
            child._cast_hook(dtype)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def reset_ctx(self, ctx):
        self.collect_params().reset_ctx(ctx)

    # -- persistence (reference block.py:440 save_parameters / :496 load) -
    def save_parameters(self, filename, deduplicate=False):
        from .. import ndarray as nd
        arrays = {}
        for name, p in self.collect_params().items():
            arrays[name] = p.data()
        nd.save(filename, arrays)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        from .. import ndarray as nd
        loaded = nd.load(filename)
        if isinstance(loaded, list):
            raise ValueError("expected dict-of-arrays params file")
        params = self.collect_params()
        for name, p in params.items():
            if name in loaded:
                if p._data is None:
                    p.shape = loaded[name].shape
                    p.initialize(ctx=ctx)
                p.set_data(loaded[name])
            elif not allow_missing:
                raise KeyError(f"parameter {name} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(params.keys())
            if extra:
                raise KeyError(f"extra params in file: {sorted(extra)}")

    save_params = save_parameters
    load_params = load_parameters

    # -- hooks ------------------------------------------------------------
    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return _HookHandle(self._forward_hooks, hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return _HookHandle(self._forward_pre_hooks, hook)

    # -- call -------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        policy = getattr(self, "_amp_policy", None)
        if policy is not None:
            from ..amp import amp as _amp
            with _amp.policy_scope(policy):
                out = self.forward(*args, **kwargs)
        else:
            out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def summary(self, *inputs):
        """Print per-block output shapes (reference block.py summary)."""
        rows = []

        def add_hooks(block, prefix):
            def hook(blk, ins, out):
                outs = out if isinstance(out, (list, tuple)) else [out]
                shapes = [tuple(o.shape) for o in outs if isinstance(o, NDArray)]
                nparams = sum(int(onp.prod(p.shape)) for p in
                              blk._reg_params.values()
                              if p._shape_complete())
                rows.append((prefix or type(blk).__name__, shapes, nparams))
            handles.append(block.register_forward_hook(hook))
            for name, c in block._children.items():
                add_hooks(c, f"{prefix}.{name}" if prefix else name)

        handles: list = []
        add_hooks(self, "")
        try:
            self(*inputs)
        finally:
            for h in handles:
                h.detach()
        print(f"{'Layer':<40} {'Output shape':<24} {'Params':>12}")
        print("-" * 78)
        for name, shapes, nparams in rows:
            print(f"{name:<40} {str(shapes):<24} {nparams:>12}")
        total = sum(int(onp.prod(p.shape)) for p in
                    self.collect_params().values() if p._shape_complete())
        print("-" * 78)
        print(f"Total params: {total}")

    def __repr__(self):
        lines = [type(self).__name__ + "("]
        for name, child in self._children.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        lines.append(")")
        return "\n".join(lines)


class _HookHandle:
    def __init__(self, hook_list, hook):
        self._list = hook_list
        self._hook = hook

    def detach(self):
        if self._hook in self._list:
            self._list.remove(self._hook)


class CachedOp:
    """Whole-block compiled executable (reference src/imperative/cached_op.h:365).

    One instance per hybridized block; caches one compiled program per
    (input shapes, dtypes, training-mode) signature — the TPU analog of
    the reference's per-bucket executors.
    """

    def __init__(self, block: "HybridBlock", static_alloc=False,
                 static_shape=False):
        self.block = block
        self.static_alloc = static_alloc
        self.static_shape = static_shape
        self._site = f"cachedop:{type(block).__name__}"
        self._cache = _xc.TraceCache(self._site)

    def _ordered_params(self):
        return list(self.block.collect_params().values())

    def _build(self, sig, params, training):
        entry = {"single": True, "su_params": []}

        def pure(param_vals, input_vals, key):
            mapping = {p: NDArray(v) for p, v in zip(params, param_vals)}
            with _TraceParams(mapping), _random.key_scope(key), \
                    autograd._scope(None, training), _CollectStateUpdates() as su:
                outs = self.block.forward(*[NDArray(v) for v in input_vals])
            if isinstance(outs, (list, tuple)):
                entry["single"] = False
                out_vals = tuple(o.data for o in outs)
            else:
                out_vals = (outs.data,)
            entry["su_params"] = [p for p, _ in su]
            upd_vals = tuple(v.data if isinstance(v, NDArray) else v
                             for _, v in su)
            return out_vals, upd_vals

        # the unified choke point (executor_cache.Executor) owns the
        # sentinel instrumentation and the jit: one trace of `pure` ==
        # one XLA compile of this CachedOp; a varying input signature
        # shows up as churn at this site.  The uninstrumented fn rides
        # on the executor for the build-time IR lint, whose extra trace
        # must not count as a compile.
        entry["executor"] = _xc.Executor(
            pure, self._site,
            donate_argnums=(1,) if self.static_alloc else ())
        entry["jfn"] = entry["executor"].jfn
        return entry

    def __call__(self, *inputs):
        params = self._ordered_params()
        # deferred shape inference: fall back to one eager pass
        for p in params:
            if p._data is None and p._deferred_init_args is not None:
                return self.block.forward(*inputs)
        raw_params = [p._check_and_get().data for p in params]
        raw_inputs = [x.data for x in inputs]
        training = autograd.is_training()
        # param shapes/dtypes are part of the signature: a re-initialized
        # or reshaped/recast parameter must rebuild, not silently reuse a
        # stale executable entry
        sig = (tuple((tuple(a.shape), str(a.dtype)) for a in raw_inputs),
               training,
               tuple((tuple(a.shape), str(a.dtype)) for a in raw_params))
        # atomic against concurrent first calls with the same signature:
        # two threads must not double-build (and double-report to the
        # sentinel) one executable
        entry, hit = self._cache.get_or_create(
            sig, lambda: self._build(sig, params, training))
        if not hit:
            # build-time analyses through the unified choke point
            # (executor_cache.run_analyses; inert by default): the
            # exact pure fn this executable compiles, with the RNG key
            # declared intentionally-unused (deterministic nets ignore
            # it).  static_alloc contracts to donate the input
            # activations; without it the params and inputs are
            # caller-held (allow_undonated), so memlint only records
            # the peak-HBM estimate and lifetime stats.
            if _xc.lint_active() or _xc.memlint_active() \
                    or _xc.shardlint_active():
                entry["executor"].analyze(
                    (raw_params, raw_inputs, jax.random.PRNGKey(0)),
                    graphlint=dict(allow_unused_args=(2,),
                                   check_donation=self.static_alloc),
                    memlint=dict(
                        allow_undonated=(0,) if self.static_alloc
                        else (0, 1),
                        require_donation=self.static_alloc),
                    # no declared entry specs here (a hybridized block
                    # is single-chip unless export/fused-step paths say
                    # otherwise): shardlint still prices any collectives
                    # and records the per-site per-shard stats
                    shardlint=dict(allow_replicated=(0, 1, 2)))
        jfn = entry["jfn"]
        key = _random.next_key()

        recording = autograd.is_recording()
        grad_params = [p for p in params if p.grad_req != "null"]
        need_grad = recording and (
            grad_params or any(x._in_graph() for x in inputs))
        if need_grad:
            out_vals, vjp_fn, upd_vals = jax.vjp(
                lambda ps, xs: jfn(ps, xs, key), raw_params, raw_inputs,
                has_aux=True)
        else:
            out_vals, upd_vals = jfn(raw_params, raw_inputs, key)

        out_nds = tuple(NDArray(v, ctx=inputs[0].ctx if inputs else current_context())
                        for v in out_vals)
        # apply collected state updates (moving stats)
        for p, v in zip(entry["su_params"], upd_vals):
            with autograd.pause():
                p._check_and_get()._set_data(v)

        if need_grad:
            nd_inputs = [p._data for p in params] + \
                [x for x in inputs if isinstance(x, NDArray)]

            def tape_vjp(seed):
                if not isinstance(seed, tuple):
                    seed = (seed,)
                grad_ps, grad_xs = vjp_fn(seed)
                return tuple(grad_ps) + tuple(grad_xs)

            autograd._record(None, tape_vjp, inputs, nd_inputs,
                             list(range(len(nd_inputs))), out_nds)
        return out_nds[0] if entry["single"] else out_nds


class HybridBlock(Block):
    """Block that can compile to a single XLA program
    (reference gluon/block.py:854)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cached_op: CachedOp | None = None
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        """Enable whole-graph compilation (reference block.py:1172)."""
        self._active = active
        self._flags = {"static_alloc": static_alloc,
                       "static_shape": static_shape}
        self._cached_op = None
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                child._active = False  # only the outermost block compiles
        return self

    def _get_cached_op(self):
        if self._cached_op is None:
            self._cached_op = CachedOp(self, **self._flags)
        return self._cached_op

    def __call__(self, *args, **kwargs):
        if self._active and args and all(
                isinstance(a, NDArray) and
                not isinstance(a.data, jax.core.Tracer) for a in args):
            for hook in self._forward_pre_hooks:
                hook(self, args)
            policy = getattr(self, "_amp_policy", None)
            if policy is not None:
                # the CachedOp trace replays forward() via invoke, so the
                # policy must be active around it exactly as in the eager
                # path (the casts bake into the compiled graph)
                from ..amp import amp as _amp
                with _amp.policy_scope(policy):
                    out = self._get_cached_op()(*args)
            else:
                out = self._get_cached_op()(*args)
            for hook in self._forward_hooks:
                hook(self, args, out)
            return out
        return super().__call__(*args, **kwargs)

    # -- reference hybrid_forward compatibility ---------------------------
    def forward(self, *args, **kwargs):
        if type(self).hybrid_forward is not HybridBlock.hybrid_forward:
            from .. import ndarray as F
            param_kwargs = {name: p.data() for name, p in
                            self._reg_params.items()}
            return self.hybrid_forward(F, *args, **param_kwargs, **kwargs)
        raise NotImplementedError(
            f"{type(self).__name__} must implement forward() or "
            f"hybrid_forward()")

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError

    # -- functional bridge (TPU-first: feeds pjit/shard_map) --------------
    def functional(self):
        """Return ``(params_dict, apply_fn)`` for pure-functional use.

        ``apply_fn(params_dict, *inputs, training=False, key=None)`` is a
        pure function suitable for ``jax.jit``/``pjit``/``shard_map`` —
        the bridge from the imperative Gluon API to SPMD training (used
        by the parallel layer; no reference equivalent, SURVEY.md §7
        stage 10).
        """
        named = list(self.collect_params().items())
        params_dict = {name: p.data().data for name, p in named}
        name2param = {name: p for name, p in named}
        param2name = {p: name for name, p in named}

        def apply_fn(pvals, *input_vals, training=False, key=None,
                     with_updates=False):
            # key=None stays None: key_scope derives PRNGKey(0) lazily,
            # so a deterministic forward traces no dead PRNG equations
            # (graphlint GL-DEAD001 on every inference graph otherwise)
            mapping = {name2param[n]: NDArray(v) for n, v in pvals.items()}
            policy = getattr(self, "_amp_policy", None)
            if policy is not None:
                from ..amp import amp as _amp
                pol_ctx = _amp.policy_scope(policy)
            else:
                import contextlib as _cl
                pol_ctx = _cl.nullcontext()
            with _TraceParams(mapping), _random.key_scope(key), \
                    autograd._scope(None, training), \
                    _CollectStateUpdates() as su, pol_ctx:
                outs = self.forward(*[NDArray(v) for v in input_vals])
            if isinstance(outs, (list, tuple)):
                out = tuple(o.data for o in outs)
            else:
                out = outs.data
            if with_updates:
                updates = {param2name[p]: (v.data if isinstance(v, NDArray)
                                           else v)
                           for p, v in su if p in param2name}
                return out, updates
            return out

        return params_dict, apply_fn

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes by abstract evaluation."""
        self.forward(*args)  # eager pass performs deferred init

    def export(self, path, epoch=0, remove_amp_cast=True, example_inputs=None):
        """Serialize graph + params (reference block.py:1248 export).

        TPU re-design of the symbol.json deployment format: the traced
        forward is serialized as a portable StableHLO program
        (``jax.export``) in ``path-symbol.stablehlo`` with a JSON
        manifest in ``path-symbol.json``, plus ``path-%04d.params``.
        This is the deploy artifact the reference's C predict API loaded
        (SURVEY.md §2.1 "C API": predict maps to serialized StableHLO).
        """
        import json as _json
        from jax import export as jax_export
        from .. import ndarray as nd

        if example_inputs is None:
            raise ValueError(
                "export needs example_inputs=(x, ...) to trace the graph")
        params = self.collect_params()
        named = list(params.items())
        pvals = [p.data().data for _, p in named]
        ivals = [x.data if isinstance(x, NDArray) else x
                 for x in example_inputs]

        def pure(param_vals, input_vals):
            mapping = {p: NDArray(v)
                       for (_, p), v in zip(named, param_vals)}
            with _TraceParams(mapping), autograd._scope(None, False), \
                    _CollectStateUpdates():
                outs = self.forward(*[NDArray(v) for v in input_vals])
            if isinstance(outs, (list, tuple)):
                return tuple(o.data for o in outs)
            return outs.data

        exported = jax_export.export(jax.jit(pure))(pvals, ivals)  # mxlint: disable=MX-DONATE001(export-time trace over the block's live parameter values — serving-side donation is deploy.export_model's donate_argnums contract)
        with open(f"{path}-symbol.stablehlo", "wb") as f:
            f.write(exported.serialize())
        manifest = {
            "format": "stablehlo",
            "inputs": [{"shape": list(v.shape), "dtype": str(v.dtype)}
                       for v in ivals],
            "params": [name for name, _ in named],
        }
        with open(f"{path}-symbol.json", "w") as f:
            _json.dump(manifest, f, indent=2)
        arrays = {f"arg:{k}": p.data() for k, p in params.items()}
        nd.save(f"{path}-{epoch:04d}.params", arrays)
        return f"{path}-symbol.json", f"{path}-{epoch:04d}.params"


class SymbolBlock(HybridBlock):
    """Run a Symbol graph as a Block (reference block.py:1410).

    Construct with ``SymbolBlock(outputs, inputs)`` or
    ``SymbolBlock.imports(symbol_file, input_names, param_file)``.
    """

    def __init__(self, outputs, inputs, params=None):
        super().__init__()
        self._symbol_outputs = outputs
        self._symbol_inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        input_names = {s.name for s in self._symbol_inputs}
        out0 = outputs[0] if isinstance(outputs, list) else outputs
        arg_names = out0.list_arguments()
        aux_names = out0.list_auxiliary_states()
        for name in arg_names + aux_names:
            if name not in input_names:
                p = Parameter(name, allow_deferred_init=True,
                              grad_req="null" if name in aux_names
                              else "write")
                if params and name in params:
                    data = params[name]
                    p.shape = data.shape
                    p.initialize(ctx=current_context())
                    p.set_data(data)
                self._reg_params[name] = p

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        import json as _json
        from .. import symbol as sym_mod
        from .. import ndarray as nd
        with open(symbol_file) as f:
            manifest = _json.load(f)
        if manifest.get("format") == "stablehlo":
            # HybridBlock.export deploy artifact: portable StableHLO
            # program + params (the predict-API path, SURVEY.md §2.1)
            return _StableHLOBlock(symbol_file, manifest, param_file)
        sym = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        params = None
        if param_file:
            loaded = nd.load(param_file)
            params = {k.split(":", 1)[-1]: v for k, v in loaded.items()}
        return SymbolBlock(sym, inputs, params=params)

    def forward(self, *args):
        bindings = {s.name: a for s, a in zip(self._symbol_inputs, args)}
        for name, p in self._reg_params.items():
            bindings[name] = p.data()
        return self._symbol_outputs.eval_with(bindings)


class _StableHLOBlock(HybridBlock):
    """Deserialized ``HybridBlock.export`` artifact, runnable as a Block.

    The TPU analog of loading prefix-symbol.json into the reference's
    C predict API (c_predict_api.cc): the graph arrives as a compiled
    StableHLO program, so inference needs no Python model definition.
    """

    def __init__(self, symbol_file, manifest, param_file):
        super().__init__()
        from jax import export as jax_export
        path = symbol_file[:-len("-symbol.json")] \
            if symbol_file.endswith("-symbol.json") else symbol_file
        with open(f"{path}-symbol.stablehlo", "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        self._param_names = manifest["params"]
        params = {}
        if param_file:
            from .. import ndarray as nd
            loaded = nd.load(param_file)
            params = {k.split(":", 1)[-1]: v for k, v in loaded.items()}
        for name in self._param_names:
            p = Parameter(name, allow_deferred_init=True)
            if name in params:
                data = params[name]
                p.shape = data.shape
                p.initialize(ctx=current_context())
                p.set_data(data)
            self._reg_params[name] = p

    def forward(self, *args):
        pvals = [self._reg_params[n].data().data for n in self._param_names]
        ivals = [x.data if isinstance(x, NDArray) else x for x in args]
        out = self._exported.call(pvals, ivals)
        if isinstance(out, (list, tuple)):
            outs = [NDArray(o) for o in out]
            return outs[0] if len(outs) == 1 else outs
        return NDArray(out)
