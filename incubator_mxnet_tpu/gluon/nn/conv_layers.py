"""Convolution and pooling layers (reference gluon/nn/conv_layers.py)."""
from __future__ import annotations

from ... import initializer as init_mod
from ...ops.registry import invoke
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, in_channels, activation, use_bias,
                 weight_initializer, bias_initializer, ndim,
                 transpose=False, output_padding=0, layout=None, **kwargs):
        super().__init__(**kwargs)
        self._layout = layout
        self._channel_minor = bool(layout) and layout.endswith("C")
        if self._channel_minor and transpose:
            raise ValueError("channel-minor layout is not supported for "
                             "transposed convolution (reference limits the "
                             "layout knob to Convolution too)")
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = _tuple(kernel_size, ndim)
        self._strides = _tuple(strides, ndim)
        self._padding = _tuple(padding, ndim)
        self._dilation = _tuple(dilation, ndim)
        self._groups = groups
        self._activation = activation
        self._use_bias = use_bias
        self._ndim = ndim
        self._transpose = transpose
        self._output_padding = _tuple(output_padding, ndim)
        if transpose:
            wshape = (in_channels, channels // groups) + self._kernel
        elif self._channel_minor:  # O, *K, I (reference NHWC kernel layout)
            wshape = (channels,) + self._kernel \
                + ((in_channels // groups) if in_channels else 0,)
        else:
            wshape = (channels, (in_channels // groups) if in_channels else 0) \
                + self._kernel
        self.weight = Parameter("weight", shape=wshape,
                                init=weight_initializer or init_mod.Xavier(),
                                allow_deferred_init=True)
        if use_bias:
            self.bias = Parameter("bias", shape=(channels,),
                                  init=bias_initializer or init_mod.Zero(),
                                  allow_deferred_init=True)
        else:
            self.bias = None

    def _ensure_init(self, x):
        c_in = x.shape[-1] if self._channel_minor else x.shape[1]
        if self.weight._data is None:
            if self._transpose:
                self.weight.shape = (c_in, self._channels // self._groups) \
                    + self._kernel
            elif self._channel_minor:
                self.weight.shape = (self._channels,) + self._kernel \
                    + (c_in // self._groups,)
            else:
                self.weight.shape = (self._channels, c_in // self._groups) \
                    + self._kernel
            self.weight._finish_deferred_init()
        if self._use_bias and self.bias._data is None:
            self.bias._finish_deferred_init()

    def forward(self, x):
        self._ensure_init(x)
        args = [x, self.weight.data()]
        if self._use_bias:
            args.append(self.bias.data())
        if self._transpose:
            out = invoke("Deconvolution", *args, kernel=self._kernel,
                         stride=self._strides, pad=self._padding,
                         dilate=self._dilation, adj=self._output_padding,
                         num_filter=self._channels, num_group=self._groups,
                         no_bias=not self._use_bias)
        else:
            out = invoke("Convolution", *args, kernel=self._kernel,
                         stride=self._strides, pad=self._padding,
                         dilate=self._dilation, num_filter=self._channels,
                         num_group=self._groups, no_bias=not self._use_bias,
                         layout=self._layout)
        if self._activation:
            out = invoke("Activation", out, act_type=self._activation)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 1,
                         layout=layout, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 2,
                         layout=layout, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 3,
                         layout=layout, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 1,
                         transpose=True, output_padding=output_padding,
                         **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 2,
                         transpose=True, output_padding=output_padding,
                         **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 3,
                         transpose=True, output_padding=output_padding,
                         **kwargs)


class _Pool(HybridBlock):
    def __init__(self, pool_size, strides, padding, global_pool, pool_type,
                 ndim, count_include_pad=True, layout=None, **kwargs):
        super().__init__(**kwargs)
        self._layout = layout
        self._kernel = _tuple(pool_size, ndim)
        self._strides = _tuple(strides if strides is not None else pool_size, ndim)
        self._padding = _tuple(padding, ndim)
        self._global = global_pool
        self._pool_type = pool_type
        self._count_include_pad = count_include_pad

    def forward(self, x):
        return invoke("Pooling", x, kernel=self._kernel,
                      pool_type=self._pool_type, global_pool=self._global,
                      stride=self._strides, pad=self._padding,
                      count_include_pad=self._count_include_pad,
                      layout=self._layout)


def _make_pool(name, pool_type, ndim, global_pool):
    if global_pool:
        class P(_Pool):
            def __init__(self, layout=None, **kwargs):
                super().__init__(1, 1, 0, True, pool_type, ndim,
                                 layout=layout, **kwargs)
    else:
        class P(_Pool):
            def __init__(self, pool_size=2, strides=None, padding=0,
                         layout=None, ceil_mode=False, count_include_pad=True,
                         **kwargs):
                super().__init__(pool_size, strides, padding, False, pool_type,
                                 ndim, count_include_pad, layout=layout,
                                 **kwargs)
    P.__name__ = P.__qualname__ = name
    return P


MaxPool1D = _make_pool("MaxPool1D", "max", 1, False)
MaxPool2D = _make_pool("MaxPool2D", "max", 2, False)
MaxPool3D = _make_pool("MaxPool3D", "max", 3, False)
AvgPool1D = _make_pool("AvgPool1D", "avg", 1, False)
AvgPool2D = _make_pool("AvgPool2D", "avg", 2, False)
AvgPool3D = _make_pool("AvgPool3D", "avg", 3, False)
GlobalMaxPool1D = _make_pool("GlobalMaxPool1D", "max", 1, True)
GlobalMaxPool2D = _make_pool("GlobalMaxPool2D", "max", 2, True)
GlobalMaxPool3D = _make_pool("GlobalMaxPool3D", "max", 3, True)
GlobalAvgPool1D = _make_pool("GlobalAvgPool1D", "avg", 1, True)
GlobalAvgPool2D = _make_pool("GlobalAvgPool2D", "avg", 2, True)
GlobalAvgPool3D = _make_pool("GlobalAvgPool3D", "avg", 3, True)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        p = padding if isinstance(padding, (list, tuple)) else (padding,) * 4
        self._padding = ((0, 0), (0, 0), (p[0], p[1]),
                         (p[2], p[3])) if len(p) == 4 else p

    def forward(self, x):
        return invoke("pad", x, pad_width=self._padding, mode="reflect")
