"""Core layers (reference python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

from ... import initializer as init_mod
from ... import random as _random
from ...ndarray import NDArray
from ...ops.registry import invoke
from ..block import Block, HybridBlock, register_state_update
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Flatten",
           "Lambda", "HybridLambda", "Embedding", "Activation", "LeakyReLU",
           "PReLU", "ELU", "SELU", "GELU", "Swish", "SiLU", "BatchNorm",
           "LayerNorm", "GroupNorm", "InstanceNorm", "Identity"]


class Sequential(Block):
    """Stack of blocks applied in order (reference basic_layers.py:46)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        children = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*children[key])
            return net
        return children[key]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(Sequential, HybridBlock):
    """Hybridizable Sequential (reference basic_layers.py:106)."""

    def __init__(self, prefix=None, params=None):
        HybridBlock.__init__(self, prefix, params)

    forward = Sequential.forward


class Dense(HybridBlock):
    """Fully connected layer (reference basic_layers.py Dense)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._activation = activation
        self._use_bias = use_bias
        self.weight = Parameter("weight", shape=(units, in_units), dtype=dtype,
                                init=weight_initializer,
                                allow_deferred_init=True)
        if use_bias:
            self.bias = Parameter("bias", shape=(units,), dtype=dtype,
                                  init=bias_initializer or init_mod.Zero(),
                                  allow_deferred_init=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.weight.shape[1] == 0:
            in_units = x.size // x.shape[0] if self._flatten else x.shape[-1]
            self.weight.shape = (self._units, in_units)
            self.weight._finish_deferred_init()
        if self._use_bias and self.bias._data is None:
            self.bias._finish_deferred_init()
        args = [x, self.weight.data()]
        if self._use_bias:
            args.append(self.bias.data())
        out = invoke("FullyConnected", *args, num_hidden=self._units,
                     no_bias=not self._use_bias, flatten=self._flatten)
        if self._activation:
            out = invoke("Activation", out, act_type=self._activation)
        return out


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self.weight = Parameter("weight", shape=(input_dim, output_dim),
                                dtype=dtype, init=weight_initializer)

    def forward(self, x):
        return invoke("Embedding", x, self.weight.data(),
                      input_dim=self._input_dim, output_dim=self._output_dim)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        from ... import autograd
        if not autograd.is_training() or self._rate <= 0:
            return x
        key = NDArray(_random.next_key(), ctx=x.ctx)
        return invoke("Dropout", x, key, p=self._rate, mode="training",
                      axes=self._axes)


class Flatten(HybridBlock):
    def forward(self, x):
        return x.reshape((x.shape[0], -1))


class Identity(HybridBlock):
    def forward(self, x):
        return x


class Lambda(Block):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        self._func = function

    def forward(self, *args):
        if isinstance(self._func, str):
            from ... import ndarray as F
            return getattr(F, self._func)(*args)
        return self._func(*args)


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act_type = activation

    def forward(self, x):
        return invoke("Activation", x, act_type=self._act_type)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return invoke("leaky_relu", x, slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=init_mod.Constant(0.25),
                 in_channels=1, **kwargs):
        super().__init__(**kwargs)
        self.alpha = Parameter("alpha", shape=(in_channels,),
                               init=alpha_initializer)

    def forward(self, x):
        return invoke("prelu", x, self.alpha.data())


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return invoke("elu", x, alpha=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return invoke("selu", x)


class GELU(HybridBlock):
    def __init__(self, approximation="erf", **kwargs):
        super().__init__(**kwargs)
        self._approx = approximation

    def forward(self, x):
        return invoke("gelu" if self._approx == "erf" else "gelu_tanh", x)


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def forward(self, x):
        if self._beta == 1.0:
            return invoke("silu", x)
        return x * invoke("sigmoid", self._beta * x)


SiLU = Swish


class BatchNorm(HybridBlock):
    """Batch normalization with moving stats (reference basic_layers.py
    BatchNorm; op semantics src/operator/nn/batch_norm.cc).

    Moving mean/var are aux parameters (grad_req null); their update is
    routed through ``register_state_update`` so hybridized graphs stay
    pure (updates returned as extra outputs and applied post-step).
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=init_mod.One(),
                               allow_deferred_init=True,
                               differentiable=scale)
        self.beta = Parameter("beta", shape=shape, init=init_mod.Zero(),
                              allow_deferred_init=True,
                              differentiable=center)
        self.running_mean = Parameter("running_mean", shape=shape,
                                      grad_req="null",
                                      init=init_mod.Zero(),
                                      allow_deferred_init=True)
        self.running_var = Parameter("running_var", shape=shape,
                                     grad_req="null",
                                     init=init_mod.One(),
                                     allow_deferred_init=True)

    def _ensure_init(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            if p._data is None:
                p.shape = (c,)
                p._finish_deferred_init()

    def forward(self, x):
        from ... import autograd
        self._ensure_init(x)
        training = autograd.is_training() and not self._use_global_stats
        if training:
            out, new_mean, new_var = invoke(
                "BatchNorm", x, self.gamma.data(), self.beta.data(),
                self.running_mean.data(), self.running_var.data(),
                eps=self._epsilon, momentum=self._momentum, axis=self._axis,
                fix_gamma=not self._scale, training=True)
            register_state_update(self.running_mean, new_mean)
            register_state_update(self.running_var, new_var)
            return out
        return invoke("BatchNorm", x, self.gamma.data(), self.beta.data(),
                      self.running_mean.data(), self.running_var.data(),
                      eps=self._epsilon, momentum=self._momentum,
                      axis=self._axis, fix_gamma=not self._scale,
                      training=False)


class BatchNormReLU(BatchNorm):
    """Fused BatchNorm + ReLU (reference nn/activations.py
    BatchNormReLU over src/operator/nn/batch_norm_relu): under XLA the
    relu fuses into the BN epilogue automatically, so this is BatchNorm
    followed by relu in one compiled program."""

    def forward(self, x):
        out = super().forward(x)
        return invoke("relu", out)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=init_mod.One(),
                               allow_deferred_init=True, differentiable=scale)
        self.beta = Parameter("beta", shape=shape, init=init_mod.Zero(),
                              allow_deferred_init=True, differentiable=center)

    def forward(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if p._data is None:
                p.shape = (c,)
                p._finish_deferred_init()
        return invoke("LayerNorm", x, self.gamma.data(), self.beta.data(),
                      axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=init_mod.One(),
                               allow_deferred_init=True, differentiable=scale)
        self.beta = Parameter("beta", shape=shape, init=init_mod.Zero(),
                              allow_deferred_init=True, differentiable=center)

    def forward(self, x):
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if p._data is None:
                p.shape = (c,)
                p._finish_deferred_init()
        return invoke("GroupNorm", x, self.gamma.data(), self.beta.data(),
                      num_groups=self._num_groups, eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=init_mod.One(),
                               allow_deferred_init=True, differentiable=scale)
        self.beta = Parameter("beta", shape=shape, init=init_mod.Zero(),
                              allow_deferred_init=True, differentiable=center)

    def forward(self, x):
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if p._data is None:
                p.shape = (c,)
                p._finish_deferred_init()
        return invoke("InstanceNorm", x, self.gamma.data(), self.beta.data(),
                      eps=self._epsilon)
