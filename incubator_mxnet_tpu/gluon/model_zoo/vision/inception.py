"""Inception V3 (reference gluon/model_zoo/vision/inception.py)."""
from ... import nn
from ...block import HybridBlock
from ....ops.registry import invoke

__all__ = ["Inception3", "inception_v3"]


def _make_basic_conv(channels, **kwargs):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(channels, use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


class _Branching(HybridBlock):
    def __init__(self, branches, **kwargs):
        super().__init__(**kwargs)
        for i, b in enumerate(branches):
            self.register_child(b, f"branch{i}")

    def forward(self, x):
        outs = [child(x) for child in self._children.values()]
        return invoke("concat", *outs, dim=1)


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential()
    if use_pool == "avg":
        out.add(nn.AvgPool2D(3, 1, 1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(3, 2))
    for channels, kernel, stride, pad in conv_settings:
        kw = {"kernel_size": kernel}
        if stride is not None:
            kw["strides"] = stride
        if pad is not None:
            kw["padding"] = pad
        out.add(_make_basic_conv(channels, **kw))
    return out


def _make_A(pool_features):
    return _Branching([
        _make_branch(None, (64, 1, None, None)),
        _make_branch(None, (48, 1, None, None), (64, 5, None, 2)),
        _make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                     (96, 3, None, 1)),
        _make_branch("avg", (pool_features, 1, None, None)),
    ])


def _make_B():
    return _Branching([
        _make_branch(None, (384, 3, 2, None)),
        _make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                     (96, 3, 2, None)),
        _make_branch("max"),
    ])


def _make_C(channels_7x7):
    return _Branching([
        _make_branch(None, (192, 1, None, None)),
        _make_branch(None, (channels_7x7, 1, None, None),
                     (channels_7x7, (1, 7), None, (0, 3)),
                     (192, (7, 1), None, (3, 0))),
        _make_branch(None, (channels_7x7, 1, None, None),
                     (channels_7x7, (7, 1), None, (3, 0)),
                     (channels_7x7, (1, 7), None, (0, 3)),
                     (channels_7x7, (7, 1), None, (3, 0)),
                     (192, (1, 7), None, (0, 3))),
        _make_branch("avg", (192, 1, None, None)),
    ])


def _make_D():
    return _Branching([
        _make_branch(None, (192, 1, None, None), (320, 3, 2, None)),
        _make_branch(None, (192, 1, None, None), (192, (1, 7), None, (0, 3)),
                     (192, (7, 1), None, (3, 0)), (192, 3, 2, None)),
        _make_branch("max"),
    ])


class _BranchingE(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.branch1 = _make_branch(None, (320, 1, None, None))
        self.branch2_stem = _make_basic_conv(384, kernel_size=1)
        self.branch2_a = _make_basic_conv(384, kernel_size=(1, 3),
                                          padding=(0, 1))
        self.branch2_b = _make_basic_conv(384, kernel_size=(3, 1),
                                          padding=(1, 0))
        self.branch3_stem = nn.HybridSequential()
        self.branch3_stem.add(_make_basic_conv(448, kernel_size=1))
        self.branch3_stem.add(_make_basic_conv(384, kernel_size=3, padding=1))
        self.branch3_a = _make_basic_conv(384, kernel_size=(1, 3),
                                          padding=(0, 1))
        self.branch3_b = _make_basic_conv(384, kernel_size=(3, 1),
                                          padding=(1, 0))
        self.branch4 = _make_branch("avg", (192, 1, None, None))

    def forward(self, x):
        b1 = self.branch1(x)
        s2 = self.branch2_stem(x)
        b2 = invoke("concat", self.branch2_a(s2), self.branch2_b(s2), dim=1)
        s3 = self.branch3_stem(x)
        b3 = invoke("concat", self.branch3_a(s3), self.branch3_b(s3), dim=1)
        b4 = self.branch4(x)
        return invoke("concat", b1, b2, b3, b4, dim=1)


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(_make_basic_conv(32, kernel_size=3, strides=2))
        self.features.add(_make_basic_conv(32, kernel_size=3))
        self.features.add(_make_basic_conv(64, kernel_size=3, padding=1))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(_make_basic_conv(80, kernel_size=1))
        self.features.add(_make_basic_conv(192, kernel_size=3))
        self.features.add(nn.MaxPool2D(3, 2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_BranchingE())
        self.features.add(_BranchingE())
        self.features.add(nn.AvgPool2D(8))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def inception_v3(**kwargs):
    return Inception3(**kwargs)
