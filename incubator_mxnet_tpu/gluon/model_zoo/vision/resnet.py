"""ResNet V1/V2 (reference gluon/model_zoo/vision/resnet.py).

The flagship benchmark model (BASELINE config 2: ResNet-50).  Identical
architecture to the reference zoo: V1 = post-activation (He et al. 2015),
V2 = pre-activation (He et al. 2016), thumbnail variant for CIFAR.
"""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock

__all__ = ["ResNetV1", "ResNetV2", "get_resnet",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
           "resnet101_v2", "resnet152_v2"]


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None
        self.relu = nn.Activation("relu")

    def forward(self, x):
        residual = x
        x_out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return self.relu(x_out + residual)


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride,
                                use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1,
                                use_bias=False))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None
        self.relu = nn.Activation("relu")

    def forward(self, x):
        residual = x
        x_out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return self.relu(x_out + residual)


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        self.relu = nn.Activation("relu")
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.relu(self.bn2(x))
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False)
        self.relu = nn.Activation("relu")
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.relu(self.bn2(x))
        x = self.conv2(x)
        x = self.relu(self.bn3(x))
        x = self.conv3(x)
        return x + residual


class S2DStem(HybridBlock):
    """Space-to-depth ResNet stem (the MLPerf TPU trick): s2d(2) then a
    4x4/s1 conv over 12 channels replaces the 7x7/s2 conv over 3.

    Same function class and FLOPs as the classic stem (the 7x7 kernel
    embeds exactly into the s2d domain — equivalence verified to 1.2e-6
    by scripts/perf_probe.py stem), but the contraction reads 12*16=192
    taps instead of 3*49=147 over a C=3 input that packs the 128-lane
    MXU at 2.3% density — the top conv-lowering lever identified in
    docs/performance.md.  Select with resnet50_v1(stem="s2d") or
    BENCH_STEM=s2d.
    """

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        self.conv = nn.Conv2D(channels, 4, 1, 2, use_bias=False,
                              in_channels=12)

    def forward(self, x):
        from .... import nd
        if x.shape[-1] % 2 or x.shape[-2] % 2:
            raise ValueError(
                f"stem='s2d' needs even spatial dims (got "
                f"{x.shape[-2:]}); use the default conv7 stem for odd "
                "crop sizes")
        y = nd.space_to_depth(x, block_size=2)
        y = self.conv(y)
        # pad 2 yields 113x113 for the canonical (2,1) asymmetric pad;
        # drop the last row/col (receptive-field shift the trained
        # weights absorb)
        return y[:, :, :-1, :-1]


def _add_stem(features, channels, thumbnail, stem):
    if thumbnail:
        features.add(_conv3x3(channels, 1, 0))
        return
    if stem == "s2d":
        features.add(S2DStem(channels))
    else:
        features.add(nn.Conv2D(channels, 7, 2, 3, use_bias=False))
    features.add(nn.BatchNorm())
    features.add(nn.Activation("relu"))
    features.add(nn.MaxPool2D(3, 2, 1))


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 stem="conv7", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self.features = nn.HybridSequential()
        _add_stem(self.features, channels[0], thumbnail, stem)
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i]))
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.Dense(classes)

    def _make_layer(self, block, layers, channels, stride, in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 stem="conv7", **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(nn.BatchNorm(scale=False, center=False))
        _add_stem(self.features, channels[0], thumbnail, stem)
        in_channels = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=in_channels))
            in_channels = channels[i + 1]
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    _make_layer = ResNetV1._make_layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None, **kwargs):
    block_type, layers, channels = resnet_spec[num_layers]
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        raise RuntimeError("no pretrained weights in zero-egress environment")
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
